"""Fleet cache tier + model-based fleet planner tests (ISSUE 20).

Coverage map:

- **Hash ring**: golden-pinned placement vectors (the on-the-wire
  placement contract — a hash change is a fleet-wide cache flush and
  must fail a test, not ship silently), the ≤ 1/N + ε churn bound on
  join/leave, and the no-bidirectional-moves property.
- **Fleet model**: fit/predict/marginal goldens, the what-if replay
  gate, and the ModelPlanner's admit/drain/probe-revert behaviors
  (pure, signal-driven — the `plan_fair_shares` discipline).
- **FleetCacheTier**: remote warm serves are byte-identical to local
  ones, adopted entries stay frame-seekable, write-through placement,
  drain handoff re-homing, and breaker-open degradation to local fills
  — wired over an in-process fake wire that mirrors the worker's
  cache_fetch/cache_put handlers (JSON round-trip included) so the
  protocol shape is exercised without sockets.
- **Dispatcher**: cache-peer list lifecycle (registration-journaled,
  draining excluded), and byte-identical WAL replay of `cache_handoff`
  and `fleet_plan` records across a restart + through a snapshot.
- **CLI**: the `status --watch` CACHE column and the CACHEHIT%
  None-baseline fix, over synthetic samples (`render_fleet_status` is
  pure).
- **Loopback integration**: remote-warm vs local-warm vs cold digest
  equality on both transports, and the drain handoff's zero-cold-refill
  contract, through `service_loopback_scenario`.
"""

import json
import time

import numpy as np
import pytest

from petastorm_tpu.cache_impl import BatchCache
from petastorm_tpu.cache_impl.fleet_tier import FleetCacheTier
from petastorm_tpu.cache_impl.hash_ring import HashRing, placement
from petastorm_tpu.service.fleet_model import (
    MIN_MARGINAL_FRACTION,
    ModelPlanner,
    ThroughputModel,
    fit_throughput_model,
    whatif_replay,
)

pytestmark = pytest.mark.service


# ---------------------------------------------------------------------------
# hash ring: goldens + churn properties
# ---------------------------------------------------------------------------

#: Pinned placement vector. These values ARE the placement contract: every
#: fleet member must map a key to the same owner, and a restarted fleet
#: must map keys where the previous one did (anything else is a silent
#: fleet-wide cache flush). A deliberate hash/vnode change must update
#: this golden IN THE SAME COMMIT and call out the flush in its message.
GOLDEN_PLACEMENT = {
    "k00": "w1", "k01": "w0", "k02": "w0", "k03": "w1",
    "k04": "w3", "k05": "w3", "k06": "w1", "k07": "w3",
    "k08": "w2", "k09": "w3", "k10": "w0", "k11": "w1",
    "fp:deadbeef": "w1", "fp:cafef00d": "w0",
    "piece:7:mem": "w2", "piece:8:mem": "w1",
}


def test_hash_ring_golden_placement_pinned():
    got = placement(list(GOLDEN_PLACEMENT), ["w0", "w1", "w2", "w3"])
    assert got == GOLDEN_PLACEMENT


def test_hash_ring_owner_independent_of_peer_insertion_order():
    keys = [f"key-{i}" for i in range(64)]
    forward = placement(keys, ["a", "b", "c"])
    backward = placement(keys, ["c", "a", "b"])
    assert forward == backward


def test_hash_ring_join_churn_bound_and_one_directional():
    keys = [f"key-{i}" for i in range(800)]
    peers = [f"w{i}" for i in range(4)]
    before = placement(keys, peers)
    after = placement(keys, peers + ["w4"])
    moved = {k for k in keys if before[k] != after[k]}
    # ≤ 1/N + ε of the keyspace moves on a join (vnode placement noise
    # allows a modest epsilon over the ideal 1/5 = 160 keys here).
    assert len(moved) <= len(keys) / 5 * 1.5
    # ... and every move lands ON the joiner: a key moving between two
    # surviving peers would be a gratuitous invalidation.
    assert all(after[k] == "w4" for k in moved)


def test_hash_ring_leave_churn_bound_and_one_directional():
    keys = [f"key-{i}" for i in range(800)]
    peers = [f"w{i}" for i in range(5)]
    before = placement(keys, peers)
    after = placement(keys, peers[:-1])
    moved = {k for k in keys if before[k] != after[k]}
    assert len(moved) <= len(keys) / 5 * 1.5
    # Only the leaver's keys move; everything else stays put.
    assert all(before[k] == "w4" for k in moved)
    assert moved == {k for k in keys if before[k] == "w4"}


def test_hash_ring_spread_is_roughly_uniform():
    keys = [f"key-{i}" for i in range(1000)]
    owners = placement(keys, ["a", "b", "c", "d"]).values()
    counts = {p: sum(1 for o in owners if o == p) for p in "abcd"}
    # 64 vnodes/peer keeps every peer within ~2x of the fair share.
    assert all(125 <= n <= 500 for n in counts.values()), counts


def test_hash_ring_owners_replicas_and_empty_ring():
    ring = HashRing(["a", "b", "c"])
    owners = ring.owners("some-key", n=2)
    assert len(owners) == 2 and len(set(owners)) == 2
    assert owners[0] == ring.owner("some-key")
    assert ring.owners("some-key", n=5) == ring.owners("some-key", n=3)
    empty = HashRing()
    assert empty.owner("k") is None
    assert empty.owners("k") == []
    assert len(empty) == 0 and "a" not in empty


def test_hash_ring_replace_updates_membership():
    ring = HashRing(["a", "b"])
    assert "a" in ring and len(ring) == 2
    ring.replace({"b": None, "c": None})
    assert "a" not in ring and "c" in ring
    assert ring.peers == ("b", "c")


# ---------------------------------------------------------------------------
# throughput model: fit / predict / what-if goldens
# ---------------------------------------------------------------------------

def test_fit_model_linear_regime():
    model = fit_throughput_model([(1, 100.0), (2, 200.0), (1, 100.0)])
    assert model.per_worker_rows_s == pytest.approx(100.0)
    assert model.ceiling_rows_s is None
    assert model.predict(3) == pytest.approx(300.0)
    assert model.marginal(3) == pytest.approx(100.0)


def test_fit_model_detects_ceiling_and_caps_marginal():
    model = fit_throughput_model([(2, 200.0), (4, 210.0)])
    assert model.per_worker_rows_s == pytest.approx(100.0)
    assert model.ceiling_rows_s == pytest.approx(210.0)
    assert model.predict(8) == pytest.approx(210.0)   # capped
    assert model.marginal(3) == pytest.approx(0.0)    # saturated
    assert model.marginal(1) == pytest.approx(100.0)  # linear regime


def test_fit_model_profile_prior_when_no_samples():
    profiles = [{"profile": {"decode": {"mean_us": 2000.0},
                             "serialize": {"mean_us": 500.0}}}]
    model = fit_throughput_model([], profiles)
    # 1e6 / worst stage mean_us = 1e6 / 2000 = 500 rows/s prior.
    assert model.per_worker_rows_s == pytest.approx(500.0)
    assert fit_throughput_model([], []) is None
    assert fit_throughput_model([(0, 0.0)], []) is None


def test_whatif_replay_gate():
    model = ThroughputModel(100.0)
    error, ok = whatif_replay(model, [(1, 100.0), (2, 200.0)])
    assert error == pytest.approx(0.0) and ok
    error, ok = whatif_replay(model, [(1, 100.0), (2, 100.0), (3, 100.0)])
    assert not ok and error > 0.25
    assert whatif_replay(model, []) == (None, False)


def test_model_round_trips_to_dict():
    model = ThroughputModel(123.0, 456.0)
    assert model.to_dict() == {"per_worker_rows_s": 123.0,
                               "ceiling_rows_s": 456.0}


# ---------------------------------------------------------------------------
# model planner: admit / drain / probe-revert / gates (pure)
# ---------------------------------------------------------------------------

def _signals(serving=(), standby=(), draining=(), rates=None, backlog=None,
             stage_profiles=()):
    return {"serving": list(serving), "standby": list(standby),
            "draining": list(draining), "rates": dict(rates or {}),
            "backlog": dict(backlog or {}),
            "stage_profiles": list(stage_profiles)}


def test_model_planner_admits_on_predicted_marginal_gain():
    planner = ModelPlanner()
    decisions = planner.plan(_signals(
        serving=["w0", "w1"], standby=["w9", "w2"],
        rates={"w0": 100.0, "w1": 100.0}))
    assert len(decisions) == 1
    decision = decisions[0]
    assert decision["action"] == "admit"
    assert decision["worker_id"] == "w2"        # deterministic: sorted
    assert decision["probe"] is True
    assert decision["predicted_rows_s"] == pytest.approx(300.0)
    assert decision["model"]["per_worker_rows_s"] == pytest.approx(100.0)
    assert decision["whatif_error"] == pytest.approx(0.0)


def test_model_planner_drains_when_marginal_below_threshold():
    planner = ModelPlanner()
    assert planner.plan(_signals(
        serving=["a", "b"], rates={"a": 100.0, "b": 100.0})) == []
    decisions = planner.plan(_signals(
        serving=["a", "b", "c", "d"],
        rates={"a": 50.0, "b": 50.0, "c": 50.0, "d": 60.0}))
    assert len(decisions) == 1
    decision = decisions[0]
    assert decision["action"] == "drain"
    # Slowest serving worker, ties broken by id.
    assert decision["worker_id"] == "a"
    assert decision["probe"] is True
    threshold = (MIN_MARGINAL_FRACTION
                 * decision["model"]["per_worker_rows_s"])
    assert decision["model"]["ceiling_rows_s"] is not None
    assert threshold > 0


def test_model_planner_whatif_gate_blocks_decisions():
    planner = ModelPlanner()
    # Wildly inconsistent measurements at one fleet size: the fitted
    # model cannot replay history within tolerance, so the planner
    # holds even with a standby available.
    planner.observe(2, 200.0)
    planner.observe(2, 50.0)
    planner.observe(2, 500.0)
    decisions = planner.plan(_signals(
        serving=["a", "b"], standby=["c"],
        rates={"a": 125.0, "b": 125.0}))
    assert decisions == []
    assert planner.last_whatif_error > 0.25


def test_model_planner_probe_reverts_underdelivering_admit():
    planner = ModelPlanner(probe_windows=1)
    first = planner.plan(_signals(
        serving=["a", "b"], standby=["c"],
        rates={"a": 100.0, "b": 100.0}))
    assert first and first[0]["action"] == "admit"
    # The admit predicted 300 rows/s at n=3; the fleet measured 210
    # (30% miss > the 25% tolerance) — the probe reverts and the model
    # re-anchors its ceiling at what was actually measured.
    revert = planner.plan(_signals(
        serving=["a", "b", "c"],
        rates={"a": 70.0, "b": 70.0, "c": 70.0}))
    assert len(revert) == 1
    assert revert[0]["action"] == "drain"
    assert revert[0]["worker_id"] == "c"
    assert "probe revert" in revert[0]["reason"]
    assert (3, 210.0) in planner.samples


def test_model_planner_probe_kept_when_prediction_held():
    planner = ModelPlanner(probe_windows=1)
    first = planner.plan(_signals(
        serving=["a", "b"], standby=["c"],
        rates={"a": 100.0, "b": 100.0}))
    assert first and first[0]["action"] == "admit"
    # Measured ≈ predicted: no revert, and cooldown still suppresses an
    # immediate follow-up decision.
    assert planner.plan(_signals(
        serving=["a", "b", "c"],
        rates={"a": 98.0, "b": 99.0, "c": 100.0})) == []


def test_model_planner_probe_dropped_when_fleet_moved_under_it():
    planner = ModelPlanner(probe_windows=1)
    first = planner.plan(_signals(
        serving=["a", "b"], standby=["c"],
        rates={"a": 100.0, "b": 100.0}))
    assert first and first[0]["action"] == "admit"
    # An operator drained a worker before the probe matured: n no longer
    # matches the probe's target, so the probe is unjudgeable — dropped
    # without a revert (reverting would punish the wrong cause).
    assert planner.plan(_signals(
        serving=["a", "b"], rates={"a": 10.0, "b": 10.0})) == []


def test_model_planner_retires_drained_worker_like_streak_planner():
    planner = ModelPlanner()
    decisions = planner.plan(_signals(
        serving=["a"], draining=["d"], rates={"a": 100.0},
        backlog={"d": 0}))
    assert {"action": "retire", "worker_id": "d",
            "reason": "drain complete"} in decisions


def test_model_planner_bare_construction_and_config_parity():
    # The controller reads planner.config.interval_s for its tick period
    # — both planner flavors must expose it.
    assert ModelPlanner().config.interval_s == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# fleet cache tier over a fake wire (protocol-shaped, no sockets)
# ---------------------------------------------------------------------------

def _make_batch(seed, kb=4):
    rng = np.random.RandomState(seed)
    return {"x": rng.rand(kb * 128).astype(np.float64),
            "i": np.arange(6, dtype=np.int64)}


def _wire(tiers):
    """Fake peer transport mirroring the worker's cache_fetch/cache_put
    handlers, with the JSON header round-trip real framing performs (so
    tuple→list and int-key coercions are exercised)."""
    def peer_request(self, peer_id, header, payload=None):
        peer = tiers[peer_id]
        header = json.loads(json.dumps(header))
        if header["type"] == "cache_fetch":
            reply, reply_payload = peer.serve_fetch(str(header["key"]))
            return json.loads(json.dumps(reply)), reply_payload
        if header["type"] == "cache_put":
            entry = peer.adopt(
                str(header["key"]), header.get("meta") or [],
                (payload or {}).get("buf", b""),
                origin=str(header.get("origin", "placement")))
            return {"type": "ok", "rows": entry.rows}, None
        raise AssertionError(f"unexpected peer rpc {header['type']!r}")
    return peer_request


@pytest.fixture()
def tier_pair(monkeypatch):
    tiers = {}
    for wid in ("wa", "wb"):
        tiers[wid] = FleetCacheTier(
            BatchCache(mem_budget_bytes=32 << 20), wid)
    monkeypatch.setattr(FleetCacheTier, "_peer_request", _wire(tiers))
    peers = [[wid, "127.0.0.1", 1] for wid in tiers]
    for tier in tiers.values():
        tier.update_peers(peers)
    yield tiers
    for tier in tiers.values():
        tier.cleanup()


def _keys_owned_by(tiers, owner, count=4):
    ring = next(iter(tiers.values()))._ring
    keys, i = [], 0
    while len(keys) < count:
        key = f"entry-{i}"
        if ring.owner(key) == owner:
            keys.append(key)
        i += 1
    return keys


def test_remote_warm_serve_byte_identical_and_promoted(tier_pair):
    wa, wb = tier_pair["wa"], tier_pair["wb"]
    key = _keys_owned_by(tier_pair, "wa", 1)[0]
    batches = [_make_batch(0), _make_batch(1)]
    wa.local.put_batches(key, batches)
    want = bytes(wa.local.peek(key).buf)

    entry, tier = wb.get_tiered(key)
    assert tier == "remote"
    assert bytes(entry.buf) == want          # the cached bytes ARE the
    #                                          wire bytes — zero decode,
    #                                          zero re-serialization
    assert wb.remote_hits == 1
    assert wa.local.stats()["hits_mem"] == 0  # peek never skews the
    #                                           owner's own hit stats
    # Promotion: the remote hit now lives in wb's memory tier, so the
    # next lookup is local.
    _, tier2 = wb.get_tiered(key)
    assert tier2 == "mem"


def test_adopted_entry_stays_frame_seekable(tier_pair):
    """Watermark seeks slice an entry at per-batch frame offsets; an
    adopted (peer-shipped) entry must reconstruct every batch at every
    index exactly like the original — the property the worker's
    watermark-resume path relies on when re-serving from a remote-warm
    entry."""
    wa, wb = tier_pair["wa"], tier_pair["wb"]
    key = _keys_owned_by(tier_pair, "wa", 1)[0]
    batches = [_make_batch(i) for i in range(4)]
    wa.local.put_batches(key, batches)

    entry, tier = wb.get_tiered(key)
    assert tier == "remote"
    original = wa.local.peek(key)
    assert entry.meta == original.meta
    assert entry.num_batches == original.num_batches == 4
    for index in range(4):                   # seek to every watermark
        got = entry.batch_at(index)
        want = original.batch_at(index)
        assert got.rows == want.rows
        assert [bytes(memoryview(f)) for f in got.frames] \
            == [bytes(memoryview(f)) for f in want.frames]


def test_write_through_placement_pushes_to_ring_owner(tier_pair):
    wa, wb = tier_pair["wa"], tier_pair["wb"]
    keys = _keys_owned_by(tier_pair, "wb", 3)
    for i, key in enumerate(keys):
        builder = wa.begin_fill(key)
        builder.add_batch(_make_batch(i))
        builder.commit()
    deadline = 100
    while wa.pushes_sent < len(keys) and deadline:
        deadline -= 1
        time.sleep(0.05)
    assert wa.pushes_sent == len(keys)
    for key in keys:                          # the owner can now serve
        assert wb.local.peek(key) is not None  # them warm
    # Keys this worker owns itself are NOT pushed anywhere.
    own = _keys_owned_by(tier_pair, "wa", 1)[0]
    builder = wa.begin_fill(own)
    builder.add_batch(_make_batch(9))
    builder.commit()
    assert wb.local.peek(own) is None


def test_handoff_rehomes_memory_tier_to_survivors(tier_pair):
    wa, wb = tier_pair["wa"], tier_pair["wb"]
    keys = [f"hand-{i}" for i in range(5)]
    for i, key in enumerate(keys):
        wa.local.put_batches(key, [_make_batch(i)])
    summary = wa.handoff()
    assert summary["entries"] == 5 and summary["errors"] == 0
    assert summary["torn"] is False
    assert summary["peers"] == {"wb": 5}      # the only survivor
    assert wa.handoff_entries_sent == 5
    assert wb.handoff_entries_received == 5
    for key in keys:                          # zero cold re-decode: the
        entry, tier = wb.get_tiered(key)      # survivor serves them all
        assert tier == "mem"                  # from memory
        assert bytes(entry.buf) == bytes(wa.local.peek(key).buf)


def test_handoff_with_no_survivors_is_a_noop(tier_pair):
    wa = tier_pair["wa"]
    wa.update_peers([["wa", "127.0.0.1", 1]])
    wa.local.put_batches("k", [_make_batch(0)])
    assert wa.handoff() == {"entries": 0, "bytes": 0, "peers": {},
                            "errors": 0, "torn": False}


def test_breaker_open_degrades_to_local_fill(monkeypatch):
    clock = [0.0]
    tier = FleetCacheTier(BatchCache(mem_budget_bytes=8 << 20), "wa",
                          clock=lambda: clock[0])
    try:
        tier.update_peers([["wa", "127.0.0.1", 1],
                           ["wb", "127.0.0.1", 2]])

        def refuse(self, peer_id, header, payload=None):
            raise ConnectionRefusedError("peer gone")
        monkeypatch.setattr(FleetCacheTier, "_peer_request", refuse)
        key = next(k for k in (f"k{i}" for i in range(64))
                   if tier._ring.owner(k) == "wb")
        # Five consecutive dial failures trip wb's breaker ...
        for _ in range(5):
            entry, got_tier = tier.get_tiered(key)
            assert entry is None and got_tier is None
        assert tier.remote_errors == 5
        assert tier.stats()["breakers_open"] == 1
        # ... after which lookups skip the dial entirely (fail fast) and
        # degrade straight to the local fill path.
        entry, got_tier = tier.get_tiered(key)
        assert entry is None and tier.breaker_skips == 1
        # The stream is not broken: a local fill serves the key warm.
        tier.put_batches(key, [_make_batch(3)])
        entry, got_tier = tier.get_tiered(key)
        assert got_tier == "mem"
        # Miss accounting: one fleet-wide miss per cold lookup, never
        # double-counted across the local+remote probes.
        assert tier.stats()["misses"] == 6
    finally:
        tier.cleanup()


def test_adopt_refuses_torn_payload(tier_pair):
    wa = tier_pair["wa"]
    wa.local.put_batches("k", [_make_batch(0)])
    entry = wa.local.peek("k")
    meta = [[rows, fmt, list(lens)] for rows, fmt, lens in entry.meta]
    wb = tier_pair["wb"]
    with pytest.raises(ValueError):
        wb.adopt("k", meta, bytes(entry.buf)[:-7])  # truncated transfer
    assert wb.local.peek("k") is None               # never published


def test_tier_stats_merge_and_delegation(tier_pair):
    wa = tier_pair["wa"]
    stats = wa.stats()
    assert stats["tier"] == "fleet"
    assert stats["peers"] == 2
    for key in ("remote_hits", "remote_misses", "pushes_sent",
                "handoff_entries_sent", "handoff_entries_received",
                "breaker_skips"):
        assert key in stats
    # Attribute delegation: the tier is a drop-in BatchCache.
    assert wa.contains("nope") is False
    assert wa.worker_id == "wa"


# ---------------------------------------------------------------------------
# dispatcher: peer list lifecycle + WAL replay byte-identity
# ---------------------------------------------------------------------------

from petastorm_tpu.reader_impl.framed_socket import FramedConnection  # noqa: E402
from petastorm_tpu.service.dispatcher import Dispatcher  # noqa: E402


def _rpc(address, header):
    with FramedConnection.connect(tuple(address), timeout=5.0) as conn:
        reply, _ = conn.request(header)
    return reply


def _register(dispatcher, worker_id, cache_fleet=True, port=9):
    reply = _rpc(dispatcher.address, {
        "type": "register_worker", "worker_id": worker_id,
        "host": "127.0.0.1", "port": port, "num_pieces": 4,
        "cache_fleet": cache_fleet})
    assert reply["type"] == "ok", reply
    return reply


def test_cache_peers_registration_seed_and_draining_exclusion():
    with Dispatcher(port=0).start() as disp:
        first = _register(disp, "wa", port=11)
        # Registration reply seeds the joiner's ring immediately.
        assert first["cache_peers"] == [["wa", "127.0.0.1", 11]]
        second = _register(disp, "wb", port=12)
        assert second["cache_peers"] == [["wa", "127.0.0.1", 11],
                                         ["wb", "127.0.0.1", 12]]
        # Non-fleet workers advertise nothing and never appear.
        plain = _register(disp, "wc", cache_fleet=False, port=13)
        assert "cache_peers" not in plain
        heartbeat = _rpc(disp.address, {"type": "worker_heartbeat",
                                        "worker_id": "wa"})
        assert heartbeat["worker_state"] == "serving"
        assert [p[0] for p in heartbeat["cache_peers"]] == ["wa", "wb"]
        # A draining peer leaves the published ring at once — the live
        # placement ring converges on the same survivor set the drain
        # handoff ships to.
        disp.drain_worker("wb")
        heartbeat = _rpc(disp.address, {"type": "worker_heartbeat",
                                        "worker_id": "wb"})
        assert heartbeat["worker_state"] == "draining"
        assert [p[0] for p in heartbeat["cache_peers"]] == ["wa"]


def test_cache_handoff_and_fleet_plan_replay_byte_identically(tmp_path):
    journal_dir = str(tmp_path / "journal")
    plan = {"action": "drain", "worker_id": "wb",
            "reason": "marginal 3.0 rows/s < 50.0",
            "model": {"per_worker_rows_s": 100.0,
                      "ceiling_rows_s": 210.0},
            "predicted_rows_s": 210.0, "whatif_error": 0.01,
            "probe": True}
    with Dispatcher(port=0, journal_dir=journal_dir).start() as disp:
        _register(disp, "wa", port=11)
        _register(disp, "wb", port=12)
        reply = _rpc(disp.address, {
            "type": "cache_handoff", "worker_id": "wb", "entries": 7,
            "bytes": 4096, "peers": {"wa": 7}, "errors": 1,
            "torn": True})
        assert reply["type"] == "ok"
        assert disp.record_fleet_plan(plan) is True
        status = _rpc(disp.address, {"type": "status"})
        want_handoffs = status["fleet"]["cache_handoffs"]
        want_plans = status["fleet"]["fleet_plans"]
        assert want_handoffs == [{"worker_id": "wb", "entries": 7,
                                  "bytes": 4096, "peers": {"wa": 7},
                                  "errors": 1, "torn": True}]
        assert want_plans[0]["action"] == "drain"
        assert want_plans[0]["model"]["ceiling_rows_s"] == 210.0
    with Dispatcher(port=0, journal_dir=journal_dir).start() as again:
        status = _rpc(again.address, {"type": "status"})
        assert status["fleet"]["cache_handoffs"] == want_handoffs
        assert status["fleet"]["fleet_plans"] == want_plans
        # cache_fleet survives replay: the peer list never guesses.
        assert [p[0] for p in status["fleet"]["cache_peers"]] \
            == ["wa", "wb"]
        # ... and through a compacted snapshot (the records ride the
        # snapshot, unlike stage_profiles — compaction between a handoff
        # and a restart must not lose the audit trail).
        with again._lock:
            again._journal.snapshot(again._state_dict_locked())
    with Dispatcher(port=0, journal_dir=journal_dir).start() as third:
        status = _rpc(third.address, {"type": "status"})
        assert status["fleet"]["cache_handoffs"] == want_handoffs
        assert status["fleet"]["fleet_plans"] == want_plans


# ---------------------------------------------------------------------------
# status --watch rendering (pure, synthetic samples)
# ---------------------------------------------------------------------------

def _sample(t, workers, status=None):
    base_status = {"mode": "static", "fencing_epoch": 0,
                   "workers": {wid: {"alive": True} for wid in workers},
                   "clients": {}, "fleet": {}}
    if status:
        base_status.update(status)
    return {"t": t, "status": base_status, "workers": workers}


def _metrics(rows=1000.0, hits=None, misses=None, tier=None, entries=0):
    metrics = {"rows_sent_total": rows, "batches_sent_total": rows / 10,
               "credit_wait_seconds_total": 0.0, "active_streams": 1.0}
    if hits is not None:
        metrics["cache_hits_total"] = hits
        metrics["cache_misses_total"] = misses
    if tier is not None:
        metrics["cache_tier"] = tier
        metrics["cache_entries_mem"] = entries
    return {"metrics": metrics}


def test_watch_renders_cache_tier_column():
    from petastorm_tpu.service.cli import render_fleet_status

    prev = _sample(0.0, {"w-fleet": _metrics(hits=0, misses=0,
                                             tier="fleet", entries=3),
                         "w-local": _metrics(hits=0, misses=0,
                                             tier="local", entries=1),
                         "w-off": _metrics()})
    cur = _sample(1.0, {"w-fleet": _metrics(rows=2000.0, hits=8, misses=2,
                                            tier="fleet", entries=12),
                        "w-local": _metrics(rows=2000.0, hits=1, misses=1,
                                            tier="local", entries=4),
                        "w-off": _metrics(rows=2000.0)})
    text = render_fleet_status(prev, cur)
    assert "CACHE" in text.splitlines()[1]
    fleet_row = next(l for l in text.splitlines()
                     if l.startswith("w-fleet"))
    assert "fleet:12" in fleet_row and "80.0" in fleet_row
    local_row = next(l for l in text.splitlines()
                     if l.startswith("w-local"))
    assert "local:4" in local_row
    off_row = next(l for l in text.splitlines() if l.startswith("w-off"))
    assert "--" in off_row.split()            # no cache armed → --


def test_watch_cachehit_requires_baseline_not_implicit_zero():
    """The None-baseline fix: a cache appearing mid-watch (prev sample
    predates it) must render `--`, not pass the worker's lifetime hit
    average off as one window's rate."""
    from petastorm_tpu.service.cli import render_fleet_status

    prev = _sample(0.0, {"w0": _metrics()})             # no cache keys
    cur = _sample(1.0, {"w0": _metrics(rows=2000.0, hits=900, misses=100,
                                       tier="local", entries=4)})
    row = next(l for l in render_fleet_status(prev, cur).splitlines()
               if l.startswith("w0"))
    cells = row.split()
    assert "90.0" not in cells                # the lifetime average
    assert cells[7] == "--"                   # CACHEHIT% column
    # Zero lookups in the window is also `--`, never a fake 0.0 or 100.
    prev = _sample(0.0, {"w0": _metrics(hits=5, misses=5)})
    cur = _sample(1.0, {"w0": _metrics(rows=2000.0, hits=5, misses=5)})
    row = next(l for l in render_fleet_status(prev, cur).splitlines()
               if l.startswith("w0"))
    assert row.split()[7] == "--"


def test_watch_renders_fleet_plan_and_handoff_lines():
    from petastorm_tpu.service.cli import render_fleet_status

    fleet = {"workers_by_state": {"serving": ["w0"], "standby": [],
                                  "draining": []},
             "fleet_plans": [{"action": "admit", "worker_id": "w1",
                              "predicted_rows_s": 300.0,
                              "whatif_error": 0.02}],
             "cache_handoffs": [{"worker_id": "w2", "entries": 7,
                                 "bytes": 4096, "peers": {"w0": 7},
                                 "errors": 0, "torn": True}]}
    prev = _sample(0.0, {"w0": _metrics()})
    cur = _sample(1.0, {"w0": _metrics(rows=2000.0)},
                  status={"fleet": fleet})
    text = render_fleet_status(prev, cur)
    assert ("fleet-plan: admit worker=w1 predicted_rows/s=300.0 "
            "whatif_err=2.0%") in text
    assert ("cache-handoff: w2 shipped 7 entries (4096 bytes) to "
            "1 peers, 0 errors [TORN]") in text


# ---------------------------------------------------------------------------
# loopback integration: digests + drain handoff
# ---------------------------------------------------------------------------

def _run_scenario(**kwargs):
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    base = dict(rows=1536, days=8, workers=2, batch_size=64,
                shuffle_seed=11, ordered=True, epochs=2)
    base.update(kwargs)
    return service_loopback_scenario(**base)


def test_remote_warm_serves_digest_equal_across_transports():
    """Cold, local-warm, and remote-warm serves must be byte-identical:
    the ordered stream digest is invariant to arming the fleet tier, on
    BOTH transports — the fleet tier moves time, never content.  A
    three-worker fleet with a mid-stream drain forces cross-worker piece
    reassignment, so epoch-2 lookups actually ride the remote-probe
    path."""
    cold = _run_scenario(cache="off", transport="tcp", workers=3)
    fleet_tcp = _run_scenario(cache="mem", fleet_cache=True,
                              fleet_cache_drain_after=12,
                              transport="tcp", workers=3)
    fleet_shm = _run_scenario(cache="mem", fleet_cache=True,
                              fleet_cache_drain_after=12,
                              transport="shm", workers=3)
    assert cold["stream_digest"] == fleet_tcp["stream_digest"]
    assert cold["stream_digest"] == fleet_shm["stream_digest"]
    for arm in (fleet_tcp, fleet_shm):
        fleet_stats = arm["cache"]["fleet"]
        # The warm paths actually engaged: entries were placed on ring
        # owners and reassigned pieces probed them remotely.
        assert fleet_stats["pushes_sent"] > 0
        assert fleet_stats["remote_hits"] \
            + fleet_stats["remote_misses"] > 0
        assert fleet_stats["remote_errors"] == 0
    # Remote WARM serves happened (which piece lands on which survivor
    # is scheduler-racy, so the hit count is asserted across the pair,
    # not per arm — content equality above is what each arm must hold).
    assert fleet_tcp["cache"]["fleet"]["remote_hits"] \
        + fleet_shm["cache"]["fleet"]["remote_hits"] > 0


def test_drain_handoff_zero_cold_refill_and_digest_stable():
    """A mid-stream drain with the fleet tier armed re-homes the drained
    worker's entries (handoff counters move, no errors) and never
    changes the delivered stream."""
    undrained = _run_scenario(cache="mem", fleet_cache=True)
    drained = _run_scenario(cache="mem", fleet_cache=True,
                            fleet_cache_drain_after=12)
    assert drained["stream_digest"] == undrained["stream_digest"]
    fleet_stats = drained["cache"]["fleet"]
    assert fleet_stats["handoff_entries_sent"] > 0
    assert fleet_stats["handoff_entries_received"] \
        == fleet_stats["handoff_entries_sent"]
    assert fleet_stats["remote_errors"] == 0
    assert fleet_stats["drained_after_batches"] == 12
