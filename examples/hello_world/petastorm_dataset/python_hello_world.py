"""Read the hello-world dataset with plain Python iteration.

Reference analogue: ``examples/hello_world/petastorm_dataset/python_hello_world.py``.
"""

import argparse

from petastorm_tpu import make_reader


def python_hello_world(dataset_url):
    with make_reader(dataset_url) as reader:
        for row in reader:
            print(row.id, row.image1.shape, row.array_4d.shape)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset-url", default="file:///tmp/hello_world_dataset")
    args = parser.parse_args()
    python_hello_world(args.dataset_url)
