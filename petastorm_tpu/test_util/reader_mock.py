"""Schema-driven fake Reader for testing downstream consumers without Parquet.

Reference parity: ``petastorm/test_util/reader_mock.py::ReaderMock`` —
SURVEY.md §2.7. Adapter (TF/Torch/JAX) tests wrap this instead of a real
dataset.
"""

from __future__ import annotations


class ReaderMock:
    """Yields ``schema.make_namedtuple(**row_generator(i))`` forever (or for
    ``num_rows`` rows when given)."""

    def __init__(self, schema, row_generator, num_rows=None, batched_output=False):
        self.schema = schema
        self.ngram = None
        self.batched_output = batched_output
        self.last_row_consumed = False
        self._row_generator = row_generator
        self._num_rows = num_rows
        self._served = 0
        self.stopped = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._num_rows is not None and self._served >= self._num_rows:
            self.last_row_consumed = True
            raise StopIteration
        row = self._row_generator(self._served)
        self._served += 1
        return self.schema.make_namedtuple(**row)

    def next(self):
        return self.__next__()

    def reset(self):
        self._served = 0
        self.last_row_consumed = False

    def stop(self):
        self.stopped = True

    def join(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()
