"""Unified telemetry layer: metrics registry, batch tracing, exposition.

Three legs (``docs/guides/diagnostics.md#metrics-and-tracing``):

- **metrics** — a process-wide, thread-safe, label-aware registry
  (:mod:`~petastorm_tpu.telemetry.registry`) with every family declared in
  :mod:`~petastorm_tpu.telemetry.metrics`; the reader pools, framed-socket
  transport, service dispatcher/worker/client, and JAX loader all publish
  into it, and the legacy ``diagnostics`` dicts are derived views;
- **tracing** — per-batch lifecycle spans keyed by a batch id minted at
  worker decode and propagated in the stream frame header
  (:mod:`~petastorm_tpu.telemetry.tracing`), exported as Perfetto-loadable
  Chrome ``trace_event`` JSON via ``JaxDataLoader(trace_path=...)`` or the
  service scenario's ``--trace-out``;
- **exposition** — Prometheus text format over a stdlib HTTP endpoint
  (:mod:`~petastorm_tpu.telemetry.http`, ``--metrics-port`` on the service
  CLIs), a :class:`~petastorm_tpu.telemetry.registry.SnapshotRing` for
  in-process ``rate()`` deltas, and ``python -m petastorm_tpu.service
  status --watch`` for a live terminal view of fleet rates.

Everything is stdlib-only and off-by-default on the hot path: with no
scraper, no trace path, and no watcher armed, producers pay a counter
increment per batch/message and nothing else.
"""

from petastorm_tpu.telemetry import tracing
from petastorm_tpu.telemetry.http import MetricsServer, start_metrics_server
from petastorm_tpu.telemetry.log import StructuredLogger, service_logger
from petastorm_tpu.telemetry.registry import (
    REGISTRY,
    MetricsRegistry,
    SnapshotRing,
    expose_prometheus,
)
from petastorm_tpu.telemetry.tracing import COLLECTOR, TraceCollector

__all__ = [
    "REGISTRY",
    "COLLECTOR",
    "MetricsRegistry",
    "MetricsServer",
    "SnapshotRing",
    "StructuredLogger",
    "TraceCollector",
    "expose_prometheus",
    "service_logger",
    "start_metrics_server",
    "tracing",
]
