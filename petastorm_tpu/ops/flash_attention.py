"""Pallas TPU flash attention: tiled online-softmax attention in VMEM.

The hot op of the sequence model (``models/sequence_model.py`` — NGram
``[B, T, H, D]`` windows). The reference has no accelerator code; this is
the TPU-native answer to "where do the FLOPs go": Q/K/V tiles stream
HBM → VMEM block by block, scores hit the MXU per tile
(``preferred_element_type=f32``), and the online softmax keeps running
``(max, sum, acc)`` statistics in VMEM scratch so the [T, T] score matrix is
NEVER materialized — memory O(block_q × block_k) instead of O(T²).

Layout/tiling choices (pallas_guide.md):

- grid = (batch·heads, Tq/block_q, Tk/block_k) — the last axis iterates
  innermost and sequentially on TPU, which is what makes scratch
  accumulation across K blocks valid;
- softmax statistics live in ``(block_q, 128)`` f32 scratch (lane-broadcast:
  min tile is 8×128, a [block_q]-vector would not tile);
- block sizes default to 128 to match the MXU's 128×128 systolic array; the
  head dim should be a multiple of 128 for full MXU rate (Mosaic pads
  smaller dims at reduced efficiency);
- sequence lengths that don't divide the block are zero-padded in the
  wrapper and masked to -inf inside the kernel via a 2D
  ``broadcasted_iota`` (1D iota does not lower on TPU).

Backward: ``jax.custom_vjp`` with a recompute-from-residuals backward
through the reference formulation — flash recomputation traded for XLA
autodiff simplicity (the standard rematerialization trade; a hand-tiled
backward kernel is the remaining headroom).

Off-TPU (tests, CPU dev) the kernel runs in interpret mode, so numerics are
validated everywhere while the Mosaic lowering is exercised on real TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_LANES = 128  # TPU lane width: scratch min-tile last dim


def _attention_reference(q, k, v):
    """Unfused oracle over ``[B, T, H, D]`` (same numerics contract as the
    kernel); used by the recompute backward."""
    scale = 1.0 / jnp.sqrt(jnp.array(q.shape[-1], jnp.float32))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs,
                      v.astype(jnp.float32)).astype(q.dtype)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch,
                  acc_scratch, *, sm_scale, block_k, kv_len):
    from jax.experimental import pallas as pl

    kb = pl.program_id(2)
    last_kb = pl.num_programs(2) - 1

    @pl.when(kb == 0)
    def _init():
        m_scratch[...] = jnp.full_like(m_scratch, -jnp.inf)
        l_scratch[...] = jnp.zeros_like(l_scratch)
        acc_scratch[...] = jnp.zeros_like(acc_scratch)

    q = q_ref[0].astype(jnp.float32)          # [block_q, d]
    k = k_ref[0].astype(jnp.float32)          # [block_k, d]
    v = v_ref[0].astype(jnp.float32)

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
    # Mask padded key rows (wrapper zero-pads KV up to the block multiple).
    col_ids = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=1)
    s = jnp.where(col_ids < kv_len, s, -jnp.inf)

    m_prev = m_scratch[...][:, :1]            # [block_q, 1]
    l_prev = l_scratch[...][:, :1]
    m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                    # [block_q, block_k]
    l_new = alpha * l_prev + p.sum(axis=1, keepdims=True)

    acc_scratch[...] = acc_scratch[...] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    m_scratch[...] = jnp.broadcast_to(m_new, m_scratch.shape)
    l_scratch[...] = jnp.broadcast_to(l_new, l_scratch.shape)

    @pl.when(kb == last_kb)
    def _emit():
        l = l_scratch[...][:, :1]
        o_ref[0] = (acc_scratch[...] / jnp.maximum(l, 1e-30)) \
            .astype(o_ref.dtype)


def _flash_forward(q, k, v, block_q, block_k, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_dtype = q.dtype
    b, t_q, h, d = q.shape
    t_kv = k.shape[1]

    # [B, T, H, D] → [B·H, T, D] (attention is independent per batch·head).
    def to_bh(x, t):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, x.shape[-1])

    qf, kf, vf = to_bh(q, t_q), to_bh(k, t_kv), to_bh(v, t_kv)

    pad_q = (-t_q) % block_q
    pad_k = (-t_kv) % block_k
    if pad_q:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))
    tq_p, tk_p = t_q + pad_q, t_kv + pad_k

    grid = (b * h, tq_p // block_q, tk_p // block_k)
    kernel = functools.partial(
        _flash_kernel,
        sm_scale=1.0 / float(d) ** 0.5,
        block_k=block_k,
        kv_len=t_kv,
    )
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b * h, tq_p, d), orig_dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running max
            pltpu.VMEM((block_q, _LANES), jnp.float32),  # running sum
            pltpu.VMEM((block_q, d), jnp.float32),       # output accumulator
        ],
        interpret=interpret,
    )(qf, kf, vf)

    out = out[:, :t_q, :]
    return out.reshape(b, h, t_q, d).transpose(0, 2, 1, 3)


def _should_interpret():
    """Mosaic lowering on real TPU; interpreter elsewhere (CPU tests)."""
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, block_q=128, block_k=128, interpret=None):
    """Tiled attention over ``[B, T, H, D]`` tensors; matches
    ``attention_reference`` numerics (f32 softmax) without materializing the
    ``[T, T]`` score matrix.

    :param block_q / block_k: VMEM tile sizes; keep at 128 (MXU-shaped)
        unless T is small.
    :param interpret: force the pallas interpreter (None = auto: interpret
        off-TPU, Mosaic on TPU).
    """
    if interpret is None:
        interpret = _should_interpret()
    return _flash_forward(q, k, v, block_q, block_k, interpret)


def _fwd(q, k, v, block_q, block_k, interpret):
    if interpret is None:
        interpret = _should_interpret()
    return _flash_forward(q, k, v, block_q, block_k, interpret), (q, k, v)


def _bwd(block_q, block_k, interpret, residuals, g):
    # Recompute-from-residuals backward via the reference formulation: the
    # O(T²) score matrix exists only inside XLA's fused backward, and only
    # for the backward pass (standard flash rematerialization trade).
    q, k, v = residuals
    _, vjp = jax.vjp(_attention_reference, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)
