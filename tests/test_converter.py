"""Dataset-converter tests: materialization dedup, ref-counting/cleanup,
and the three pipeline surfaces.

Reference analogue: ``petastorm/tests/test_spark_dataset_converter.py``.
"""

import os

import numpy as np
import pandas as pd
import pytest

from petastorm_tpu.spark import make_spark_converter
from petastorm_tpu.spark import dataset_converter as dc


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.setattr(dc, "_parent_cache_dir_url", None)
    monkeypatch.setattr(dc, "_cache_registry", {})
    d = tmp_path / "conv_cache"
    dc.set_parent_cache_dir_url(f"file://{d}")
    yield str(d)
    dc.set_parent_cache_dir_url(None)


def _df(n=20):
    return pd.DataFrame({
        "x": np.arange(n, dtype=np.float64),
        "y": np.arange(n, dtype=np.int64),
    })


def test_content_hash_distinguishes_slices_of_same_table():
    # Regression: zero-copy slices share parent buffers; equal-sized slices
    # of one table used to hash identically and reuse the wrong cache dir.
    import pyarrow as pa

    table = pa.table({"a": list(range(10))})
    first, second = table.slice(0, 5), table.slice(5, 5)
    h1 = dc._content_hash(first, 1 << 20, "snappy", None)
    h2 = dc._content_hash(second, 1 << 20, "snappy", None)
    assert h1 != h2


def test_requires_cache_dir_config(monkeypatch, tmp_path):
    monkeypatch.setattr(dc, "_parent_cache_dir_url", None)
    monkeypatch.delenv("PETASTORM_TPU_CACHE_DIR", raising=False)
    with pytest.raises(ValueError, match="No cache directory configured"):
        make_spark_converter(_df())


def test_materializes_once_and_dedups(cache_dir):
    c1 = make_spark_converter(_df())
    c2 = make_spark_converter(_df())          # identical content → same dir
    c3 = make_spark_converter(_df(25))        # different content → new dir
    assert c1.cache_dir_url == c2.cache_dir_url
    assert c3.cache_dir_url != c1.cache_dir_url
    assert len(os.listdir(cache_dir)) == 2
    assert len(c1) == 20 and len(c3) == 25
    c1.delete()
    assert len(os.listdir(cache_dir)) == 2    # c2 still references it
    c2.delete()
    assert len(os.listdir(cache_dir)) == 1    # refcount hit zero → removed
    c3.delete()
    assert os.listdir(cache_dir) == []


def test_dtype_cast_to_float32(cache_dir):
    conv = make_spark_converter(_df(), dtype="float32")
    with conv.make_jax_dataloader(batch_size=10, num_epochs=1,
                                  loader_kwargs={"stage_to_device": False}) \
            as loader:
        batch = next(iter(loader))
    assert batch["x"].dtype == np.float32     # cast
    assert batch["y"].dtype == np.int64       # ints untouched
    conv.delete()


def test_make_torch_dataloader(cache_dir):
    import torch

    conv = make_spark_converter(_df(30))
    with conv.make_torch_dataloader(batch_size=10, num_epochs=1,
                                    shuffle_row_groups=False) as loader:
        batches = list(loader)
    assert len(batches) == 3
    assert torch.is_tensor(batches[0]["x"])
    ys = [int(v) for b in batches for v in b["y"]]
    assert sorted(ys) == list(range(30))
    conv.delete()


def test_make_tf_dataset(cache_dir):
    conv = make_spark_converter(_df(30))
    with conv.make_tf_dataset(batch_size=10, num_epochs=1,
                              shuffle_row_groups=False) as dataset:
        batches = list(dataset)
    assert len(batches) == 3
    ys = sorted(int(v) for b in batches for v in b.y.numpy())
    assert ys == list(range(30))
    conv.delete()


def test_pyarrow_table_input(cache_dir):
    import pyarrow as pa

    table = pa.table({"a": list(range(10))})
    conv = make_spark_converter(table, dtype=None)
    with conv.make_jax_dataloader(batch_size=5, num_epochs=1,
                                  loader_kwargs={"stage_to_device": False}) \
            as loader:
        vals = [v for b in loader for v in b["a"].tolist()]
    assert sorted(vals) == list(range(10))
    conv.delete()


def test_converter_handles_array_columns(cache_dir):
    df = pd.DataFrame({
        "id": [1, 2, 3],
        "vec": [np.zeros(3), np.ones(3), np.full(3, 2.0)],
    })
    conv = make_spark_converter(df, dtype=None)
    with conv.make_jax_dataloader(batch_size=3, num_epochs=1,
                                  loader_kwargs={"stage_to_device": False}) \
            as loader:
        batch = next(iter(loader))
    assert batch["vec"].shape == (3, 3)
    conv.delete()
