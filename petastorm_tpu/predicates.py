"""Row-level predicates, evaluated worker-side before full decode.

Reference parity: ``petastorm/predicates.py`` (``PredicateBase``, ``in_set``,
``in_lambda``, ``in_negate``, ``in_reduce``, ``in_pseudorandom_split``) —
SURVEY.md §2.1. Predicates declare the minimal column subset they need
(:meth:`PredicateBase.get_fields`); the reader worker does a two-phase read
(predicate columns → boolean mask → remaining columns for surviving rows), so
a selective predicate skips most of the expensive decode work.
"""

from __future__ import annotations

import hashlib
from abc import ABC, abstractmethod


class PredicateBase(ABC):
    """A row filter: which fields it needs + per-row inclusion decision."""

    @abstractmethod
    def get_fields(self):
        """Set of field names :meth:`do_include` reads."""

    @abstractmethod
    def do_include(self, values):
        """``values`` maps each field from :meth:`get_fields` to the row's
        value; return True to keep the row."""


class in_set(PredicateBase):
    """Keep rows whose ``predicate_field`` value is in ``inclusion_values``."""

    def __init__(self, inclusion_values, predicate_field):
        self._inclusion_values = set(inclusion_values)
        self._predicate_field = predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def do_include(self, values):
        return values[self._predicate_field] in self._inclusion_values


class in_lambda(PredicateBase):
    """Keep rows for which ``predicate_func(values [, state])`` is truthy."""

    def __init__(self, predicate_fields, predicate_func, state_arg=None):
        if not isinstance(predicate_fields, (list, tuple, set)):
            raise ValueError("predicate_fields must be a list/tuple/set of names")
        self._predicate_fields = set(predicate_fields)
        self._predicate_func = predicate_func
        self._state_arg = state_arg

    def get_fields(self):
        return set(self._predicate_fields)

    def do_include(self, values):
        if self._state_arg is not None:
            return self._predicate_func(values, self._state_arg)
        return self._predicate_func(values)


class in_negate(PredicateBase):
    """Logical NOT of another predicate."""

    def __init__(self, predicate):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)


class in_reduce(PredicateBase):
    """Combine several predicates with a reduction (``all``/``any``-style).

    ``reduce_func`` receives the list of per-predicate booleans.
    """

    def __init__(self, predicate_list, reduce_func):
        self._predicate_list = list(predicate_list)
        self._reduce_func = reduce_func

    def get_fields(self):
        fields = set()
        for predicate in self._predicate_list:
            fields |= predicate.get_fields()
        return fields

    def do_include(self, values):
        return self._reduce_func(
            [p.do_include(values) for p in self._predicate_list]
        )


class in_pseudorandom_split(PredicateBase):
    """Deterministic hash-of-field train/val/test splitting.

    ``fraction_list`` partitions [0, 1); a row belongs to subset ``i`` when
    the normalized md5 hash of its ``predicate_field`` value falls in the
    ``i``-th interval. The same value always lands in the same subset, on any
    host — which is what makes the split usable across a TPU pod with no
    coordination (reference parity: ``petastorm/predicates.py``).
    """

    def __init__(self, fraction_list, subset_index, predicate_field):
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError(
                f"subset_index {subset_index} out of range for "
                f"{len(fraction_list)} fractions"
            )
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError(f"fractions sum to {sum(fraction_list)} > 1")
        self._fraction_list = list(fraction_list)
        self._subset_index = subset_index
        self._predicate_field = predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def do_include(self, values):
        value = values[self._predicate_field]
        position = _hash_to_unit_interval(value)
        low = sum(self._fraction_list[: self._subset_index])
        high = low + self._fraction_list[self._subset_index]
        return low <= position < high


def _hash_to_unit_interval(value):
    if isinstance(value, bytes):
        data = value
    else:
        data = str(value).encode("utf-8")
    digest = hashlib.md5(data).hexdigest()  # noqa: S324 - splitting, not security
    return int(digest, 16) / float(1 << 128)
