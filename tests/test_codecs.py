"""Codec round-trip tests (reference model: petastorm/tests/test_codecs.py)."""

from decimal import Decimal

import numpy as np
import pyarrow as pa
import pytest

from petastorm_tpu.schema.codecs import (
    CompressedImageCodec,
    CompressedNdarrayCodec,
    NdarrayCodec,
    ScalarCodec,
    numpy_to_arrow_type,
)
from petastorm_tpu.schema.unischema import UnischemaField


def test_scalar_codec_int_roundtrip():
    field = UnischemaField("x", np.int32, (), ScalarCodec(np.int32), False)
    encoded = field.codec.encode(field, 42)
    assert encoded == 42
    decoded = field.codec.decode(field, encoded)
    assert decoded == np.int32(42)
    assert decoded.dtype == np.int32


def test_scalar_codec_float_and_bool():
    ffield = UnischemaField("f", np.float64, (), ScalarCodec(np.float64), False)
    assert ffield.codec.decode(ffield, ffield.codec.encode(ffield, 1.5)) == 1.5
    bfield = UnischemaField("b", np.bool_, (), ScalarCodec(np.bool_), False)
    assert bfield.codec.decode(bfield, bfield.codec.encode(bfield, True)) == np.bool_(True)


def test_scalar_codec_string_and_bytes():
    sfield = UnischemaField("s", np.str_, (), ScalarCodec(str), False)
    assert sfield.codec.decode(sfield, sfield.codec.encode(sfield, "héllo")) == "héllo"
    # bytes in, str out when stored value arrives as utf-8 bytes
    assert sfield.codec.decode(sfield, "héllo".encode("utf-8")) == "héllo"
    bfield = UnischemaField("raw", np.bytes_, (), ScalarCodec(bytes), False)
    assert bfield.codec.decode(bfield, bfield.codec.encode(bfield, b"\x00\x01")) == b"\x00\x01"


def test_scalar_codec_decimal():
    field = UnischemaField("d", Decimal, (), ScalarCodec(Decimal), False)
    encoded = field.codec.encode(field, Decimal("123.45"))
    assert encoded == "123.45"
    assert field.codec.decode(field, encoded) == Decimal("123.45")
    # reference datasets surface arrow decimal128 -> decimal.Decimal directly
    assert field.codec.decode(field, Decimal("9.01")) == Decimal("9.01")


def test_scalar_codec_rejects_shaped_field():
    field = UnischemaField("m", np.float32, (2, 2), ScalarCodec(np.float32), False)
    with pytest.raises(ValueError, match="scalar"):
        field.codec.encode(field, np.zeros((2, 2), np.float32))


def test_ndarray_codec_roundtrip_bytes_format_is_np_save():
    field = UnischemaField("m", np.float64, (3, 4), NdarrayCodec(), False)
    value = np.random.random((3, 4))
    encoded = field.codec.encode(field, value)
    assert isinstance(encoded, bytes)
    # np.save magic prefix: reference byte-format compatibility
    assert encoded[:6] == b"\x93NUMPY"
    np.testing.assert_array_equal(field.codec.decode(field, encoded), value)


def test_ndarray_codec_wildcard_dims():
    field = UnischemaField("m", np.int16, (None, 3), NdarrayCodec(), False)
    value = np.arange(12, dtype=np.int16).reshape(4, 3)
    np.testing.assert_array_equal(
        field.codec.decode(field, field.codec.encode(field, value)), value
    )


def test_ndarray_codec_shape_mismatch_raises():
    field = UnischemaField("m", np.int16, (2, 3), NdarrayCodec(), False)
    with pytest.raises(ValueError, match="shape"):
        field.codec.encode(field, np.zeros((3, 3), np.int16))
    with pytest.raises(ValueError, match="rank"):
        field.codec.encode(field, np.zeros((2, 3, 1), np.int16))


def test_ndarray_codec_dtype_mismatch_raises():
    field = UnischemaField("m", np.int16, (2,), NdarrayCodec(), False)
    with pytest.raises(ValueError, match="dtype"):
        field.codec.encode(field, np.zeros((2,), np.int32))


def test_compressed_ndarray_codec_roundtrip():
    field = UnischemaField("m", np.float32, (10, 10), CompressedNdarrayCodec(), False)
    value = np.random.random((10, 10)).astype(np.float32)
    encoded = field.codec.encode(field, value)
    assert encoded[:2] == b"PK"  # zip container, as in the reference
    np.testing.assert_array_equal(field.codec.decode(field, encoded), value)


def test_compressed_image_codec_png_lossless():
    codec = CompressedImageCodec("png")
    field = UnischemaField("im", np.uint8, (32, 16, 3), codec, False)
    value = np.random.randint(0, 255, (32, 16, 3), dtype=np.uint8)
    encoded = codec.encode(field, value)
    assert encoded[:8] == b"\x89PNG\r\n\x1a\n"
    np.testing.assert_array_equal(codec.decode(field, encoded), value)


def test_compressed_image_codec_png_uint16_grayscale():
    codec = CompressedImageCodec("png")
    field = UnischemaField("im", np.uint16, (8, 8), codec, False)
    value = np.random.randint(0, 2**16 - 1, (8, 8)).astype(np.uint16)
    np.testing.assert_array_equal(codec.decode(field, codec.encode(field, value)), value)


def test_compressed_image_codec_jpeg_lossy_close():
    codec = CompressedImageCodec("jpeg", quality=95)
    field = UnischemaField("im", np.uint8, (32, 32, 3), codec, False)
    value = np.full((32, 32, 3), 128, dtype=np.uint8)
    decoded = codec.decode(field, codec.encode(field, value))
    assert decoded.shape == value.shape
    assert np.abs(decoded.astype(int) - value.astype(int)).mean() < 5


def test_compressed_image_codec_bad_format():
    with pytest.raises(ValueError):
        CompressedImageCodec("gif")


def test_codec_equality():
    assert NdarrayCodec() == NdarrayCodec()
    assert ScalarCodec(np.int32) == ScalarCodec(np.int32)
    assert ScalarCodec(np.int32) != ScalarCodec(np.int64)
    assert CompressedImageCodec("png") == CompressedImageCodec("png")
    assert CompressedImageCodec("png") != CompressedImageCodec("jpeg")


def test_numpy_to_arrow_type():
    assert numpy_to_arrow_type(np.int32) == pa.int32()
    assert numpy_to_arrow_type(np.float16) == pa.float16()
    assert numpy_to_arrow_type(str) == pa.string()
    assert numpy_to_arrow_type(bytes) == pa.binary()
    assert numpy_to_arrow_type(Decimal) == pa.string()
    assert numpy_to_arrow_type(np.dtype("datetime64[ns]")) == pa.timestamp("ns")
    assert numpy_to_arrow_type(np.dtype("datetime64[D]")) == pa.date32()
