"""Local-disk row-group cache with size-based LRU eviction.

Reference parity: ``petastorm/local_disk_cache.py::LocalDiskCache``. The
reference delegates storage to the third-party ``diskcache`` package; that is
absent in this environment (SURVEY.md §7 preamble), so the store is
self-written: one file per key (sha256-named), LRU eviction by access time
when the directory exceeds ``size_limit``. Concurrent readers on one host are
safe: writes go through a temp file + atomic rename, and eviction tolerates
concurrently-deleted files.

Repeated-epoch accelerator: on a TPU pod reading from GCS, epoch 2+ hits
local NVMe instead of the network.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile


class LocalDiskCache:
    def __init__(self, path, size_limit, expected_row_size_estimate=None,
                 shards=None, cleanup=False, **settings):
        """``size_limit`` in bytes; ``expected_row_size_estimate`` kept for
        reference API parity (unused — eviction is measured, not estimated)."""
        self._path = path
        self._size_limit = size_limit
        self._cleanup_on_exit = cleanup
        os.makedirs(path, exist_ok=True)

    def _key_path(self, key):
        digest = hashlib.sha256(repr(key).encode("utf-8")).hexdigest()
        return os.path.join(self._path, digest + ".cache")

    def get(self, key, fill_cache_func):
        file_path = self._key_path(key)
        try:
            with open(file_path, "rb") as f:
                value = self._deserialize(f.read())
        except Exception:  # corrupt/missing/format-mismatched entry → refill
            pass
        else:
            try:
                os.utime(file_path)  # LRU touch
            except OSError:  # read-only/shared cache dir: value still valid
                pass
            return value
        value = fill_cache_func()
        self._store(file_path, self._serialize(value))
        return value

    def _serialize(self, value):
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def _deserialize(self, payload):
        return pickle.loads(payload)  # noqa: S301

    def _store(self, file_path, payload):
        fd, tmp_path = tempfile.mkstemp(dir=self._path, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp_path, file_path)
        except OSError:  # pragma: no cover - disk full etc.; cache is best-effort
            try:
                os.unlink(tmp_path)
            except OSError:
                pass
            return
        self._evict_if_needed()

    def _evict_if_needed(self):
        entries = []
        total = 0
        try:
            names = os.listdir(self._path)
        except OSError:  # pragma: no cover
            return
        for name in names:
            if not name.endswith(".cache"):
                continue
            full = os.path.join(self._path, name)
            try:
                stat = os.stat(full)
            except OSError:
                continue
            entries.append((stat.st_atime, stat.st_size, full))
            total += stat.st_size
        if total <= self._size_limit:
            return
        entries.sort()  # oldest access first
        for _, size, full in entries:
            if total <= self._size_limit:
                break
            try:
                os.unlink(full)
                total -= size
            except OSError:
                continue

    def size_on_disk(self):
        return sum(
            os.stat(os.path.join(self._path, n)).st_size
            for n in os.listdir(self._path) if n.endswith(".cache")
        )

    def cleanup(self):
        if not self._cleanup_on_exit:
            return
        import shutil

        shutil.rmtree(self._path, ignore_errors=True)
