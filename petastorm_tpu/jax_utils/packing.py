"""Sequence packing: ragged rows → dense ``[B, T]`` batches + segment ids.

The TPU-first answer to ragged long-context input. The loader's
``last_batch="pad"`` path pads every example to the static T — at long T
with skewed length distributions most MXU FLOPs hit padding. Packing lays
MULTIPLE sequences end-to-end in each batch row instead, and the attention
kernel keeps them from attending to each other via ``segment_ids``
(``ops.flash_attention(segment_ids=...)`` masks cross-segment pairs
in-kernel; ``models.sequence_model.attention_reference`` is the dense
oracle). Static shapes throughout — XLA sees one ``[B, T]`` program
regardless of how many sequences each batch carries.

This is a host-side (numpy) stage: run it between the reader and
``device_put``/``make_jax_dataloader``-style staging, the same place the
batcher lives. The reference has no packing (its NGram windows are
fixed-length by construction — SURVEY.md §5 "long-context"); this exists
for the variable-length sequence corpora the flash kernel targets.

Conventions of the packed layout:

- ``segment_ids[b, t]``: 0-based index of the sequence occupying slot
  position ``t`` of batch row ``b``; **-1 marks padding**. Valid-token mask
  = ``segment_ids >= 0`` (padding positions attend only among themselves —
  mask them out of the loss).
- ``positions[b, t]``: offset WITHIN the sequence (0 at each sequence
  start; 0 on padding) — feed rotary/learned position embeddings from this,
  not from ``t``.
"""

from __future__ import annotations

import logging

import numpy as np

logger = logging.getLogger(__name__)

PACK_SEGMENT_KEY = "__segment_ids__"
PACK_POSITION_KEY = "__positions__"


def packed_valid_mask(segment_ids):
    """Boolean [B, T] mask of real (non-padding) token positions."""
    return np.asarray(segment_ids) >= 0


def pack_ragged(rows, slot_len, slots, keys=None):
    """Pack an iterable of ragged rows into dense batches (generator).

    :param rows: iterable of dicts; every packed field must be an array
        whose LEADING axis is the sequence length (lengths may differ per
        row, trailing dims must agree). Non-array / scalar fields are
        dropped (packing has no per-sequence row to carry them on — keep
        them upstream or fold them into a packed field).
    :param slot_len: tokens per batch row (the static T).
    :param slots: batch rows per emitted batch (the static B).
    :param keys: fields to pack (default: every ndarray field of the first
        row with ndim >= 1). An explicit key absent from the first row
        raises ``ValueError`` naming it — a typo must not silently pack
        the wrong field set. Fields NOT packed (scalars, 0-d arrays, or
        keys left out of an explicit list) are dropped with a one-time
        warning naming them.
    :return: yields dicts of ``{key: [slots, slot_len, ...]}`` plus
        ``PACK_SEGMENT_KEY`` / ``PACK_POSITION_KEY`` int32 arrays. The final
        batch is emitted even if partially filled (all -1 rows possible).

    Sequences are placed first-fit into the open batch's rows; a sequence
    longer than ``slot_len`` raises (truncation would silently corrupt the
    training distribution — split upstream instead), and zero-length
    sequences are skipped (they carry no tokens to place).
    """
    state = None
    warned_dropped = False

    def fresh(proto):
        nonlocal keys, warned_dropped
        if keys is None:
            keys = [k for k, val in proto.items() if val.ndim >= 1]
            if not keys:
                raise ValueError("no packable (array) fields in row")
        else:
            unknown = [k for k in keys if k not in proto]
            if unknown:
                raise ValueError(
                    f"keys={unknown} not present in row (row has "
                    f"{sorted(proto)}) — packing an absent field is a "
                    f"configuration error, not a drop")
        dropped = sorted(k for k in proto if k not in keys)
        if dropped and not warned_dropped:
            # Once per pack_ragged call: silently losing fields is how
            # labels/ids vanish from a training stream with no error
            # anywhere.
            warned_dropped = True
            logger.warning(
                "pack_ragged: dropping non-packed field(s) %s — packing "
                "has no per-sequence row to carry them on (keep them "
                "upstream, fold them into a packed field, or name them "
                "in keys=)", dropped)
        cols = {}
        for key in keys:
            trailing = proto[key].shape[1:]
            cols[key] = np.zeros((slots, slot_len) + trailing,
                                 proto[key].dtype)
        seg = np.full((slots, slot_len), -1, np.int32)
        pos = np.zeros((slots, slot_len), np.int32)
        return {"cols": cols, "seg": seg, "pos": pos,
                "used": np.zeros(slots, np.int64),
                "count": np.zeros(slots, np.int32)}

    def emit(st):
        out = {k: v for k, v in st["cols"].items()}
        out[PACK_SEGMENT_KEY] = st["seg"]
        out[PACK_POSITION_KEY] = st["pos"]
        return out

    for row in rows:
        row = {k: np.asarray(v) for k, v in row.items()}
        if state is None:
            state = fresh(row)
        length = row[keys[0]].shape[0]
        for key in keys:
            if row[key].shape[0] != length:
                raise ValueError(
                    f"field {key!r} length {row[key].shape[0]} != "
                    f"{keys[0]!r} length {length} (packed fields must share "
                    "the sequence axis)")
        if length > slot_len:
            raise ValueError(
                f"sequence of length {length} does not fit slot_len "
                f"{slot_len}; split long sequences upstream")
        if length == 0:
            # An empty sequence carries no tokens: placing it would burn a
            # segment id with no positions (breaking the exactly-once
            # round-trip); skip it instead.
            continue
        # First-fit: the leftmost row with room.
        fit = np.nonzero(state["used"] + length <= slot_len)[0]
        if fit.size == 0:
            yield emit(state)
            state = fresh(row)
            fit = np.array([0])
        b = int(fit[0])
        start = int(state["used"][b])
        for key in keys:
            state["cols"][key][b, start:start + length] = row[key]
        state["seg"][b, start:start + length] = state["count"][b]
        state["pos"][b, start:start + length] = np.arange(length)
        state["used"][b] += length
        state["count"][b] += 1

    if state is not None and state["count"].sum() > 0:
        yield emit(state)


def iter_ragged_rows(reader, sequence_fields, length_field=None):
    """Adapt a Reader's output stream into ragged-row dicts for
    :func:`pack_ragged`.

    Handles both row readers (one namedtuple per row) and batch/columnar
    readers (namedtuples of ``[N, ...]`` column arrays, split back into
    rows). ``length_field``: optional int column holding each row's true
    sequence length — the packed fields' leading axis is trimmed to it
    (the standard ragged-in-Parquet layout: static shapes on disk, true
    length as data).
    """
    # Column-batch readers (make_batch_reader / make_columnar_reader)
    # advertise batched_output; row readers yield one row per item.
    batched = bool(getattr(reader, "batched_output", False))
    for item in reader:
        cols = {f: np.asarray(getattr(item, f)) for f in sequence_fields}
        if batched:
            lens = (np.asarray(getattr(item, length_field))
                    if length_field else None)
            for i in range(cols[sequence_fields[0]].shape[0]):
                cut = int(lens[i]) if lens is not None else None
                yield {f: cols[f][i][:cut] for f in sequence_fields}
        else:
            cut = (int(getattr(item, length_field))
                   if length_field else None)
            yield {f: cols[f][:cut] for f in sequence_fields}


def count_packed_batches(reader, slot_len, slots, sequence_fields,
                         length_field=None):
    """Count the batches :func:`pack_ragged` will emit for ``reader`` by
    DRAINING it once — the observation half of
    :func:`~petastorm_tpu.jax_utils.sharding.agree_max_batches` for the
    PACKED delivery path (the packed analogue of
    :func:`~petastorm_tpu.jax_utils.sharding.count_deliverable_batches`,
    which counts ROW batches and therefore cannot predict packed emission).

    Packed batch counts are doubly data-dependent — they depend on the
    ragged LENGTH DISTRIBUTION through first-fit placement, not just on row
    counts — so under a global sharding every host must observe its own
    count on a separately-constructed counting reader (same arguments),
    agree the minimum across hosts, and pass it as ``max_batches`` to
    :func:`make_packed_jax_dataloader`. Drains :func:`pack_ragged` itself
    rather than re-implementing first-fit arithmetic: the count is exactly
    the emission count, including the final partial batch and zero-length
    skips, by construction.
    """
    if getattr(reader, "num_epochs", 1) is None:
        raise ValueError(
            "count_packed_batches would never terminate on an infinite "
            "reader (num_epochs=None): construct the counting reader with "
            "num_epochs=1 and scale the agreed count by your epoch budget")
    n = 0
    with reader:
        for _ in pack_ragged(
                iter_ragged_rows(reader, sequence_fields, length_field),
                slot_len=slot_len, slots=slots):
            n += 1
    return n


def make_packed_jax_dataloader(reader, slot_len, slots, sequence_fields,
                               length_field=None, max_batches=None,
                               **loader_kwargs):
    """Packed delivery path: reader → ragged rows → :func:`pack_ragged` →
    the :class:`~petastorm_tpu.jax_utils.loader.JaxDataLoader` staging
    machinery (prefetch, async device_put, diagnostics) unchanged.

    Yields ``{field: [slots, slot_len, ...]}`` batches plus
    ``PACK_SEGMENT_KEY`` / ``PACK_POSITION_KEY`` — feed the segment ids to
    ``flash_attention`` / ``ring_attention`` / ``ulysses_attention``.

    ``sequence_fields``: the reader fields to pack (leading axis =
    sequence). ``length_field``: optional true-length column for
    padded-on-disk layouts. Not resumable (``state_dict`` raises): repacked
    batches cannot be attributed to reader deliveries. With a global
    ``sharding``, pass ``max_batches`` explicitly (packed batch counts are
    data-dependent — agree them across hosts with
    :func:`~petastorm_tpu.jax_utils.sharding.agree_max_batches`).
    """
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    def source():
        return pack_ragged(
            iter_ragged_rows(reader, sequence_fields, length_field),
            slot_len=slot_len, slots=slots)

    return JaxDataLoader(reader, slots, max_batches=max_batches,
                         batch_source=source, **loader_kwargs)


def unpack(packed, key):
    """Recover the list of original sequences of ``packed[key]`` (row-major:
    batch row 0's segments first) — the inverse of :func:`pack_ragged` for
    round-trip tests and debugging."""
    seg = packed[PACK_SEGMENT_KEY]
    out = []
    for b in range(seg.shape[0]):
        for s in range(seg[b].max() + 1):
            mask = seg[b] == s
            if mask.any():
                out.append(np.asarray(packed[key])[b, mask])
    return out
