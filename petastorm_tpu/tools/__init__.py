"""Operator CLI tools (reference parity: ``petastorm/tools/``)."""
