"""GCS fast-listing tests — all against a fake fsspec filesystem (no network).

Reference analogue: ``petastorm/gcsfs_helpers/gcsfs_fast_list.py`` (SURVEY.md
§2.4): one recursive listing sweep + pseudo-directory synthesis replaces
per-directory ``ls`` round-trips during dataset discovery.
"""

import pytest

from petastorm_tpu.gcsfs_helpers.gcsfs_fast_list import (
    FastListingFilesystem,
    build_dircache,
    fast_list,
    seed_listing_cache,
    warm_gcs_listing,
)


class FakeGCSFileSystem:
    """Flat-key store mimicking gcsfs's listing surface.

    ``find`` assembles its result from fixed-size pages the way gcsfs follows
    ``nextPageToken`` — tests assert multi-page listings come back complete.
    Every API entry point counts its calls so tests can prove "one sweep,
    zero per-directory round-trips".
    """

    PAGE_SIZE = 100

    def __init__(self, keys):
        self._objects = {k: {"name": k, "size": 11, "type": "file"}
                         for k in keys}
        self.dircache = {}
        self.find_calls = 0
        self.pages_served = 0
        self.ls_network_calls = 0

    def find(self, path, detail=False):
        self.find_calls += 1
        names = sorted(k for k in self._objects
                       if k == path or k.startswith(path.rstrip("/") + "/"))
        listing = {}
        for start in range(0, len(names), self.PAGE_SIZE):
            self.pages_served += 1  # one objects.list page per PAGE_SIZE keys
            for name in names[start:start + self.PAGE_SIZE]:
                listing[name] = dict(self._objects[name])
        return listing if detail else sorted(listing)

    def ls(self, path, detail=False):
        path = path.rstrip("/")
        if path in self.dircache:  # fsspec semantics: cache first
            infos = self.dircache[path]
            return list(infos) if detail else [i["name"] for i in infos]
        self.ls_network_calls += 1
        raise AssertionError(f"network ls({path!r}) — dircache incomplete")


DATASET_KEYS = [
    "bucket/ds/_common_metadata",
    "bucket/ds/part-00000.parquet",
    "bucket/ds/part-00001.parquet",
    "bucket/ds/year=2024/month=1/part-00002.parquet",
    "bucket/ds/year=2024/month=2/part-00003.parquet",
    "bucket/ds/year=2025/month=1/part-00004.parquet",
]


def test_fast_list_is_one_find_sweep():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    paths = fast_list("gs://bucket/ds", filesystem=fs)
    assert paths == sorted(DATASET_KEYS)
    assert fs.find_calls == 1


def test_fast_list_detail_and_scheme_stripping():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    listing = fast_list("gcs://bucket/ds", filesystem=fs, detail=True)
    assert set(listing) == set(DATASET_KEYS)
    assert listing["bucket/ds/_common_metadata"]["type"] == "file"


def test_fast_list_paginates_completely():
    # 2.5 pages worth of objects — result must span every page.
    keys = [f"bucket/big/part-{i:05d}.parquet" for i in range(250)]
    fs = FakeGCSFileSystem(keys)
    paths = fast_list("gs://bucket/big", filesystem=fs)
    assert len(paths) == 250
    assert fs.find_calls == 1
    assert fs.pages_served == 3  # 100 + 100 + 50


def test_build_dircache_synthesizes_intermediate_directories():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    cache = build_dircache("bucket/ds", fs.find("bucket/ds", detail=True))
    # Every intermediate level exists, including dirs holding only dirs.
    assert set(cache) == {
        "bucket/ds", "bucket/ds/year=2024", "bucket/ds/year=2024/month=1",
        "bucket/ds/year=2024/month=2", "bucket/ds/year=2025",
        "bucket/ds/year=2025/month=1",
    }
    root_names = {i["name"]: i["type"] for i in cache["bucket/ds"]}
    assert root_names["bucket/ds/year=2024"] == "directory"
    assert root_names["bucket/ds/part-00000.parquet"] == "file"
    # A directory containing only directories still lists its children.
    y2025 = cache["bucket/ds/year=2025"]
    assert [i["name"] for i in y2025] == ["bucket/ds/year=2025/month=1"]


def test_build_dircache_skips_root_marker_and_rejects_foreign_paths():
    cache = build_dircache("bucket/ds", {
        "bucket/ds": {"name": "bucket/ds", "size": 0, "type": "file"},
        "bucket/ds/a.parquet": {"name": "bucket/ds/a.parquet", "size": 1,
                                "type": "file"},
    })
    assert [i["name"] for i in cache["bucket/ds"]] == ["bucket/ds/a.parquet"]
    with pytest.raises(ValueError, match="not under the root"):
        build_dircache("bucket/ds", {"bucket/other/x": {"size": 1}})


def test_build_dircache_skips_nested_directory_markers():
    # GCS console creates zero-byte 'dir/' placeholder objects; they must not
    # become phantom files in the cache.
    cache = build_dircache("bucket/ds", {
        "bucket/ds/sub/": {"name": "bucket/ds/sub/", "size": 0,
                           "type": "file"},
        "bucket/ds/sub/a.parquet": {"name": "bucket/ds/sub/a.parquet",
                                    "size": 1, "type": "file"},
    })
    names = [i["name"] for i in cache["bucket/ds/sub"]]
    assert names == ["bucket/ds/sub/a.parquet"]


def test_fast_listing_filesystem_ls_of_file_path():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    wrapped = FastListingFilesystem(fs, "gs://bucket/ds")
    # fsspec contract: ls of a file returns that file's own entry.
    assert wrapped.ls("bucket/ds/part-00000.parquet") == \
        ["bucket/ds/part-00000.parquet"]
    assert wrapped.ls("bucket/ds/part-00000.parquet",
                      detail=True)[0]["size"] == 11


def test_seed_listing_cache_makes_every_ls_hit_memory():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    files = warm_gcs_listing(fs, "gs://bucket/ds")
    assert files == len(DATASET_KEYS)
    assert fs.find_calls == 1
    # Walk the whole tree through ls() — the fake raises on any network ls.
    to_visit = ["bucket/ds"]
    seen_files = []
    while to_visit:
        for info in fs.ls(to_visit.pop(), detail=True):
            if info["type"] == "directory":
                to_visit.append(info["name"])
            else:
                seen_files.append(info["name"])
    assert sorted(seen_files) == sorted(DATASET_KEYS)
    assert fs.ls_network_calls == 0


def test_seed_listing_cache_direct():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    listing = fast_list("gs://bucket/ds", filesystem=fs, detail=True)
    seed_listing_cache(fs, "gs://bucket/ds", listing)
    assert fs.ls("bucket/ds/year=2024") == [
        "bucket/ds/year=2024/month=1", "bucket/ds/year=2024/month=2"]


def test_fast_listing_filesystem_metadata_surface():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    wrapped = FastListingFilesystem(fs, "gs://bucket/ds")
    assert fs.find_calls == 1

    assert wrapped.isdir("bucket/ds/year=2024")
    assert not wrapped.isdir("bucket/ds/part-00000.parquet")
    assert wrapped.isfile("bucket/ds/part-00000.parquet")
    assert wrapped.exists("bucket/ds/year=2025/month=1/part-00004.parquet")
    assert not wrapped.exists("bucket/ds/nope")
    assert wrapped.info("bucket/ds/part-00000.parquet")["size"] == 11
    assert wrapped.info("bucket/ds/year=2024")["type"] == "directory"
    with pytest.raises(FileNotFoundError):
        wrapped.ls("bucket/ds/absent")

    files = wrapped.find("bucket/ds/year=2024")
    assert files == ["bucket/ds/year=2024/month=1/part-00002.parquet",
                     "bucket/ds/year=2024/month=2/part-00003.parquet"]

    walked = list(wrapped.walk())
    dirpaths = [d for d, _, _ in walked]
    assert dirpaths[0] == "bucket/ds"
    assert set(dirpaths) == {
        "bucket/ds", "bucket/ds/year=2024", "bucket/ds/year=2025",
        "bucket/ds/year=2024/month=1", "bucket/ds/year=2024/month=2",
        "bucket/ds/year=2025/month=1",
    }
    all_files = [f for _, _, fnames in walked for f in fnames]
    assert len(all_files) == len(DATASET_KEYS)
    # After construction, zero further API calls were made.
    assert fs.find_calls == 1
    assert fs.ls_network_calls == 0


def test_fast_listing_filesystem_passes_content_ops_through():
    class FakeWithOpen(FakeGCSFileSystem):
        def open(self, path, mode="rb"):
            return ("opened", path, mode)

    fs = FakeWithOpen(DATASET_KEYS)
    wrapped = FastListingFilesystem(fs, "gs://bucket/ds")
    assert wrapped.open("bucket/ds/part-00000.parquet") == \
        ("opened", "bucket/ds/part-00000.parquet", "rb")


# --- resolver integration (round 4): gs:// URLs get the fast path ---------

class LocalBackedGCSFake(FakeGCSFileSystem):
    """FakeGCSFileSystem plus content ops: keys map onto a local directory,
    so pyarrow can actually read parquet bytes through the wrapper while the
    listing counters prove discovery never touched "the network"."""

    local_root = None  # set by the test (class-level: fsspec instantiates)
    instances = []

    # minimal fsspec class contract for url_to_fs dispatch
    protocol = "gs"

    @classmethod
    def _get_kwargs_from_urls(cls, url):
        return {}

    @classmethod
    def _strip_protocol(cls, path):
        for scheme in ("gs://", "gcs://"):
            if path.startswith(scheme):
                return path[len(scheme):]
        return path

    def __init__(self, *args, **kwargs):
        import os

        keys = []
        for dirpath, _, files in os.walk(self.local_root):
            for f in files:
                full = os.path.join(dirpath, f)
                rel = os.path.relpath(full, self.local_root)
                keys.append("bucket/ds/" + rel.replace(os.sep, "/"))
        super().__init__(keys)
        for k in list(self._objects):
            self._objects[k]["size"] = os.path.getsize(self._local(k))
        LocalBackedGCSFake.instances.append(self)

    def _local(self, path):
        import os

        rel = path[len("bucket/ds/"):]
        return os.path.join(self.local_root, rel.replace("/", os.sep))

    def open(self, path, mode="rb", **kwargs):
        return open(self._local(path.rstrip("/")), mode)

    def cat_file(self, path, start=None, end=None, **kwargs):
        with open(self._local(path), "rb") as f:
            data = f.read()
        return data[start:end]

    def size(self, path):
        import os

        return os.path.getsize(self._local(path))


@pytest.fixture
def gs_registered(petastorm_dataset, monkeypatch):
    import fsspec

    LocalBackedGCSFake.local_root = petastorm_dataset.path
    LocalBackedGCSFake.instances = []
    # Register the fake as the "gs" protocol implementation; url_to_fs will
    # instantiate it (clobber gcsfs if present).
    fsspec.register_implementation("gs", LocalBackedGCSFake, clobber=True)
    yield
    fsspec.register_implementation("gs", None, clobber=True)


def test_resolver_wraps_gs_in_fast_listing(gs_registered):
    from petastorm_tpu.fs_utils import FilesystemResolver

    resolver = FilesystemResolver("gs://bucket/ds", fast_gcs_listing=True)
    fs = resolver.filesystem()
    assert resolver.get_dataset_path() == "bucket/ds"
    (fake,) = LocalBackedGCSFake.instances
    assert fake.find_calls == 1          # exactly one sweep at construction
    assert fake.ls_network_calls == 0    # nothing fell through
    # discovery-style traffic resolves from the cached tree
    infos = fs.get_file_info(
        __import__("pyarrow").fs.FileSelector("bucket/ds", recursive=True))
    assert any(i.path.endswith(".parquet") for i in infos)
    assert fake.find_calls == 1 and fake.ls_network_calls == 0


def test_make_reader_over_gs_uses_one_sweep(gs_registered):
    from petastorm_tpu import make_reader

    with make_reader("gs://bucket/ds", reader_pool_type="dummy",
                     num_epochs=1, shuffle_row_groups=False) as reader:
        rows = sum(1 for _ in reader)
    assert rows > 0
    (fake,) = LocalBackedGCSFake.instances
    assert fake.find_calls == 1, "discovery must be ONE listing sweep"
    assert fake.ls_network_calls == 0, "no per-directory network ls"


def test_make_reader_gs_opt_out_skips_wrapper(gs_registered):
    from petastorm_tpu.fs_utils import FilesystemResolver

    # Opt-out: no sweep is performed at construction (resolution falls back
    # to the default path, which for the registered fake protocol errors or
    # lists lazily — just assert no eager sweep happened).
    try:
        FilesystemResolver("gs://bucket/ds", fast_gcs_listing=False)
    except Exception:
        pass  # pyarrow's native gs resolution may be unavailable here
    assert all(f.find_calls == 0 for f in LocalBackedGCSFake.instances)


def test_multi_url_gs_list_skips_fast_listing(gs_registered):
    from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths

    # Two URLs: the wrapper would be rooted at one prefix, so resolution
    # must fall back — and no eager sweep should happen.
    try:
        get_filesystem_and_path_or_paths(
            ["gs://bucket/ds", "gs://bucket/ds"], fast_gcs_listing=True)
    except Exception:
        pass  # default gs resolution may be unavailable here
    assert all(f.find_calls == 0 for f in LocalBackedGCSFake.instances)


# ---------------------------------------------------------------------------
# transient-failure retry (satellite of the data-service PR: one flaky
# listing page must not abort reader construction for a whole pod)
# ---------------------------------------------------------------------------

class FlakyGCSFileSystem(FakeGCSFileSystem):
    """Fails the first ``fail_times`` find() sweeps with ``error``."""

    def __init__(self, keys, fail_times=1, error=None):
        super().__init__(keys)
        self._fail_times = fail_times
        self._error = error or OSError("503 backend unavailable")

    def find(self, path, detail=False):
        if self.find_calls < self._fail_times:
            self.find_calls += 1
            raise self._error
        return super().find(path, detail=detail)


def test_fast_list_retries_transient_failures(monkeypatch):
    import time as _time

    slept = []
    monkeypatch.setattr(_time, "sleep", slept.append)
    fs = FlakyGCSFileSystem(DATASET_KEYS, fail_times=2)
    paths = fast_list("gs://bucket/ds", filesystem=fs, retries=3,
                      retry_base_delay=0.25)
    assert paths == sorted(DATASET_KEYS)
    assert fs.find_calls == 3          # 2 failures + 1 success
    assert len(slept) == 2
    # Exponential backoff with jitter: base, then doubled, each within
    # [delay, delay * 1.5).
    assert 0.25 <= slept[0] < 0.375
    assert 0.5 <= slept[1] < 0.75


def test_fast_list_retry_budget_is_bounded(monkeypatch):
    import time as _time

    monkeypatch.setattr(_time, "sleep", lambda _s: None)
    fs = FlakyGCSFileSystem(DATASET_KEYS, fail_times=99)
    with pytest.raises(OSError, match="503"):
        fast_list("gs://bucket/ds", filesystem=fs, retries=2)
    assert fs.find_calls == 3          # initial call + 2 retries, no more


def test_fast_list_does_not_retry_missing_dataset():
    fs = FlakyGCSFileSystem(DATASET_KEYS, fail_times=99,
                            error=FileNotFoundError("bucket/nope"))
    with pytest.raises(FileNotFoundError):
        fast_list("gs://bucket/nope", filesystem=fs, retries=5)
    assert fs.find_calls == 1          # permanent error: no retry


def test_retry_with_backoff_is_shared_with_the_service_client():
    """The factored helper is the exact policy the service client reuses."""
    from petastorm_tpu.utils import retry_with_backoff

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionRefusedError("worker not up yet")
        return "ok"

    assert retry_with_backoff(flaky, retries=4, base_delay=0,
                              sleep=lambda _s: None) == "ok"
    assert len(calls) == 3
