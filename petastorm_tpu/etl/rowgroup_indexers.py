"""Concrete row-group indexers.

Reference parity: ``petastorm/etl/rowgroup_indexers.py`` (``SingleFieldIndexer``,
``FieldNotNullIndexer``). An indexer maps field values → the set of row-group
ordinals containing them; selectors use it to prune I/O.
"""

from __future__ import annotations

from abc import ABC, abstractmethod


class RowGroupIndexerBase(ABC):
    """Builds and serves one value→row-groups index."""

    @property
    @abstractmethod
    def index_name(self):
        ...

    @property
    @abstractmethod
    def column_names(self):
        """Columns this indexer must read while building."""

    @abstractmethod
    def build_index(self, decoded_rows, piece_index):
        """Feed one row group's (decoded) rows during the build pass."""

    @abstractmethod
    def get_row_group_indexes(self, value=None):
        """Set of row-group ordinals for ``value`` (indexer-specific)."""


class SingleFieldIndexer(RowGroupIndexerBase):
    """value-of-field → set of row-group ordinals."""

    def __init__(self, index_name, index_field):
        self._index_name = index_name
        self._column_name = index_field
        self._index_data = {}

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._column_name]

    @property
    def indexed_values(self):
        return list(self._index_data.keys())

    def build_index(self, decoded_rows, piece_index):
        for row in decoded_rows:
            value = row.get(self._column_name)
            if value is None:
                continue
            self._index_data.setdefault(value, set()).add(piece_index)

    def get_row_group_indexes(self, value=None):
        if value is None:
            all_groups = set()
            for groups in self._index_data.values():
                all_groups |= groups
            return all_groups
        return set(self._index_data.get(value, set()))

    def __setstate__(self, state):
        # Tolerate reference-written attribute layouts (petastorm pickles
        # carry the same three attributes; normalize if names drift).
        self.__dict__.update(state)
        self.__dict__.setdefault("_index_data", {})


class FieldNotNullIndexer(RowGroupIndexerBase):
    """Row groups where ``index_field`` has at least one non-null value."""

    def __init__(self, index_name, index_field):
        self._index_name = index_name
        self._column_name = index_field
        self._row_groups = set()

    @property
    def index_name(self):
        return self._index_name

    @property
    def column_names(self):
        return [self._column_name]

    def build_index(self, decoded_rows, piece_index):
        for row in decoded_rows:
            if row.get(self._column_name) is not None:
                self._row_groups.add(piece_index)
                return

    def get_row_group_indexes(self, value=None):
        return set(self._row_groups)
