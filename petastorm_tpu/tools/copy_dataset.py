"""Copy/subset/re-rowgroup a petastorm dataset.

Reference parity: ``petastorm/tools/copy_dataset.py`` (``copy_dataset`` +
console script ``petastorm-copy-dataset.py``). Engine difference: the copy
streams row groups through pyarrow in-process instead of a Spark job —
``copy_dataset(None, ...)`` is the native path; a SparkSession first arg is
accepted and ignored for signature parity.
"""

from __future__ import annotations

import argparse
import sys

from petastorm_tpu.schema.unischema import match_unischema_fields


def copy_dataset(spark, source_url, target_url, field_regex=None,
                 not_null_fields=None, overwrite_output=False,
                 partitions_count=None, row_group_size_mb=None,
                 rows_per_row_group=None,
                 hdfs_driver="libhdfs", storage_options=None):
    """Copy ``source_url`` → ``target_url``, optionally subsetting fields
    (``field_regex``) and dropping rows with nulls in ``not_null_fields``.

    ``spark`` is accepted for reference-signature parity and unused.
    ``partitions_count`` maps to output file count (rows are re-split).
    """
    from petastorm_tpu import make_reader
    from petastorm_tpu.etl import metadata as etl_metadata
    from petastorm_tpu.fs_utils import FilesystemResolver

    resolver = FilesystemResolver(target_url, hdfs_driver=hdfs_driver,
                                  storage_options=storage_options)
    target_fs, target_path = resolver.filesystem(), resolver.get_dataset_path()
    if not overwrite_output:
        try:
            infos = target_fs.get_file_info(
                __import__("pyarrow.fs", fromlist=["FileSelector"])
                .FileSelector(target_path))
            if infos:
                raise ValueError(
                    f"Target {target_url!r} is not empty; pass "
                    f"overwrite_output=True to overwrite")
        except FileNotFoundError:
            pass

    source_resolver = FilesystemResolver(source_url, hdfs_driver=hdfs_driver,
                                         storage_options=storage_options)
    schema = etl_metadata.get_schema(source_resolver.filesystem(),
                                     source_resolver.get_dataset_path())
    if field_regex:
        subset_fields = match_unischema_fields(schema, field_regex)
        if not subset_fields:
            raise ValueError(
                f"field_regex {field_regex!r} matched no fields of "
                f"{list(schema.fields)}")
        out_schema = schema.create_schema_view(subset_fields)
    else:
        out_schema = schema

    not_null = set(not_null_fields or [])
    unknown = not_null - set(out_schema.fields)
    if unknown:
        raise ValueError(f"not_null_fields not in copied schema: {unknown}")

    reader = make_reader(source_url, schema_fields=list(out_schema.fields),
                         reader_pool_type="dummy", num_epochs=1,
                         shuffle_row_groups=False,
                         storage_options=storage_options)

    def rows():
        with reader:
            for row in reader:
                row_dict = row._asdict()
                if any(row_dict[f] is None for f in not_null):
                    continue
                yield row_dict

    write_kwargs = {"storage_options": storage_options}
    if row_group_size_mb is not None:
        write_kwargs["row_group_size_mb"] = row_group_size_mb
    if rows_per_row_group is not None:
        write_kwargs["rows_per_row_group"] = rows_per_row_group
    if partitions_count:
        total = sum(p.num_rows for p in etl_metadata.load_row_groups(
            source_resolver.filesystem(), source_resolver.get_dataset_path()))
        write_kwargs["rows_per_file"] = max(1, -(-total // partitions_count))
    etl_metadata.materialize_rows(target_url, out_schema, rows(),
                                  **write_kwargs)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Copy a petastorm dataset, optionally subsetting")
    parser.add_argument("source_url")
    parser.add_argument("target_url")
    parser.add_argument("--field-regex", nargs="*", default=None)
    parser.add_argument("--not-null-fields", nargs="*", default=None)
    parser.add_argument("--overwrite-output", action="store_true")
    parser.add_argument("--partitions-count", type=int, default=None)
    parser.add_argument("--row-group-size-mb", type=int, default=None)
    args = parser.parse_args(argv)
    copy_dataset(None, args.source_url, args.target_url,
                 field_regex=args.field_regex,
                 not_null_fields=args.not_null_fields,
                 overwrite_output=args.overwrite_output,
                 partitions_count=args.partitions_count,
                 row_group_size_mb=args.row_group_size_mb)
    print(f"Copied {args.source_url} -> {args.target_url}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
