"""Explicit pipeline stage graph + profile-driven online autotuner.

``graph.py`` models the reader/worker/loader stack as stage nodes with
placement attributes and measured costs; ``autotune.py`` plans knob
deltas from windowed profiles (pure, unit-testable) and applies them
live. Entry points: ``build_loader_graph(loader)`` and
``JaxDataLoader(autotune=...)``. See ``docs/guides/pipeline.md``.
"""

from petastorm_tpu.pipeline.autotune import (  # noqa: F401
    AutotuneController,
    Planner,
    classify,
)
from petastorm_tpu.pipeline.graph import (  # noqa: F401
    Knob,
    PipelineGraph,
    StageNode,
    build_loader_graph,
)
