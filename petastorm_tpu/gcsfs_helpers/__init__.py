"""GCS helpers (reference parity: ``petastorm/gcsfs_helpers/``)."""
