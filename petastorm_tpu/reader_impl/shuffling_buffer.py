"""Bounded reservoir giving approximate row-level shuffle.

Reference parity: ``petastorm/reader_impl/shuffling_buffer.py``
(``ShufflingBufferBase``, ``NoopShufflingBuffer``, ``RandomShufflingBuffer``).
Row-group shuffling alone leaves rows correlated within a group; this buffer
decorrelates them with O(capacity) memory. Retrieval swaps a random element
with the tail (O(1), no list compaction).
"""

from __future__ import annotations

import random
from abc import ABC, abstractmethod
from collections import deque


class ShufflingBufferBase(ABC):
    """Items flow add_many() → retrieve(); finish() drains the tail."""

    @abstractmethod
    def add_many(self, items):
        ...

    @abstractmethod
    def retrieve(self):
        ...

    @abstractmethod
    def can_add(self):
        ...

    @abstractmethod
    def can_retrieve(self):
        ...

    @property
    @abstractmethod
    def size(self):
        ...

    @abstractmethod
    def finish(self):
        """No more items will be added; everything buffered becomes retrievable."""


class NoopShufflingBuffer(ShufflingBufferBase):
    """Pass-through FIFO (shuffling disabled)."""

    def __init__(self):
        self._queue = deque()
        self._done = False

    def add_many(self, items):
        self._queue.extend(items)

    def retrieve(self):
        return self._queue.popleft()

    def can_add(self):
        return not self._done

    def can_retrieve(self):
        return len(self._queue) > 0

    @property
    def size(self):
        return len(self._queue)

    def finish(self):
        self._done = True


class RandomShufflingBuffer(ShufflingBufferBase):
    """Random-eviction reservoir.

    ``shuffling_buffer_capacity``: target fill level — :meth:`can_add` is
    False at or above it (producers should pause).
    ``min_after_retrieve``: retrieval is blocked until this many items are
    buffered (shuffle quality floor), until :meth:`finish`.
    ``extra_capacity``: hard headroom above capacity for producers that add
    whole row groups at once (reference semantics: adds may overshoot).
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve=0,
                 extra_capacity=1000, random_seed=None):
        if min_after_retrieve > shuffling_buffer_capacity:
            raise ValueError("min_after_retrieve cannot exceed capacity")
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._hard_capacity = shuffling_buffer_capacity + extra_capacity
        self._random = random.Random(random_seed)
        self._items = []
        self._done = False

    def add_many(self, items):
        if self._done:
            raise RuntimeError("Cannot add to a finished shuffling buffer")
        items = list(items)
        if len(self._items) + len(items) > self._hard_capacity:
            raise RuntimeError(
                f"Shuffling buffer overflow: {len(self._items)} + {len(items)} "
                f"> hard capacity {self._hard_capacity}. Producers must check "
                f"can_add() between row groups."
            )
        self._items.extend(items)

    def retrieve(self):
        if not self.can_retrieve():
            raise RuntimeError("retrieve() called when can_retrieve() is False")
        index = self._random.randrange(len(self._items))
        self._items[index], self._items[-1] = self._items[-1], self._items[index]
        return self._items.pop()

    def can_add(self):
        return len(self._items) < self._capacity and not self._done

    def can_retrieve(self):
        if self._done:
            return len(self._items) > 0
        return len(self._items) > self._min_after_retrieve

    @property
    def size(self):
        return len(self._items)

    def finish(self):
        self._done = True
