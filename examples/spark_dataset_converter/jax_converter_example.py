"""DataFrame → JAX pipeline in one call via the dataset converter.

Reference analogue: ``examples/spark_dataset_converter/*_converter_example.py``
with the new JAX surface.
"""

import tempfile

import numpy as np
import pandas as pd

from petastorm_tpu.spark import make_spark_converter, set_parent_cache_dir_url


def main():
    with tempfile.TemporaryDirectory() as cache_dir:
        set_parent_cache_dir_url(f"file://{cache_dir}")
        df = pd.DataFrame({
            "features": np.random.rand(256).astype(np.float64),
            "label": np.random.randint(0, 2, 256),
        })
        converter = make_spark_converter(df)  # floats cast to float32
        print(f"materialized {len(converter)} rows at {converter.cache_dir_url}")
        with converter.make_jax_dataloader(batch_size=64, num_epochs=1) \
                as loader:
            for batch in loader:
                print("batch:", batch["features"].shape,
                      batch["features"].dtype)
        converter.delete()


if __name__ == "__main__":
    main()
