"""Driver-contract tests for ``__graft_entry__``.

The driver compile-checks ``entry()`` single-chip and runs
``dryrun_multichip(n)`` with N virtual CPU devices; these tests exercise both
under the test session's 8-device CPU mesh (tests/conftest.py).
"""

import jax
import pytest

import __graft_entry__


def test_entry_compiles_and_runs():
    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (8, 10)


def test_dryrun_multichip_in_process():
    # The test session already has 8 CPU devices, so this goes through the
    # in-process path (no subprocess).
    assert len(jax.devices()) >= 8
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_subprocess_bootstrap():
    # Force the subprocess path regardless of local device count — this is
    # the path the driver takes from its single-chip axon process.
    __graft_entry__._dryrun_multichip_subprocess(8)
