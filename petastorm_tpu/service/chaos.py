"""Chaos harness for the disaggregated data service.

Injects the control-plane failures the service claims to survive —
dispatcher kill/restart, worker SIGKILL-style death, connection drops — at
a configurable rate while a real topology serves a real epoch, so the
delivery invariants (no lost rows; no duplicates when only the control
plane is perturbed) are asserted against actual behavior instead of unit
mocks. The ``service`` benchmark scenario wires this in via ``--chaos``
(``docs/guides/service.md#failure-model-and-recovery``); the fault-injection
tests drive the same actions deterministically.

Each injected event is recorded as ``(elapsed_s, label)`` so a failing
invariant can be correlated with what the harness did when.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

from petastorm_tpu.telemetry.log import service_logger

logger = service_logger(__name__)

CHAOS_KINDS = ("dispatcher-restart", "worker-kill", "conn-drop",
               "cache-corrupt", "job-cancel", "worker-drain",
               "failpoints")


class ChaosInjector:
    """Run ``actions`` on a background thread — round-robin by default,
    or **seed-derived** (action choice AND inter-event interval jitter)
    when ``seed`` is given, so a timed chaos run is reproducible: the
    n-th injected event is the same action at the same nominal offset in
    every run of the same seed (wall-clock scheduling still jitters with
    the host, which is why the *failpoint* schedule — call-count-indexed
    — is the byte-replayable substrate; the seed here makes the coarse
    kinds replayable at the sequence level and lands the full injection
    record in the scenario's ``--json-out``).

    :param actions: list of ``(label, callable)`` — each callable injects
        one fault when invoked (and must tolerate being called while the
        topology is mid-recovery from the previous one).
    :param interval_s: nominal pause between injected events.
    :param initial_delay_s: pause before the first event (lets the epoch's
        streams start so the fault lands mid-flight, not at setup).
    :param max_events: stop injecting after this many events (``None`` =
        until :meth:`stop`).
    :param seed: derive the event sequence from this seed
        (``seedtree.fold_in`` — no hidden RNG state). ``None`` keeps the
        legacy fixed-interval round-robin.
    """

    def __init__(self, actions, interval_s=1.5, initial_delay_s=0.4,
                 max_events=None, seed=None):
        if not actions:
            raise ValueError("chaos needs at least one (label, action)")
        self._actions = list(actions)
        self._interval_s = interval_s
        self._initial_delay_s = initial_delay_s
        self._max_events = max_events
        self._seed = int(seed) if seed is not None else None
        self._stop = threading.Event()
        self._thread = None
        self._start_time = None
        self.events = []   # (elapsed_s, label) per injected fault
        self.errors = []   # (label, repr(exc)) — injection must not die

    def start(self):
        self._start_time = time.perf_counter()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="service-chaos")
        self._thread.start()
        return self

    def stop(self, timeout=30):
        """Signal and join. The join budget covers a worst-case in-flight
        ``dispatcher_restart_action`` (graceful stop ≈ up to ~10s on a
        wedged handler + downtime + start): callers tear nodes down AFTER
        this returns, so an action must not be left installing a fresh
        node behind the teardown's back."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                logger.error(
                    "chaos injector thread still alive after %.0fs stop "
                    "budget — a node it installs now may leak", timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()

    def _event_plan(self, count):
        """``(label, action, interval)`` for event ``count`` — seed-derived
        when a seed is armed (pure in ``(seed, count)``), else the legacy
        round-robin at the fixed interval."""
        if self._seed is None:
            label, action = self._actions[count % len(self._actions)]
            return label, action, self._interval_s
        from petastorm_tpu.service.seedtree import fold_in

        key = fold_in(self._seed, ("chaos-event", count))
        label, action = self._actions[key % len(self._actions)]
        # Interval jitter in [0.5, 1.5) × nominal, derived — not drawn.
        interval = self._interval_s * (
            0.5 + (fold_in(key, "interval") % 1000) / 1000.0)
        return label, action, interval

    def _run(self):
        if self._stop.wait(self._initial_delay_s):
            return
        count = 0
        while not self._stop.is_set():
            label, action, interval = self._event_plan(count)
            elapsed = time.perf_counter() - self._start_time
            logger.warning("chaos: injecting %s at t=%.2fs", label, elapsed)
            try:
                action()
                self.events.append((round(elapsed, 3), label))
            except Exception as exc:  # a failed injection must not kill
                logger.exception("chaos action %s failed", label)
                self.errors.append((label, repr(exc)))
            count += 1
            if self._max_events is not None and count >= self._max_events:
                return
            if self._stop.wait(interval):
                return


def dispatcher_restart_action(holder, dispatcher_factory, downtime_s=0.15):
    """Crash-and-restart the dispatcher in ``holder[0]``.

    The running dispatcher is stopped abruptly (no final snapshot — a
    crash), and after ``downtime_s`` a replacement built by
    ``dispatcher_factory(host, port)`` is started on the SAME address and
    placed back into ``holder`` (a one-element list, so the surrounding
    scenario's teardown always stops the current incumbent). Point the
    factory at the same ``journal_dir`` to exercise WAL replay — that is
    the configuration whose delivery invariant is zero lost AND zero
    duplicate rows.
    """
    def action():
        old = holder[0]
        host, port = old.address
        old.stop()
        time.sleep(downtime_s)
        holder[0] = dispatcher_factory(host, port).start()
    return action


def worker_kill_action(fleet, min_survivors=1):
    """Kill (SIGKILL-style: connections dropped mid-stream, no ``end``)
    the next live worker in ``fleet``, never dropping the live count below
    ``min_survivors`` — the delivery invariant under worker death is
    at-least-once (no loss; duplicates allowed)."""
    state = {"killed": set()}

    def action():
        alive = [w for w in fleet if id(w) not in state["killed"]]
        if len(alive) <= min_survivors:
            logger.warning("chaos: only %d worker(s) left — not killing",
                           len(alive))
            return
        victim = alive[0]
        state["killed"].add(id(victim))
        victim.kill()
    return action


def connection_drop_action(nodes_fn):
    """Drop every open connection on every node (dispatcher and/or
    workers) without stopping their servers — a transport blip; clients
    must reconnect and re-stream (at-least-once). ``nodes_fn`` is called
    per event so the action tracks replacements (a dispatcher-restart
    injection swaps the incumbent out from under a static list)."""
    def action():
        for node in nodes_fn():
            node.drop_connections()
    return action


def cache_corrupt_action(cache_dir):
    """Corrupt one disk-tier decoded-batch cache entry per injection —
    alternately truncating the file to half its length and bit-flipping a
    byte in its payload region (the two damage signatures a real disk /
    torn write produces). The worker's load path must detect either
    (magic / frame-length sum / payload crc32), count it in
    ``cache_corrupt_entries``, delete the entry, and degrade to a fresh
    decode — never serve bad bytes, never error the stream. Victim choice
    cycles a sorted listing with a counter (no RNG: the harness obeys the
    same determinism lint as the service)."""
    state = {"count": 0}

    def action():
        from petastorm_tpu.cache_impl.batch_cache import ENTRY_SUFFIX

        entries = sorted(
            os.path.join(cache_dir, name)
            for name in os.listdir(cache_dir)
            if name.endswith(ENTRY_SUFFIX))
        if not entries:
            logger.warning("chaos: no disk-tier entries under %s yet — "
                           "nothing to corrupt", cache_dir)
            return
        victim = entries[state["count"] % len(entries)]
        truncate = state["count"] % 2 == 0
        state["count"] += 1
        size = os.path.getsize(victim)
        with open(victim, "r+b") as f:
            if truncate or size < 2:
                f.truncate(size // 2)
                logger.warning("chaos: truncated cache entry %s (%d -> %d "
                               "bytes)", victim, size, size // 2)
            else:
                f.seek(size // 2)
                original = f.read(1)
                f.seek(size // 2)
                f.write(bytes([original[0] ^ 0x40]))
                logger.warning("chaos: bit-flipped cache entry %s at "
                               "offset %d", victim, size // 2)
    return action


def job_cancel_action(dispatcher_address_fn, weight=0.5):
    """Exercise one full job lifecycle per injection — register a
    sacrificial job, then immediately ``end_job`` it — against a live
    multi-tenant fleet. The isolation invariant under this kind: the
    surviving jobs' streams keep flowing untouched (a cancelled job's
    scoped fencing must never fence a peer), which the soak's per-job
    zero-loss/zero-dup and byte-determinism assertions certify.
    ``dispatcher_address_fn`` is called per event so the action tracks a
    restarted dispatcher."""
    state = {"count": 0}

    def action():
        from petastorm_tpu.service.fleet import end_job, register_job

        job = f"chaos-job-{state['count']}"
        state["count"] += 1
        address = dispatcher_address_fn()
        register_job(address, job, weight=weight)
        end_job(address, job)
    return action


def worker_drain_action(dispatcher_fn, min_serving=1):
    """Alternately drain a serving worker and re-admit it — the
    autoscaler's lifecycle exercised as a fault: a drain mid-epoch must
    hand the worker's queued backlog to serving peers exactly-once (the
    ordinary revoke→extend steal path) while its in-flight pieces finish
    at their watermarks. Never drains below ``min_serving``; victims
    cycle deterministically (sorted order, no RNG — the harness obeys
    the same determinism lint as the service). ``dispatcher_fn`` is
    called per event so the action tracks a restarted dispatcher."""
    state = {"drained": [], "count": 0}

    def action():
        dispatcher = dispatcher_fn()
        if state["drained"]:
            wid = state["drained"].pop(0)
            dispatcher.admit_worker(wid, reason="chaos re-admit")
            return
        signals = dispatcher.fleet_signals()
        serving = signals["serving"]
        if len(serving) <= min_serving:
            logger.warning("chaos: only %d serving worker(s) — not "
                           "draining", len(serving))
            return
        wid = serving[state["count"] % len(serving)]
        state["count"] += 1
        if dispatcher.drain_worker(wid, reason="chaos drain"):
            state["drained"].append(wid)
    return action


class StreamDigest:
    """Order-sensitive hash of a delivered batch stream.

    Byte-identity is the determinism contract's check: two runs (or a
    perturbed run vs a clean one, or a killed-and-resumed run's two
    halves) must produce the SAME digest, which multiset equality cannot
    certify. Each batch folds in every field's name, dtype, shape, and
    raw bytes, in sorted field order — any reordering, dropped row,
    duplicate, or flipped bit changes the digest.
    """

    def __init__(self):
        self._hash = hashlib.blake2b(digest_size=16)
        self.batches = 0

    def update(self, batch):
        import numpy as np

        for name in sorted(batch):
            arr = np.asarray(batch[name])
            self._hash.update(name.encode("utf-8"))
            self._hash.update(str(arr.dtype).encode("utf-8"))
            self._hash.update(repr(arr.shape).encode("utf-8"))
            if arr.dtype == object:
                # Ragged/string fields have no flat buffer: hash per
                # element (bytes stay bytes; everything else reprs),
                # length-prefixed — bare concatenation would let
                # boundary-shifted values ([b"ab", b"c"] vs [b"a", b"bc"])
                # collide, and this digest is the byte-identity check.
                for item in arr.ravel():
                    data = (item if isinstance(item, bytes)
                            else repr(item).encode("utf-8"))
                    self._hash.update(len(data).to_bytes(8, "big"))
                    self._hash.update(data)
            else:
                self._hash.update(np.ascontiguousarray(arr).tobytes())
        self.batches += 1
        return self

    def hexdigest(self):
        return self._hash.hexdigest()


def delivery_invariants(expected_ids, got_ids, allow_duplicates):
    """Check the chaos run's row-delivery invariants.

    :param expected_ids: the unique sample keys one clean epoch delivers.
    :param got_ids: every sample key the trainer actually received.
    :param allow_duplicates: ``True`` under data-plane faults (worker
        kill, connection drop — at-least-once re-delivery is the
        contract); ``False`` when only the control plane was perturbed
        (dispatcher restart with a journal must not repeat rows).
    :returns: ``{"lost_rows", "duplicate_rows", "ok"}``.
    """
    from collections import Counter

    expected = Counter(expected_ids)
    got = Counter(got_ids)
    lost = sum((expected - got).values())
    duplicates = sum((got - expected).values())
    return {
        "lost_rows": lost,
        "duplicate_rows": duplicates,
        "ok": lost == 0 and (allow_duplicates or duplicates == 0),
    }
