"""Joint model + input-pipeline checkpointing (orbax + reader state).

The reference has no checkpointable reader state at all (SURVEY.md §5
"Checkpoint / resume: absent for readers"); this framework added resumable
iteration (``Reader.state_dict`` / ``resume_state=``,
``JaxDataLoader.state_dict``). What was still the user's job is gluing that
to MODEL checkpointing so a preempted training job restores both halves
consistently — this module is that glue:

- model arrays (params / optimizer state — any pytree of jax/numpy arrays)
  go through ``orbax.checkpoint`` (async-capable, TPU-aware restore);
- the loader/reader input state (a small JSON-serializable dict) rides in
  the same checkpoint directory as a JSON file, captured BETWEEN steps from
  the training thread — the consistency point the resume machinery is
  specified against. Local-reader states resume at-least-once (buffered
  rows re-read); a ``ServiceBatchSource`` state is a v2 watermark snapshot,
  so a service-fed job resumes **exactly-once** — each mid-piece piece
  continues at its next batch — and with the dispatcher's ``shuffle_seed``
  plus ``ordered=True`` delivery the restored stream is bit-identical to
  the uninterrupted run from the checkpoint batch onward
  (``docs/guides/service.md#delivery-semantics``).

Crash safety is pointer-file based: each save writes a COMPLETE checkpoint
(arrays + input state + per-host commit markers) into a fresh versioned
subdirectory, then atomically publishes it by ``os.replace``-ing the
``CURRENT`` pointer file. A crash at ANY point leaves ``CURRENT`` aimed at
the last fully-committed version — there is no window in which the previous
good checkpoint is unrestorable. Superseded versions are pruned on the next
successful save.

On a pod every host checkpoints its OWN input state (shard identity is part
of it) while orbax handles the array layout; restore hands each host back
the state it saved (``input_state.<process_index>.json``) and refuses a
checkpoint whose host count differs from the restoring job's.
"""

from __future__ import annotations

import json
import os
import shutil

_INPUT_STATE_TMPL = "input_state.{}.json"
_COMMIT_MARKER_PREFIX = "COMMITTED."
_CURRENT_FILE = "CURRENT"
_VERSION_TMPL = "v{}"
_ARRAYS_DIR = "arrays"
_checkpointer = None


def _process_index():
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax missing/uninitialized
        return 0


def _process_count():
    try:
        import jax

        return jax.process_count()
    except Exception:  # pragma: no cover - jax missing/uninitialized
        return 1


def _get_checkpointer():
    """One orbax checkpointer per process: StandardCheckpointer owns async
    background resources, so constructing one per save would leak them."""
    global _checkpointer
    if _checkpointer is None:
        import orbax.checkpoint as ocp

        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def _barrier(name):
    """Cross-host barrier (no-op single-host): hosts must not race each
    other through the version-dir lifecycle on a shared filesystem."""
    if _process_count() > 1:  # pragma: no cover - single-host test env
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def _agree_version(next_num):
    """Process 0's version number, broadcast to every host. Each host reads
    CURRENT from the shared filesystem independently; with stale attribute
    caching (NFS) they can disagree — and since the barrier names embed the
    version string, a disagreement would make ``sync_global_devices`` hang
    on mismatched barrier names instead of failing cleanly. Agreeing the
    number via a device collective first makes the barrier names provably
    identical on every host."""
    if _process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils

        next_num = int(
            multihost_utils.broadcast_one_to_all(np.int64(next_num)))
    return next_num


def _is_version_name(name):
    """Strictly ``v<int>`` — the only names this module creates; anything
    else in the directory belongs to the user and must never be pruned."""
    return name.startswith("v") and name[1:].isdigit()


def _read_current(directory):
    """Version name ``CURRENT`` points at, or ``None`` if unpublished."""
    try:
        with open(os.path.join(directory, _CURRENT_FILE)) as f:
            name = f.read().strip()
        return name or None
    except OSError:
        return None


def save_training_state(directory, arrays, loader=None, input_state=None,
                        force=True):
    """Write ``arrays`` (pytree) + the input-pipeline state under
    ``directory``.

    :param arrays: pytree of params / optimizer state (jax or numpy arrays).
    :param loader: a :class:`~petastorm_tpu.jax_utils.loader.JaxDataLoader`
        to snapshot via its ``state_dict()`` (call between steps). Mutually
        exclusive with ``input_state``.
    :param input_state: a pre-captured reader/loader state dict.
    :param force: overwrite an existing checkpoint at ``directory``. The new
        checkpoint is fully written to a new versioned subdirectory before
        the ``CURRENT`` pointer moves, so the last good checkpoint survives
        a crash at any point during the save.
    """
    if loader is not None and input_state is not None:
        raise ValueError("pass loader OR input_state, not both")
    if loader is not None:
        input_state = loader.state_dict()

    directory = os.path.abspath(directory)
    current = _read_current(directory)
    if current is not None and not force:
        # Refuse BEFORE touching anything — the existing checkpoint stays
        # fully restorable.
        raise ValueError(f"checkpoint already exists at {directory} "
                         "(pass force=True to overwrite)")
    os.makedirs(directory, exist_ok=True)
    try:
        next_num = int(current[1:]) + 1 if current else 1
    except ValueError:  # pragma: no cover - hand-edited CURRENT
        next_num = 1
    version = _VERSION_TMPL.format(_agree_version(next_num))
    vdir = os.path.join(directory, version)
    # Barrier: no host may clear/write the shared version dir while another
    # is still deciding the version (or finishing a previous save call).
    _barrier(f"petastorm_tpu_ckpt_enter:{version}")
    if _process_index() == 0:
        shutil.rmtree(vdir, ignore_errors=True)  # debris of a crashed save
    _barrier(f"petastorm_tpu_ckpt_clean:{version}")
    _write_checkpoint(vdir, arrays, input_state)
    # Barrier: every host's input state + commit marker must be on disk
    # before CURRENT moves — otherwise a crash right after publish leaves a
    # version that restore rejects as torn AND the old version pruned.
    _barrier(f"petastorm_tpu_ckpt_written:{version}")
    if _process_index() == 0:
        # Atomic publish: from here on, restore sees the NEW checkpoint;
        # any crash before this line left CURRENT on the previous good one.
        tmp = os.path.join(directory, _CURRENT_FILE + ".tmp")
        with open(tmp, "w") as f:
            f.write(version)
        os.replace(tmp, os.path.join(directory, _CURRENT_FILE))
        # Prune superseded/orphaned versions (best-effort; a crash here
        # only delays cleanup to the next save). Strictly v<int> names —
        # anything else in the directory is the user's.
        for name in os.listdir(directory):
            if (name != version and _is_version_name(name)
                    and os.path.isdir(os.path.join(directory, name))):
                shutil.rmtree(os.path.join(directory, name),
                              ignore_errors=True)
    # No host returns (and potentially starts the next save) before the
    # publish is visible everywhere.
    _barrier(f"petastorm_tpu_ckpt_published:{version}")
    return directory


def _write_checkpoint(directory, arrays, input_state):
    os.makedirs(directory, exist_ok=True)
    idx = _process_index()
    ckptr = _get_checkpointer()
    ckptr.save(os.path.join(directory, _ARRAYS_DIR), arrays, force=True)
    ckptr.wait_until_finished()
    if input_state is not None:
        path = os.path.join(directory, _INPUT_STATE_TMPL.format(idx))
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(input_state, f)
        os.replace(tmp, path)  # atomic publish
    # Commit marker goes last within the version: its presence certifies
    # arrays + input state were both fully written by this host.
    marker = os.path.join(directory, _COMMIT_MARKER_PREFIX + str(idx))
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        f.write("ok")
    os.replace(tmp, marker)


def restore_training_state(directory, abstract_arrays=None):
    """Restore ``(arrays, input_state)`` from ``directory``.

    :param abstract_arrays: optional pytree of ``jax.ShapeDtypeStruct`` (or
        concrete arrays) guiding orbax's typed/sharded restore; ``None``
        restores as saved.
    :return: ``(arrays, input_state_or_None)`` — pass the input state as
        ``resume_state=`` to the reader factory (or ``ServiceBatchSource``)
        feeding a fresh loader. Local readers re-read buffered-but-
        unyielded rows (at-least-once); a service source resumes at its
        per-piece watermarks (exactly-once — nothing re-delivered, nothing
        lost).
    :raises RuntimeError: if no published checkpoint exists, this host's
        commit marker is absent (torn save), or the checkpoint was saved by
        a different number of hosts than are restoring (the other hosts'
        reader positions would be silently dropped).
    """
    directory = os.path.abspath(directory)
    current = _read_current(directory)
    if current is None:
        raise RuntimeError(
            f"no published checkpoint at {directory} (missing/empty "
            f"{_CURRENT_FILE}): either nothing was saved here or every "
            "save crashed before completing")
    vdir = os.path.join(directory, current)
    idx = _process_index()
    if not os.path.exists(os.path.join(vdir,
                                       _COMMIT_MARKER_PREFIX + str(idx))):
        raise RuntimeError(
            f"checkpoint {current} at {directory} has no commit marker for "
            f"host {idx}: the save did not complete on this host (torn "
            "checkpoint) — restoring it could pair arrays with stale or "
            "missing input state")
    saved_hosts = len([n for n in os.listdir(vdir)
                       if n.startswith(_COMMIT_MARKER_PREFIX)
                       and not n.endswith(".tmp")])
    if saved_hosts != _process_count():
        raise RuntimeError(
            f"checkpoint {current} at {directory} was saved by "
            f"{saved_hosts} host(s) but {_process_count()} are restoring: "
            "the other hosts' input-pipeline positions would be silently "
            "dropped — restore with the same process count, or restore "
            "arrays only via orbax directly")
    ckptr = _get_checkpointer()
    arrays_path = os.path.join(vdir, _ARRAYS_DIR)
    if abstract_arrays is None:
        arrays = ckptr.restore(arrays_path)
    else:
        arrays = ckptr.restore(arrays_path, abstract_arrays)
    path = os.path.join(vdir, _INPUT_STATE_TMPL.format(idx))
    input_state = None
    if os.path.exists(path):
        with open(path) as f:
            input_state = json.load(f)
    return arrays, input_state
