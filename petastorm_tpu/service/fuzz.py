"""Chaos-schedule fuzzer: seeded failpoint soaks + reproducer shrinking.

The failpoint substrate (:mod:`petastorm_tpu.failpoints`) makes every fault
schedule a pure function of a seed — which turns robustness testing from a
flaky soak into a **fuzzer**: run the loopback service under K seeded
schedules, assert the delivery invariants (zero lost rows, zero duplicate
rows) and digest-determinism (two runs of one seed produce byte-identical
stream digests and identical injection logs) per seed, and when a seed
fails, SHRINK the schedule — re-run with subsets of the failpoint
vocabulary until only the points needed to reproduce the failure remain —
then print a one-line reproducer::

    python -m petastorm_tpu.benchmark scenario service \\
        --chaos failpoints --chaos-seed 17   # points: transport.send

``fuzz()`` takes an injectable ``run_fn(seed, points)`` so the shrinker is
unit-testable without sockets; the default runner is the real loopback
service scenario, sized small (the soak is slow-marked in tier-1's terms —
each seed is a full service epoch under fire). Each seed runs on a
dedicated ``failpoint-fuzz-*`` thread with a hard join timeout, so one
hung run fails its seed instead of hanging the whole soak (the tests'
conftest leak guard tracks the thread prefix).
"""

from __future__ import annotations

import threading

from petastorm_tpu.telemetry.log import service_logger

logger = service_logger(__name__)

#: Thread-name prefix for per-seed runs — tracked by the tests' resource
#: leak guard (a surviving fuzz thread means a hung, never-joined run).
FUZZ_THREAD_PREFIX = "failpoint-fuzz"

#: Hard per-seed wall budget: a run that neither finishes nor raises
#: inside it counts as a failure ("hung") and the soak moves on.
DEFAULT_RUN_TIMEOUT_S = 120.0


class FuzzFailure(AssertionError):
    """A seed's run violated an invariant (or hung). Carries the shrunk,
    seed-stamped reproducer in ``.report``."""

    def __init__(self, message, report):
        super().__init__(message)
        self.report = report


def default_run_fn(seed, points):
    """One loopback service epoch under the seeded failpoint schedule,
    restricted to ``points`` (``None`` = the full vocabulary). Raises on
    any delivery-invariant violation (the scenario's own contract) and
    returns the scenario result dict. Sized small: the soak multiplies
    this by ``2 × len(seeds)``."""
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    return service_loopback_scenario(
        rows=1536, days=8, workers=2, batch_size=64,
        chaos="failpoints", chaos_seed=seed, failpoint_points=points,
        # Narrow fire window, sized against the run's actual call counts.
        # With the data plane on the shm tier (the loopback default) the
        # TCP points see only control traffic — credits, piece reports,
        # dispatcher RPCs — the shm points count one check per ring-sent
        # batch, and the resilience points (``slow-peer``) one check per
        # worker batch send — so the geometry must yield enough batches
        # (24 here, the per-batch points' whole call budget) and control
        # round-trips (>24) that seeded indices in [4, 24) actually land;
        # a run whose counts never reach its indices fires nothing and
        # trips the scenario's fired-nothing guard.
        failpoint_window=24,
        # Fleet cache tier armed with a deterministic mid-stream drain:
        # this is what puts the ``cache-peer-gone`` (peer fetch/push/serve
        # paths) and ``handoff-torn`` (drain handoff shipping) points on
        # exercised code paths — without it their call counts stay at
        # zero and their fire windows are unreachable. Digest stays the
        # seeded contract: remote-warm, local-warm and cold fills serve
        # byte-identical batches, and the drain happens at a fixed
        # consumed-batch position, not a timer.
        cache="mem", fleet_cache=True, fleet_cache_drain_after=12,
        shuffle_seed=seed, ordered=True)


def hedged_run_fn(seed, points):
    """:func:`default_run_fn` with the resilience layer ARMED: hedged
    watermark re-serves on (threshold fitted from a short epoch, so the
    quantile is the median and the floor sits below the injected
    ``slow-peer`` stalls), breakers and retry budgets live on the client
    by default. The hedged soak's contract is strictly stronger than the
    plain one: hedges may launch, win, or lose differently run-to-run
    (they race wall-clock timing), yet the digest must stay byte-identical
    — exactly-once delivery is watermark-deduped, not schedule-lucky."""
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    return service_loopback_scenario(
        rows=1536, days=8, workers=2, batch_size=64,
        chaos="failpoints", chaos_seed=seed, failpoint_points=points,
        failpoint_window=24,
        # Stretch the generic delay action past the hedge floor so the
        # injected stalls are hedgeable, not just observable.
        failpoint_delay_s=0.3,
        hedging=True, hedge_floor_s=0.2, hedge_min_samples=6,
        hedge_quantile=0.5,
        shuffle_seed=seed, ordered=True)


def _run_guarded(run_fn, seed, points, timeout_s):
    """Run one seed on a named, join-bounded thread. Returns
    ``(result, error)`` — ``error`` is the exception repr, or a "hung"
    marker when the join timed out (the daemon thread is abandoned)."""
    box = {}

    def target():
        try:
            box["result"] = run_fn(seed, points)
        except BaseException as exc:  # noqa: BLE001 — the soak must go on
            box["error"] = f"{type(exc).__name__}: {exc}"

    thread = threading.Thread(
        target=target, daemon=True,
        name=f"{FUZZ_THREAD_PREFIX}-{seed}")
    thread.start()
    thread.join(timeout=timeout_s)
    if thread.is_alive():
        from petastorm_tpu import failpoints

        # The abandoned run may still hold the armed schedule: disarm so
        # the NEXT seed can arm (its own failure is already recorded).
        failpoints.disarm()
        return None, f"hung: run exceeded {timeout_s:.0f}s"
    return box.get("result"), box.get("error")


def shrink_points(run_fn, seed, points, timeout_s=DEFAULT_RUN_TIMEOUT_S):
    """Greedy ddmin-lite: drop one failpoint at a time while the failure
    still reproduces; the fixpoint is a (locally) minimal failing subset.
    Worst case O(n²) runs of ``run_fn`` — fine for a vocabulary of ~10
    points, and the reproducer it emits is what turns a fuzz hit into a
    targeted regression test."""
    points = list(points)
    changed = True
    while changed and len(points) > 1:
        changed = False
        for candidate in list(points):
            trial = [p for p in points if p != candidate]
            _, error = _run_guarded(run_fn, seed, trial, timeout_s)
            if error is not None and error.startswith("hung"):
                # The abandoned thread still holds a live topology and
                # may arm/fire/disarm the process-global schedule under
                # later trials — further shrinking would race it and
                # produce a nondeterministic (wrong) minimal set. Stop
                # here with what we have.
                logger.error(
                    "fuzz shrink aborted: a trial hung past %.0fs — "
                    "returning the current %d-point set un-shrunk",
                    timeout_s, len(points))
                return points
            if error is not None:
                points = trial
                changed = True
                logger.warning(
                    "fuzz shrink: still fails without %r — %d point(s) "
                    "remain", candidate, len(points))
                break
    return points


def reproducer_command(seed, points):
    """The EXACT command that replays a failing fuzz run: the seed, the
    (possibly shrunk) point set, and every geometry knob
    :func:`default_run_fn` used — a reproducer that ran under a different
    window, dataset size, or vocabulary would have a different
    call-count/fire profile and not replay the bug."""
    return ("python -m petastorm_tpu.benchmark scenario service "
            f"--chaos failpoints --chaos-seed {seed} "
            f"--failpoint-points {','.join(points)} "
            "--failpoint-window 24 --rows 1536 --days 8 --workers 2 "
            "--batch-size 64 --cache mem --fleet-cache "
            f"--fleet-cache-drain-after 12 --shuffle-seed {seed} "
            "--ordered")


def fuzz(seeds, run_fn=None, shrink=True, check_determinism=True,
         compare_logs=False, timeout_s=DEFAULT_RUN_TIMEOUT_S):
    """Run the soak: every seed in ``seeds`` once (twice with
    ``check_determinism`` — the second run must produce the identical
    ``stream_digest``). Returns the report dict on an all-green soak;
    raises :class:`FuzzFailure` carrying the shrunk, seed-stamped
    reproducer on the first failing seed.

    :param run_fn: ``(seed, points) -> result dict`` (``points=None`` =
        full vocabulary); raises on invariant violation. Defaults to the
        real loopback service scenario.
    :param compare_logs: additionally require the two runs' injection
        logs to match (sorted). Off by default in the soak: a fire index
        sitting exactly at a point's run-to-run call-count boundary
        (wall-clock-paced heartbeats move totals by ±a few) can
        legitimately fire in one run and not the other without any
        determinism bug — the *digest* is the contract; the pinned
        replay test compares logs under a seed chosen away from such
        boundaries.
    """
    from petastorm_tpu.failpoints import POINTS

    run_fn = run_fn if run_fn is not None else default_run_fn
    report = {"seeds": [int(s) for s in seeds], "runs": 0, "failures": []}
    for seed in report["seeds"]:
        result, error = _run_guarded(run_fn, seed, None, timeout_s)
        report["runs"] += 1
        if error is None and check_determinism:
            replay, error = _run_guarded(run_fn, seed, None, timeout_s)
            report["runs"] += 1
            if error is None and isinstance(result, dict) \
                    and isinstance(replay, dict):
                if replay.get("stream_digest") \
                        != result.get("stream_digest"):
                    error = ("digest-determinism violated: two runs of "
                             f"seed {seed} produced digests "
                             f"{result.get('stream_digest')} vs "
                             f"{replay.get('stream_digest')}")
                elif compare_logs and (
                        sorted(map(tuple,
                                   replay.get("failpoint_injections")
                                   or []))
                        != sorted(map(
                            tuple,
                            result.get("failpoint_injections") or []))):
                    error = ("injection-log determinism violated for "
                             f"seed {seed}")
        if error is None:
            logger.info("fuzz: seed %d green", seed)
            continue
        points = sorted(POINTS)
        if shrink and not error.startswith("hung"):
            # A hung run's abandoned thread still drives the process-
            # global schedule (it will arm-race and disarm it whenever it
            # finally unblocks) — shrink trials after it would be
            # nondeterministic, so a hang reports the full set.
            points = shrink_points(run_fn, seed, points,
                                   timeout_s=timeout_s)
        failure = {"seed": seed, "error": error, "points": points,
                   "reproducer": reproducer_command(seed, points)}
        # Attach the flight recorder's ring (telemetry/flight.py): the
        # scenario's structured event log right up to the violation —
        # what the fleet was DOING when the shrunk reproducer fails,
        # correlated by fencing epoch + batch id.
        from petastorm_tpu.telemetry.flight import RECORDER

        failure["flight_dump"] = RECORDER.dump(
            f"fuzz-seed-{seed}")
        report["failures"].append(failure)
        logger.error("FUZZ REPRODUCER: %s (%s)", failure["reproducer"],
                     error)
        raise FuzzFailure(
            f"fuzz seed {seed} failed ({error}); shrunk reproducer: "
            f"{failure['reproducer']}", report)
    return report
