"""Row decode loop + small shared helpers.

Reference parity: ``petastorm/utils.py`` (``decode_row``, ``DecodeFieldError``;
``add_to_dataset_metadata`` lives in ``petastorm_tpu/etl/metadata.py`` because
the metadata engine here is pyarrow-native).
"""

from __future__ import annotations

import numpy as np


class DecodeFieldError(RuntimeError):
    pass


def decode_table(table, schema):
    """Columnar decode of a whole ``pa.Table`` into a list of row dicts.

    Same result as ``[decode_row(r, schema) for r in table.to_pylist()]`` but
    decodes column-at-a-time: numeric scalar columns convert through one
    ``to_numpy`` call (C loop) instead of per-cell ``np.dtype(...).type(v)``,
    and only one dict per row is built. This is the no-predicate hot path of
    ``PyDictReaderWorker`` (reference hot-loop analysis: SURVEY.md §3.2).
    """
    names, cols = [], []
    for name in table.column_names:
        field = schema.fields.get(name)
        if field is None:
            continue
        names.append(name)
        cols.append(_decode_column(table.column(name), field))
    if not names:
        return []
    return [dict(zip(names, vals)) for vals in zip(*cols)]


def _decode_column(col, field):
    from petastorm_tpu.schema.codecs import ScalarCodec

    try:
        if field.codec is not None:
            if isinstance(field.codec, ScalarCodec):
                fast = _fast_numeric_column(col, field)
                if fast is not None:
                    return fast
            if not col.null_count:
                # Columnar kernel: one ``decode_column`` call over the raw
                # cells (imdecode/frombuffer into a preallocated [N, ...]
                # block) instead of a python ``decode`` per row. Columns
                # WITH nulls keep the per-cell loop — ``to_numpy`` has no
                # None representation for them.
                cells = col.to_numpy(zero_copy_only=False)
                return list(field.codec.decode_column(field, cells))
            decode = field.codec.decode
            return [None if v is None else decode(field, v)
                    for v in col.to_pylist()]
        if field.shape:
            dtype = np.dtype(field.numpy_dtype)
            return [None if v is None else np.asarray(v, dtype=dtype)
                    for v in col.to_pylist()]
        fast = _fast_numeric_column(col, field)
        if fast is not None:
            return fast
        codec = ScalarCodec()
        return [None if v is None else codec.decode(field, v)
                for v in col.to_pylist()]
    except Exception as exc:
        raise DecodeFieldError(
            f"Decoding field {field.name!r} failed: {exc}") from exc


def _fast_numeric_column(col, field):
    """Whole-column numeric conversion; None when the dtype needs the
    per-cell path (strings, Decimal, datetime, nulls present)."""
    try:
        dtype = np.dtype(field.numpy_dtype)  # Decimal etc. raise TypeError
    except TypeError:
        return None
    if dtype.kind not in "biuf" or col.null_count:
        return None
    arr = col.to_numpy(zero_copy_only=False).astype(dtype, copy=False)
    return list(arr)


def decode_row(row, schema):
    """Decode all fields of one storage-row dict into numpy-land values.

    Reference parity: ``petastorm/utils.py::decode_row``. Fields with a codec
    are decoded by it; codec-less tensor fields (plain-Parquet list columns)
    are converted to ndarrays; scalars pass through with dtype normalization.
    """
    decoded_row = {}
    for field_name, value in row.items():
        field = schema.fields.get(field_name)
        if field is None:
            continue
        try:
            if value is None:
                decoded_row[field_name] = None
            elif field.codec is not None:
                decoded_row[field_name] = field.codec.decode(field, value)
            elif field.shape:
                decoded_row[field_name] = np.asarray(
                    value, dtype=np.dtype(field.numpy_dtype)
                )
            else:
                from petastorm_tpu.schema.codecs import ScalarCodec

                decoded_row[field_name] = ScalarCodec().decode(field, value)
        except Exception as exc:
            raise DecodeFieldError(
                f"Decoding field {field_name!r} failed: {exc}"
            ) from exc
    return decoded_row


def resize_bounded_queue(q, maxsize):
    """Live-resize a ``queue.Queue``'s bound (the pipeline autotuner's
    prefetch/ready-queue knobs — ``docs/guides/pipeline.md``): waiters
    blocked on the old bound are woken so a raise takes effect
    immediately; a shrink lets the queue drain down to the new bound
    (``put`` re-checks ``maxsize`` under the mutex on every attempt, so
    nothing is dropped). Reaches into ``queue.Queue`` internals
    (``mutex``/``not_full`` share one lock by contract) — keep every
    caller on THIS helper."""
    with q.mutex:
        q.maxsize = int(maxsize)
        q.not_full.notify_all()


def retry_with_backoff(fn, retries=3, base_delay=0.1, max_delay=5.0,
                       jitter=0.5, retry_on=(Exception,), no_retry_on=(),
                       description=None, sleep=None, rng=None,
                       deadline_s=None, clock=None, budget=None):
    """Call ``fn()`` with bounded retries, exponential backoff and jitter.

    The shared transient-failure policy for network-facing control paths:
    the GCS listing sweep (one flaky ``objects.list`` page must not abort
    reader construction for a whole pod) and every control RPC of the data
    service (dispatcher requests, worker registration, heartbeats, stream
    reconnects) route through here so the backoff shape AND the total
    time budget are tuned in one place instead of ad-hoc per-call timeouts.

    :param retries: additional attempts after the first (``retries=3`` ⇒ up
        to 4 calls). The final failure re-raises the original exception.
    :param base_delay: delay before the first retry; doubles per attempt.
    :param max_delay: cap on the exponential delay (pre-jitter).
    :param jitter: each delay is scaled by ``1 + uniform(0, jitter)`` so a
        pod's worth of hosts retrying the same outage don't re-stampede in
        lockstep.
    :param retry_on: exception types worth retrying (transient).
    :param no_retry_on: exception types that fail immediately even when they
        match ``retry_on`` (e.g. ``FileNotFoundError`` — a missing dataset
        never becomes present by waiting).
    :param description: label for the retry warning log line.
    :param sleep: injection point for tests (default ``time.sleep``).
    :param rng: injection point for tests (default module-level ``random``).
    :param deadline_s: total time budget across all attempts AND backoff
        sleeps, measured from the first call. Once sleeping for the next
        retry would cross the budget, the last exception is re-raised even
        if ``retries`` remain — a caller-facing bound on worst-case latency
        that per-attempt socket timeouts alone cannot give.
    :param clock: injection point for tests (default ``time.monotonic``).
    :param budget: optional per-peer
        :class:`petastorm_tpu.service.resilience.RetryBudget`: each retry
        spends one token (an empty bucket stops retrying even when
        ``retries`` remain — a degraded peer gets a bounded retry RATE,
        not a storm), and the eventual success refills it.
    """
    import logging
    import time

    sleep = sleep if sleep is not None else time.sleep
    clock = clock if clock is not None else time.monotonic
    start = clock()
    delays = backoff_delays(retries, base_delay, max_delay, jitter=jitter,
                            rng=rng)
    for attempt in range(retries + 1):
        try:
            result = fn()
            if budget is not None:
                budget.record_success()
            return result
        except no_retry_on:
            raise
        except retry_on as exc:
            if attempt == retries:
                raise
            delay = next(delays)
            if deadline_s is not None \
                    and clock() - start + delay >= deadline_s:
                logging.getLogger(__name__).warning(
                    "%s failed (attempt %d/%d): %s — deadline budget "
                    "%.2fs exhausted, not retrying",
                    description or getattr(fn, "__name__", "call"),
                    attempt + 1, retries + 1, exc, deadline_s)
                raise
            if budget is not None and not budget.try_spend():
                logging.getLogger(__name__).warning(
                    "%s failed (attempt %d/%d): %s — retry budget "
                    "exhausted, not retrying",
                    description or getattr(fn, "__name__", "call"),
                    attempt + 1, retries + 1, exc)
                raise
            logging.getLogger(__name__).warning(
                "%s failed (attempt %d/%d): %s — retrying in %.2fs",
                description or getattr(fn, "__name__", "call"),
                attempt + 1, retries + 1, exc, delay)
            sleep(delay)


def backoff_delays(retries, base_delay, max_delay, jitter=0.5, rng=None):
    """The delay schedule :func:`retry_with_backoff` sleeps on, as a
    generator — for call sites that cannot wrap the retried body in a
    closure (e.g. a generator that must keep yielding between attempts,
    like the service client's fcfs split streaming). One policy, two entry
    points."""
    import random

    rng = rng if rng is not None else random
    for attempt in range(retries):
        delay = min(max_delay, base_delay * (2 ** attempt))
        yield delay * (1.0 + jitter * rng.random())


def run_in_subprocess(func, *args, **kwargs):
    """Run ``func(*args, **kwargs)`` in a fresh child process and return its
    result.

    Reference parity: ``petastorm/utils.py::run_in_subprocess`` — used to
    isolate code that must not pollute the parent (e.g. libhdfs forks, CUDA
    context in the reference's world; on a TPU host, anything that would
    initialize a second JAX runtime). ``func`` must be picklable
    (module-level).
    """
    import multiprocessing

    ctx = multiprocessing.get_context("spawn")
    with ctx.Pool(1) as pool:
        return pool.apply(func, args, kwargs)
