"""Row decode loop + small shared helpers.

Reference parity: ``petastorm/utils.py`` (``decode_row``, ``DecodeFieldError``;
``add_to_dataset_metadata`` lives in ``petastorm_tpu/etl/metadata.py`` because
the metadata engine here is pyarrow-native).
"""

from __future__ import annotations

import numpy as np


class DecodeFieldError(RuntimeError):
    pass


def decode_row(row, schema):
    """Decode all fields of one storage-row dict into numpy-land values.

    Reference parity: ``petastorm/utils.py::decode_row``. Fields with a codec
    are decoded by it; codec-less tensor fields (plain-Parquet list columns)
    are converted to ndarrays; scalars pass through with dtype normalization.
    """
    decoded_row = {}
    for field_name, value in row.items():
        field = schema.fields.get(field_name)
        if field is None:
            continue
        try:
            if value is None:
                decoded_row[field_name] = None
            elif field.codec is not None:
                decoded_row[field_name] = field.codec.decode(field, value)
            elif field.shape:
                decoded_row[field_name] = np.asarray(
                    value, dtype=np.dtype(field.numpy_dtype)
                )
            else:
                from petastorm_tpu.schema.codecs import ScalarCodec

                decoded_row[field_name] = ScalarCodec().decode(field, value)
        except Exception as exc:
            raise DecodeFieldError(
                f"Decoding field {field_name!r} failed: {exc}"
            ) from exc
    return decoded_row
