"""Pickle payload serializer for the process pool's zmq transport.

Reference parity: ``petastorm/reader_impl/pickle_serializer.py``.
"""

from __future__ import annotations

import pickle


class PickleSerializer:
    def serialize(self, rows):
        return pickle.dumps(rows, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, serialized_rows):
        return pickle.loads(serialized_rows)  # noqa: S301 - host-local IPC from our own workers
