"""Benchmark library/CLI + metadata/copy CLI tests.

Reference analogue: ``petastorm/tests/{test_copy_dataset,test_generate_metadata}``.
"""

import numpy as np
import pytest

from petastorm_tpu import make_reader
from petastorm_tpu.benchmark.throughput import reader_throughput
from petastorm_tpu.errors import PetastormMetadataError


def test_reader_throughput_python(petastorm_dataset):
    result = reader_throughput(petastorm_dataset.url, pool_type="dummy",
                               warmup_cycles_count=5, measure_cycles_count=20)
    assert result.rows_per_second > 0
    assert result.rows_count == 20
    assert result.input_stall_pct is None


def test_reader_throughput_jax_loader(scalar_dataset):
    result = reader_throughput(scalar_dataset.url, pool_type="dummy",
                               read_method="arrow",
                               warmup_cycles_count=1, measure_cycles_count=2,
                               apply_jax_loader=True, jax_batch_size=5)
    assert result.rows_per_second > 0
    # Regression: the stall pct was read while the loader generator was still
    # suspended (its finally block never ran) and always reported 0.0. The
    # consumer always waits a nonzero time on the host queue, so a real
    # measurement is strictly positive.
    assert result.input_stall_pct is not None
    assert result.input_stall_pct > 0.0


def test_benchmark_cli(petastorm_dataset, capsys):
    from petastorm_tpu.benchmark.cli import main

    assert main([petastorm_dataset.url, "-p", "dummy", "-w", "2",
                 "-m", "10"]) == 0
    out = capsys.readouterr().out
    assert "rows/sec" in out


def test_generate_metadata_restores_deleted_metadata(tmp_path):
    from petastorm_tpu.etl.petastorm_generate_metadata import (
        generate_petastorm_metadata,
    )
    from petastorm_tpu.test_util.dataset_factory import create_test_dataset

    path = tmp_path / "regen_ds"
    url = f"file://{path}"
    create_test_dataset(url, rows_count=20, rows_per_row_group=10)
    (path / "_common_metadata").unlink()
    with pytest.raises((RuntimeError, PetastormMetadataError)):
        make_reader(url, reader_pool_type="dummy")
    # schema inference can't reconstruct codecs, so name the schema class
    generate_petastorm_metadata(
        url,
        unischema_class="petastorm_tpu.test_util.dataset_factory.TestSchema")
    with make_reader(url, reader_pool_type="dummy", num_epochs=1,
                     shuffle_row_groups=False) as reader:
        ids = sorted(row.id for row in reader)
    assert ids == list(range(20))


def test_generate_metadata_default_infer_path(tmp_path):
    # Regression: the no---unischema-class path passed the (schema, bool)
    # tuple from infer_or_load_unischema straight into materialize_dataset.
    from petastorm_tpu import make_batch_reader
    from petastorm_tpu.etl.petastorm_generate_metadata import (
        generate_petastorm_metadata,
    )
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    path = tmp_path / "infer_ds"
    url = f"file://{path}"
    create_test_scalar_dataset(url, rows_count=12, rows_per_row_group=4)
    generate_petastorm_metadata(url)  # infer from the arrow schema
    assert (path / "_common_metadata").exists()
    with make_batch_reader(url, reader_pool_type="dummy", num_epochs=1,
                           shuffle_row_groups=False) as reader:
        ids = sorted(int(v) for b in reader for v in b.id)
    assert ids == list(range(12))


def test_metadata_util_cli(petastorm_dataset, capsys):
    from petastorm_tpu.etl.metadata_util import main

    assert main([petastorm_dataset.url, "--schema", "--index"]) == 0
    out = capsys.readouterr().out
    assert "Row groups: 3" in out
    assert "image_png" in out


def test_copy_dataset_subset_and_not_null(petastorm_dataset, tmp_path):
    from petastorm_tpu.tools.copy_dataset import copy_dataset

    target = f"file://{tmp_path / 'copied'}"
    copy_dataset(None, petastorm_dataset.url, target,
                 field_regex=["^id$", "^matrix.*$"],
                 not_null_fields=["matrix_nullable"],
                 rows_per_row_group=5)
    with make_reader(target, reader_pool_type="dummy", num_epochs=1,
                     shuffle_row_groups=False) as reader:
        rows = list(reader)
    # fixture nulls matrix_nullable on every 3rd row (i % 3 == 0)
    expected_ids = [i for i in range(30) if i % 3 != 0]
    assert sorted(r.id for r in rows) == expected_ids
    assert set(rows[0]._fields) == {"id", "matrix", "matrix_nullable"}
    assert rows[0].matrix.shape == (4, 8)


def test_copy_dataset_cli_refuses_nonempty_target(petastorm_dataset, tmp_path):
    from petastorm_tpu.tools.copy_dataset import copy_dataset

    target_dir = tmp_path / "occupied"
    target_dir.mkdir()
    (target_dir / "something.txt").write_text("x")
    with pytest.raises(ValueError, match="not empty"):
        copy_dataset(None, petastorm_dataset.url, f"file://{target_dir}")
