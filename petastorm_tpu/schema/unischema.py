"""Unischema: a tensor-aware schema over Parquet columns.

Reference parity: ``petastorm/unischema.py`` (``Unischema``, ``UnischemaField``,
``dict_to_spark_row``, ``insert_explicit_nulls``, ``Unischema.from_arrow_schema``,
``create_schema_view``, ``match_unischema_fields``) — see SURVEY.md §2.1.

Differences from the reference (TPU-first design):
- the canonical serialized form is JSON (safe), not a pickle — see
  ``petastorm_tpu/etl/metadata.py`` (``unischema_to_json`` /
  ``unischema_from_json``); reference pickled schemas are *read* via a
  restricted compat unpickler there so existing corpora load unchanged;
- conversion targets arrow schemas (the pyarrow ETL engine), with Spark
  StructType conversion provided only as an optional shim.
"""

from __future__ import annotations

import re
import sys
import warnings
from collections import OrderedDict, namedtuple
from decimal import Decimal

import numpy as np
import pyarrow as pa

from petastorm_tpu.schema.codecs import (
    ScalarCodec,
    numpy_to_arrow_type,
)


class UnischemaField(
    namedtuple("UnischemaField", ["name", "numpy_dtype", "shape", "codec", "nullable"])
):
    """A single field: name, numpy dtype, tensor shape, storage codec, nullability.

    ``shape`` is a tuple; ``None`` entries mean "any size in this dimension".
    ``codec=None`` means the field is stored natively (plain Parquet column).
    """

    __slots__ = ()

    def __new__(cls, name, numpy_dtype, shape=(), codec=None, nullable=False):
        if shape is None:
            shape = ()
        return super().__new__(cls, name, numpy_dtype, tuple(shape), codec, nullable)

    def __hash__(self):
        return hash((self.name, _dtype_token(self.numpy_dtype), self.shape, self.nullable))

    def __eq__(self, other):
        if not isinstance(other, UnischemaField):
            return NotImplemented
        return (
            self.name == other.name
            and _dtype_token(self.numpy_dtype) == _dtype_token(other.numpy_dtype)
            and self.shape == other.shape
            and self.codec == other.codec
            and self.nullable == other.nullable
        )

    def __ne__(self, other):
        result = self.__eq__(other)
        return result if result is NotImplemented else not result


def _dtype_token(numpy_dtype):
    """A hashable, comparable token for a field dtype (np dtype, Decimal, str, bytes)."""
    if numpy_dtype is Decimal:
        return "decimal"
    if numpy_dtype in (str, np.str_):
        return "str"
    if numpy_dtype in (bytes, np.bytes_):
        return "bytes"
    return np.dtype(numpy_dtype).str


class Unischema:
    """An ordered collection of :class:`UnischemaField`.

    Exposes each field as an attribute (``schema.field_name``), generates the
    namedtuple row type used by the reader, and converts to/from arrow schemas.
    """

    def __init__(self, name, fields):
        self._name = name
        self._fields = OrderedDict((f.name, f) for f in fields)
        for field in self._fields.values():
            if hasattr(self, field.name):
                raise ValueError(
                    f"Field name {field.name!r} conflicts with a Unischema attribute"
                )
            setattr(self, field.name, field)
        self._namedtuple = None

    @property
    def fields(self):
        return self._fields

    def __getstate__(self):
        # The memoized namedtuple class is dynamically generated and not
        # picklable; workers regenerate it lazily after unpickling.
        state = self.__dict__.copy()
        state["_namedtuple"] = None
        return state

    def _get_namedtuple(self):
        if self._namedtuple is None:
            self._namedtuple = namedtuple(
                _sanitize_identifier(self._name), list(self._fields.keys())
            )
        return self._namedtuple

    @property
    def field_names(self):
        """Field names in schema order (cached tuple — hot-path helper)."""
        names = getattr(self, "_field_names", None)
        if names is None:
            names = self._field_names = tuple(self._fields)
        return names

    def make_namedtuple(self, **kwargs):
        """Build a row namedtuple from per-field kwargs (missing nullable -> None)."""
        # map(dict.get, ...) runs the per-field loop in C — this is the
        # consumer-side hot path (one call per delivered row, §3.2).
        return self._get_namedtuple()(*map(kwargs.get, self.field_names))

    def make_namedtuples(self, row_dicts):
        """Batch variant of :meth:`make_namedtuple` (same missing-field→None
        rule); owns the fast form so the reader hot loop and single-row path
        can't drift apart."""
        nt = self._get_namedtuple()
        fields = self.field_names
        return [nt(*map(row.get, fields)) for row in row_dicts]

    def make_namedtuple_tf(self, *args, **kwargs):
        return self._get_namedtuple()(*args, **kwargs)

    def create_schema_view(self, fields):
        """A sub-schema. ``fields`` is a list of UnischemaField instances and/or
        field-name regex strings (full match, reference semantics)."""
        if not isinstance(fields, (list, tuple)):
            raise ValueError("fields must be a list of UnischemaField or regex strings")
        view_fields = []
        seen = set()
        for item in fields:
            if isinstance(item, UnischemaField):
                if item.name not in self._fields:
                    raise ValueError(
                        f"Field {item.name!r} does not belong to schema {self._name!r}"
                    )
                own = self._fields[item.name]
                if item != own:
                    warnings.warn(
                        f"Field {item.name!r} differs from the schema's definition "
                        f"(dtype/shape/codec/nullable mismatch); using the schema's field",
                        UserWarning,
                        stacklevel=2,
                    )
                matches = [own]
            elif isinstance(item, str):
                matches = match_unischema_fields(self, [item])
                if not matches:
                    raise ValueError(
                        f"Field regex {item!r} matched no fields of schema {self._name!r}"
                    )
            else:
                raise ValueError(f"Invalid field spec: {item!r}")
            for match in matches:
                if match.name not in seen:
                    seen.add(match.name)
                    view_fields.append(match)
        # preserve schema order
        ordered = [f for f in self._fields.values() if f.name in seen]
        return Unischema(f"{self._name}_view", ordered)

    def as_arrow_schema(self):
        """The *storage* arrow schema (codec-encoded columns are binary)."""
        arrow_fields = []
        for field in self._fields.values():
            arrow_fields.append(
                pa.field(field.name, _storage_arrow_type(field), nullable=field.nullable)
            )
        return pa.schema(arrow_fields)

    def as_spark_schema(self):  # pragma: no cover - pyspark absent in this build
        """API-parity shim: Spark StructType (requires pyspark)."""
        from petastorm_tpu.compat.spark_shim import unischema_as_spark_schema

        return unischema_as_spark_schema(self)

    @classmethod
    def from_arrow_schema(cls, arrow_schema_or_dataset, omit_unsupported_fields=False):
        """Infer a (codec-less) Unischema from an arrow schema — the
        ``make_batch_reader`` path for plain Parquet stores."""
        arrow_schema = arrow_schema_or_dataset
        if not isinstance(arrow_schema, pa.Schema):
            arrow_schema = arrow_schema_or_dataset.schema
            if not isinstance(arrow_schema, pa.Schema):  # pyarrow.dataset.Dataset
                arrow_schema = arrow_schema_or_dataset.schema.to_arrow_schema()
        fields = []
        for arrow_field in arrow_schema:
            try:
                numpy_dtype, shape = _arrow_to_numpy_dtype(arrow_field.type)
            except ValueError:
                if omit_unsupported_fields:
                    continue
                raise
            fields.append(
                UnischemaField(
                    arrow_field.name, numpy_dtype, shape, None, arrow_field.nullable
                )
            )
        return cls("inferred_schema", fields)

    def resolve_schema_view(self, schema_fields):
        """``schema_fields=None`` -> self; else a view (names/regexes/fields)."""
        if schema_fields is None:
            return self
        return self.create_schema_view(list(schema_fields))

    def __eq__(self, other):
        if not isinstance(other, Unischema):
            return NotImplemented
        return list(self._fields.values()) == list(other._fields.values())

    def __ne__(self, other):
        result = self.__eq__(other)
        return result if result is NotImplemented else not result

    def __hash__(self):
        return hash(tuple(self._fields.values()))

    def __repr__(self):
        lines = [f"{self._name}:"]
        for field in self._fields.values():
            lines.append(
                f"  {field.name}: {_dtype_token(field.numpy_dtype)} {field.shape} "
                f"codec={type(field.codec).__name__ if field.codec else None} "
                f"nullable={field.nullable}"
            )
        return "\n".join(lines)


def _sanitize_identifier(name):
    sanitized = re.sub(r"[^A-Za-z0-9_]", "_", name)
    if not sanitized or sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return sanitized


def _storage_arrow_type(field):
    codec = field.codec
    if codec is None:
        if field.shape:
            # codec-less tensor field: stored as a (nested) arrow list column
            inner = numpy_to_arrow_type(field.numpy_dtype)
            for _ in field.shape:
                inner = pa.list_(inner)
            return inner
        return numpy_to_arrow_type(field.numpy_dtype)
    if isinstance(codec, ScalarCodec):
        return codec.arrow_dtype_for_field(field)
    return codec.arrow_dtype()


def _arrow_to_numpy_dtype(arrow_type, depth=0):
    """arrow type -> (numpy dtype or str/bytes/Decimal class, shape tuple)."""
    if pa.types.is_list(arrow_type) or pa.types.is_large_list(arrow_type):
        inner_dtype, inner_shape = _arrow_to_numpy_dtype(arrow_type.value_type, depth + 1)
        return inner_dtype, (None,) + inner_shape
    if pa.types.is_decimal(arrow_type):
        return Decimal, ()
    if pa.types.is_string(arrow_type) or pa.types.is_large_string(arrow_type):
        return str, ()
    if pa.types.is_binary(arrow_type) or pa.types.is_large_binary(arrow_type):
        return bytes, ()
    if pa.types.is_timestamp(arrow_type):
        return np.dtype(f"datetime64[{arrow_type.unit}]"), ()
    if pa.types.is_date32(arrow_type):
        return np.dtype("datetime64[D]"), ()
    if pa.types.is_date64(arrow_type):
        return np.dtype("datetime64[ms]"), ()
    try:
        return np.dtype(arrow_type.to_pandas_dtype()), ()
    except (NotImplementedError, TypeError) as exc:
        raise ValueError(f"Unsupported arrow type: {arrow_type}") from exc


def match_unischema_fields(schema, field_regexes):
    """Return schema fields whose names fully match any of ``field_regexes``.

    Reference semantics (``petastorm/unischema.py::match_unischema_fields``):
    patterns are anchored full matches, not prefix matches.
    """
    if not field_regexes:
        return []
    compiled = [re.compile(pattern) for pattern in field_regexes]
    matched = []
    for field in schema.fields.values():
        if any(c.fullmatch(field.name) for c in compiled):
            matched.append(field)
    return matched


def insert_explicit_nulls(unischema, row_dict):
    """Insert ``None`` for missing nullable fields; raise on missing non-nullable.

    Reference parity: ``petastorm/unischema.py::insert_explicit_nulls``.
    """
    for field_name, field in unischema.fields.items():
        if field_name not in row_dict:
            if field.nullable:
                row_dict[field_name] = None
            else:
                raise ValueError(
                    f"Field {field_name!r} is not nullable but is missing from the row"
                )
    return row_dict


def encode_row(unischema, row_dict):
    """Encode one row dict into storage cells (our arrow-native analogue of the
    reference's ``dict_to_spark_row``): validates field names, applies codecs,
    inserts explicit nulls."""
    if not isinstance(row_dict, dict):
        raise TypeError(f"row must be a dict, got {type(row_dict)}")
    unknown = set(row_dict.keys()) - set(unischema.fields.keys())
    if unknown:
        raise ValueError(f"Unknown fields in row: {sorted(unknown)}")
    row_dict = dict(row_dict)  # never mutate the caller's dict
    insert_explicit_nulls(unischema, row_dict)
    encoded = {}
    for name, field in unischema.fields.items():
        value = row_dict[name]
        if value is None:
            if not field.nullable:
                raise ValueError(f"Field {name!r} is not nullable but got None")
            encoded[name] = None
        elif field.codec is not None:
            encoded[name] = field.codec.encode(field, value)
        else:
            encoded[name] = _encode_codecless(field, value)
    return encoded


def _encode_codecless(field, value):
    if field.shape:
        arr = np.asarray(value, dtype=np.dtype(field.numpy_dtype))
        return arr.tolist()
    return ScalarCodec().encode(field, value)


def dict_to_spark_row(unischema, row_dict):  # pragma: no cover - pyspark absent
    """API-parity shim for the reference's Spark write path (requires pyspark)."""
    from petastorm_tpu.compat.spark_shim import dict_to_spark_row as _impl

    return _impl(unischema, row_dict)
