"""Seed-tree deterministic shuffling for the data service.

The reproducibility contract (ROADMAP: end-to-end deterministic pipelines;
PAPERS.md 2604.21275) demands a shuffle order that is a pure function of
``(seed, epoch, piece identity)`` and of NOTHING else — not the worker
count, not steal history, not which worker joined when, not whether the run
was killed and resumed. The classic ``rng.shuffle(pieces)`` fails that the
moment the piece list is sharded differently (a permutation of N elements
says nothing about a permutation of a subset), so the service derives order
the way ``jax.random.fold_in`` derives keys: every piece gets its own key by
folding the piece identity into an ``(seed, epoch)`` node of a seed tree,
and the epoch's order is simply the pieces sorted by their keys. Any subset
of pieces — a client shard, a worker deque, the survivors of a takeover —
sorts into the same RELATIVE order, which is what makes the delivered
stream byte-identical across fleet shapes and failures.

Pure stdlib (blake2b), no RNG state, no global seeding — every function is
referentially transparent, so two processes (dispatcher and client) agree
without coordination.
"""

from __future__ import annotations

import hashlib

_KEY_BYTES = 8
_KEY_MASK = (1 << (8 * _KEY_BYTES)) - 1


def fold_in(key, data):
    """Derive a child key from ``key`` and ``data`` — the seed-tree split.

    Deterministic across processes and Python versions (no ``hash()``):
    the child is the first 8 bytes of ``blake2b(key_bytes || repr(data))``.
    ``data`` may be any object with a stable ``repr`` (ints, strings,
    tuples of those). ``key`` is reduced mod 2**64 first — the function
    must be total: a negative or oversized ``--shuffle-seed`` reaching a
    request handler must derive an order, not crash the control plane.
    """
    h = hashlib.blake2b(digest_size=_KEY_BYTES)
    h.update((int(key) & _KEY_MASK).to_bytes(_KEY_BYTES, "big",
                                             signed=False))
    h.update(repr(data).encode("utf-8"))
    return int.from_bytes(h.digest(), "big")


def piece_key(seed, epoch, piece):
    """The sort key of one piece in one epoch: ``fold_in(fold_in(seed,
    ("epoch", epoch)), ("piece", piece))`` — a per-piece leaf of the seed
    tree. Ties (astronomically unlikely) break by the piece identity
    itself, see :func:`piece_order`."""
    return fold_in(fold_in(int(seed), ("epoch", int(epoch))),
                   ("piece", int(piece)))


def piece_order(seed, epoch, pieces):
    """Deterministic epoch order of ``pieces``.

    ``seed=None`` means shuffling is off: the natural ascending order
    (itself deterministic). Otherwise pieces sort by their seed-tree keys.
    Subset-stable by construction: ``piece_order(s, e, subset)`` is the
    restriction of ``piece_order(s, e, universe)`` to ``subset`` — the
    property that makes the order invariant to sharding, steals, and
    takeovers.
    """
    pieces = [int(p) for p in pieces]
    if seed is None:
        return sorted(pieces)
    return sorted(pieces, key=lambda p: (piece_key(seed, epoch, p), p))


def permutation(key, n):
    """Deterministic permutation of ``range(n)`` derived from the seed-tree
    node ``key``: ordinal ``i`` sorts by ``fold_in(key, ("ordinal", i))``.
    Like :func:`piece_order` this is a pure function — every process that
    holds the same key replays the same permutation, which is what lets a
    cache serve one canonical batch sequence through a per-epoch order
    without storing the order anywhere."""
    return sorted(range(int(n)),
                  key=lambda i: (fold_in(key, ("ordinal", i)), i))


def batch_permutation(seed, epoch, piece, n):
    """Serve-time order of one piece's ``n`` cached/decoded batches in one
    epoch — the intra-piece analogue of :func:`piece_order`, keyed off the
    piece's own seed-tree leaf so the batch order reshuffles per epoch and
    per seed while the cached bytes stay canonical
    (``docs/guides/caching.md#shuffle-compatible-serving``). ``seed=None``
    is the identity (shuffling off). Deterministic in ``(seed, epoch,
    piece, n)`` and NOTHING else: a takeover, kill-resume, or warm-vs-cold
    re-serve of the same piece replays the same order, so per-piece batch
    watermarks index a stable permuted stream."""
    if seed is None:
        return list(range(int(n)))
    return permutation(piece_key(seed, epoch, piece), n)
