"""NGram: sliding time-windows over consecutive rows within a row group.

Reference parity: ``petastorm/ngram.py`` — SURVEY.md §2.1, §5. This is the
reference's long-sequence feature (multi-frame video/lidar assembly,
BASELINE.md config #4). Semantics preserved exactly:

- rows are sorted by ``timestamp_field`` *within* a row group; windows never
  span row groups (documented quirk — sequence length is bounded by row-group
  size);
- a window is rejected when any two consecutive timestamps differ by more
  than ``delta_threshold``;
- ``timestamp_overlap=False`` makes accepted windows share no timestamps
  (stride = window length instead of 1).

On the JAX path windows collate to ``[B, T, ...]`` arrays
(``petastorm_tpu/jax_utils/loader.py``), the shape sequence-parallel training
consumes.
"""

from __future__ import annotations

from petastorm_tpu.schema.unischema import Unischema, UnischemaField, match_unischema_fields


class NGram:
    """A window spec: ``fields`` maps relative offset → list of fields wanted
    at that offset (as :class:`UnischemaField` or name/regex strings)."""

    def __init__(self, fields, delta_threshold, timestamp_field,
                 timestamp_overlap=True):
        if not isinstance(fields, dict) or not fields:
            raise ValueError("fields must be a non-empty {offset: [field,...]} dict")
        for offset, field_list in fields.items():
            if not isinstance(offset, int):
                raise ValueError(f"Offsets must be ints, got {offset!r}")
            if not isinstance(field_list, (list, tuple)):
                raise ValueError(f"fields[{offset}] must be a list of fields")
        self._fields = {offset: list(field_list) for offset, field_list in fields.items()}
        self._delta_threshold = delta_threshold
        self._timestamp_field = timestamp_field
        self._timestamp_overlap = timestamp_overlap

    @property
    def fields(self):
        return self._fields

    @property
    def length(self):
        offsets = sorted(self._fields)
        return offsets[-1] - offsets[0] + 1

    @property
    def delta_threshold(self):
        return self._delta_threshold

    @property
    def timestamp_field(self):
        return self._timestamp_field

    @property
    def timestamp_overlap(self):
        return self._timestamp_overlap

    @property
    def timestamp_field_name(self):
        if isinstance(self._timestamp_field, UnischemaField):
            return self._timestamp_field.name
        return self._timestamp_field

    def resolve_regex_field_names(self, schema):
        """Expand any regex/name strings in the field lists against ``schema``
        (reference parity: regex resolution happens once the schema is known)."""
        resolved = {}
        for offset, field_list in self._fields.items():
            fields = []
            seen = set()
            for item in field_list:
                if isinstance(item, UnischemaField):
                    matches = [item]
                else:
                    matches = match_unischema_fields(schema, [item])
                    if not matches:
                        raise ValueError(
                            f"NGram field pattern {item!r} matched nothing at "
                            f"offset {offset}"
                        )
                for match in matches:
                    if match.name not in seen:
                        seen.add(match.name)
                        fields.append(match)
            resolved[offset] = fields
        self._fields = resolved

    def get_field_names_at_timestep(self, timestep):
        if timestep not in self._fields:
            return []
        return [f.name if isinstance(f, UnischemaField) else f
                for f in self._fields[timestep]]

    def get_field_names_at_all_timesteps(self):
        names = set()
        for timestep in self._fields:
            names.update(self.get_field_names_at_timestep(timestep))
        names.add(self.timestamp_field_name)
        return sorted(names)

    def get_schema_at_timestep(self, schema, timestep):
        """Schema view containing only the fields wanted at ``timestep``."""
        return schema.create_schema_view(
            [schema.fields[name] for name in self.get_field_names_at_timestep(timestep)
             if name in schema.fields]
        )

    def form_ngram(self, data, schema):
        """Assemble windows from one row group's decoded rows.

        ``data``: list of row dicts (each containing at least every field this
        NGram needs plus the timestamp field). Returns a list of
        ``{offset: row-dict}`` windows honoring delta_threshold and overlap.
        """
        ts_name = self.timestamp_field_name
        rows = sorted(data, key=lambda r: r[ts_name])
        offsets = sorted(self._fields)
        base_offset = offsets[0]
        window_len = self.length
        ngrams = []
        index = 0
        while index + window_len <= len(rows):
            window = rows[index:index + window_len]
            if self._window_ok(window, ts_name):
                ngram = {}
                for offset in offsets:
                    row = window[offset - base_offset]
                    wanted = self.get_field_names_at_timestep(offset)
                    ngram[offset] = {name: row[name] for name in wanted if name in row}
                ngrams.append(ngram)
                index += window_len if not self._timestamp_overlap else 1
            else:
                index += 1
        return ngrams

    def _window_ok(self, window, ts_name):
        if self._delta_threshold is None:
            return True
        for prev, cur in zip(window, window[1:]):
            if cur[ts_name] - prev[ts_name] > self._delta_threshold:
                return False
        return True

    def make_namedtuple(self, schema, ngram_as_dicts):
        """Convert a ``{offset: dict}`` window into ``{offset: namedtuple}``
        using per-timestep schema views (reference output shape)."""
        as_tuples = {}
        for offset, row in ngram_as_dicts.items():
            view = self.get_schema_at_timestep(schema, offset)
            as_tuples[offset] = view.make_namedtuple(**row)
        return as_tuples

    def get_schema_view(self, schema):
        """Flat schema view over the union of all fields this NGram touches
        (what the worker must read + decode)."""
        names = [n for n in self.get_field_names_at_all_timesteps()
                 if n in schema.fields]
        return schema.create_schema_view([schema.fields[n] for n in names])
