"""Process-wide metrics registry: counters, gauges, log-bucket histograms.

The unified substrate under every diagnostics surface in the repo
(``docs/guides/diagnostics.md#metrics-and-tracing``): producers — the reader
layer's pools and ventilators, the framed-socket transport, the service
dispatcher/worker/client, the JAX loader's stage timings — declare **typed,
named, label-aware metric families** here instead of ad-hoc snapshot-dict
entries, so the same numbers are simultaneously

- readable in-process (the legacy ``diagnostics`` dicts are re-derived from
  the same metric objects),
- scrapeable (Prometheus text exposition, :mod:`petastorm_tpu.telemetry.http`),
- and rate-able (a :class:`SnapshotRing` of periodic snapshots makes
  ``rate()``-style deltas — rows/s, evictions/min — computable without an
  external TSDB).

Design constraints, in order: (1) **zero hot-path cost when idle** — an
increment is one small-lock acquire and a float add, no allocation after the
child is interned; (2) stdlib only; (3) thread-safe everywhere — producers
increment from reader/stream/heartbeat threads while a scraper snapshots.

The process-default registry is :data:`REGISTRY`; all of the repo's metric
families are declared centrally in :mod:`petastorm_tpu.telemetry.metrics`.
"""

from __future__ import annotations

import math
import threading
import time


def log_buckets(lo=1e-5, hi=100.0, factor=4.0):
    """Fixed logarithmically-spaced bucket bounds: ``lo * factor**k`` up to
    (and including the first bound >=) ``hi``. The histogram default covers
    10 microseconds to ~2 minutes in 13 buckets — wide enough for decode
    times and stall waits alike, cheap enough to expose per label set."""
    bounds = []
    edge = lo
    while edge < hi:
        bounds.append(edge)
        edge *= factor
    bounds.append(edge)
    return tuple(bounds)


DEFAULT_TIME_BUCKETS = log_buckets()


class _Child:
    """One (family, label-values) time series. Interned per label set by the
    family, so producers hold a reference and pay no dict lookup per update."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0

    @property
    def value(self):
        with self._lock:
            return self._value


class CounterChild(_Child):
    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += amount


class GaugeChild(_Child):
    def set(self, value):
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        with self._lock:
            self._value -= amount


class HistogramChild:
    """Fixed-bucket histogram series: per-bucket counts + sum + count."""

    __slots__ = ("_lock", "_bounds", "_counts", "sum", "count")

    def __init__(self, lock, bounds):
        self._lock = lock
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value):
        value = float(value)
        with self._lock:
            # Linear scan: bucket lists are ~13 long and observations are
            # per-batch (hundreds/s), not per-row — bisect would save
            # nothing measurable and cost a function call.
            for i, bound in enumerate(self._bounds):
                if value <= bound:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
            self.sum += value
            self.count += 1

    def bucket_counts(self):
        """Per-bucket (non-cumulative) counts, +Inf last."""
        with self._lock:
            return list(self._counts)

    def quantile(self, q):
        """Approximate quantile by linear interpolation inside the bucket
        that crosses rank ``q * count`` (the same estimate Prometheus's
        ``histogram_quantile`` computes server-side). ``None`` when empty."""
        with self._lock:
            total = self.count
            if total == 0:
                return None
            rank = q * total
            seen = 0
            prev_bound = 0.0
            for i, bound in enumerate(self._bounds):
                in_bucket = self._counts[i]
                if seen + in_bucket >= rank:
                    if in_bucket == 0:
                        return bound
                    frac = (rank - seen) / in_bucket
                    return prev_bound + frac * (bound - prev_bound)
                seen += in_bucket
                prev_bound = bound
            return self._bounds[-1]  # rank fell in the +Inf bucket


_CHILD_TYPES = {"counter": CounterChild, "gauge": GaugeChild}


class MetricFamily:
    """A named metric with a fixed label schema; ``labels()`` interns one
    child per label-value tuple."""

    def __init__(self, name, help_text, kind, label_names=(), buckets=None):
        self.name = name
        self.help = help_text
        self.kind = kind
        self.label_names = tuple(label_names)
        self.buckets = (tuple(buckets) if buckets is not None
                        else DEFAULT_TIME_BUCKETS) if kind == "histogram" \
            else None
        self._lock = threading.Lock()
        self._children = {}

    def labels(self, *values, **kv):
        """The child for these label values (positional, in declared order,
        or by keyword). Label values are coerced to str — a worker_id or a
        stage name, never unbounded per-row data."""
        if kv:
            if values:
                raise ValueError("pass labels positionally or by keyword, "
                                 "not both")
            try:
                values = tuple(kv[name] for name in self.label_names)
            except KeyError as exc:
                raise ValueError(
                    f"{self.name} labels are {self.label_names}") from exc
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name} expects labels {self.label_names}, "
                f"got {values!r}")
        key = tuple(str(v) for v in values)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                if self.kind == "histogram":
                    child = HistogramChild(self._lock, self.buckets)
                else:
                    child = _CHILD_TYPES[self.kind](self._lock)
                self._children[key] = child
            return child

    def remove(self, *values):
        """Drop the series for these label values (e.g. a finalized
        per-instance label) — the series vanishes from exposition and
        snapshots, exactly like a restarted Prometheus target."""
        key = tuple(str(v) for v in values)
        with self._lock:
            self._children.pop(key, None)

    # Unlabeled convenience: family.inc()/set()/observe() act on the
    # zero-label child.
    def inc(self, amount=1.0):
        self.labels().inc(amount)

    def set(self, value):
        self.labels().set(value)

    def dec(self, amount=1.0):
        self.labels().dec(amount)

    def observe(self, value):
        self.labels().observe(value)

    @property
    def value(self):
        return self.labels().value

    def children(self):
        with self._lock:
            return dict(self._children)


class MetricsRegistry:
    """A set of metric families; declaration is idempotent (re-declaring the
    same name with the same type/labels returns the existing family — the
    pattern of module-level declarations surviving re-imports)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families = {}

    def _declare(self, name, help_text, kind, label_names, buckets=None):
        with self._lock:
            family = self._families.get(name)
            if family is not None:
                if family.kind != kind \
                        or family.label_names != tuple(label_names):
                    raise ValueError(
                        f"metric {name!r} already registered as a "
                        f"{family.kind} with labels {family.label_names}")
                return family
            family = MetricFamily(name, help_text, kind, label_names,
                                  buckets)
            self._families[name] = family
            return family

    def counter(self, name, help_text, labels=()):
        return self._declare(name, help_text, "counter", labels)

    def gauge(self, name, help_text, labels=()):
        return self._declare(name, help_text, "gauge", labels)

    def histogram(self, name, help_text, labels=(), buckets=None):
        return self._declare(name, help_text, "histogram", labels, buckets)

    def families(self):
        """Name → family, sorted by name (stable exposition order)."""
        with self._lock:
            return dict(sorted(self._families.items()))

    def snapshot(self):
        """Point-in-time value of every series, JSON-shaped::

            {family_name: {"type": ..., "help": ..., "series": [
                {"labels": {...}, "value": x}                    # counter/gauge
                {"labels": {...}, "sum": s, "count": n,
                 "buckets": [[le, cumulative_count], ...]}       # histogram
            ]}}
        """
        out = {}
        for name, family in self.families().items():
            series = []
            for key, child in sorted(family.children().items()):
                labels = dict(zip(family.label_names, key))
                if family.kind == "histogram":
                    counts = child.bucket_counts()
                    cumulative, buckets = 0, []
                    for bound, n in zip(family.buckets, counts):
                        cumulative += n
                        buckets.append([bound, cumulative])
                    buckets.append(["+Inf", cumulative + counts[-1]])
                    series.append({"labels": labels, "sum": child.sum,
                                   "count": child.count, "buckets": buckets})
                else:
                    series.append({"labels": labels, "value": child.value})
            out[name] = {"type": family.kind, "help": family.help,
                         "series": series}
        return out


# -- Prometheus text exposition ---------------------------------------------

def _escape_help(text):
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value):
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _format_value(value):
    if value == math.inf:
        return "+Inf"
    if value == -math.inf:
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _format_labels(label_names, key, extra=()):
    parts = [f'{name}="{_escape_label_value(value)}"'
             for name, value in sorted(zip(label_names, key))]
    parts.extend(f'{name}="{value}"' for name, value in extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def expose_prometheus(registry):
    """The registry in Prometheus text exposition format (version 0.0.4):
    ``# HELP`` / ``# TYPE`` per family (emitted even for families with no
    series yet, so a scrape enumerates the full vocabulary), label values
    escaped, label names sorted, histogram buckets cumulative with a
    ``+Inf`` terminal plus ``_sum``/``_count``."""
    lines = []
    for name, family in registry.families().items():
        lines.append(f"# HELP {name} {_escape_help(family.help)}")
        lines.append(f"# TYPE {name} {family.kind}")
        for key, child in sorted(family.children().items()):
            if family.kind == "histogram":
                cumulative = 0
                counts = child.bucket_counts()
                for bound, n in zip(family.buckets, counts):
                    cumulative += n
                    labels = _format_labels(
                        family.label_names, key,
                        extra=[("le", _format_value(bound))])
                    lines.append(f"{name}_bucket{labels} {cumulative}")
                labels = _format_labels(family.label_names, key,
                                        extra=[("le", "+Inf")])
                lines.append(f"{name}_bucket{labels} "
                             f"{cumulative + counts[-1]}")
                base = _format_labels(family.label_names, key)
                lines.append(f"{name}_sum{base} "
                             f"{_format_value(child.sum)}")
                lines.append(f"{name}_count{base} {child.count}")
            else:
                labels = _format_labels(family.label_names, key)
                lines.append(f"{name}{labels} "
                             f"{_format_value(child.value)}")
    return "\n".join(lines) + "\n"


# -- rate()-style deltas ------------------------------------------------------

class SnapshotRing:
    """Bounded ring of periodic registry snapshots — in-process ``rate()``.

    A scraping Prometheus computes rates server-side; a bare trainer (or the
    ``service status --watch`` terminal view) has no TSDB, so the ring keeps
    the last ``capacity`` snapshots taken every ``interval_s`` on a daemon
    thread and :meth:`rate` answers "per-second delta over the last N
    seconds" from the two snapshots straddling the window."""

    def __init__(self, registry, interval_s=5.0, capacity=120):
        self._registry = registry
        self.interval_s = interval_s
        self._capacity = capacity
        self._snaps = []          # [(monotonic_t, snapshot), ...]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self.take()  # t0 baseline, so rates are available after one tick
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="telemetry-snapshot-ring")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.take()

    def take(self):
        snap = (time.monotonic(), self._registry.snapshot())
        with self._lock:
            self._snaps.append(snap)
            if len(self._snaps) > self._capacity:
                self._snaps.pop(0)

    def snapshots(self):
        with self._lock:
            return list(self._snaps)

    @staticmethod
    def _series_value(snapshot, name, labels):
        family = snapshot.get(name)
        if family is None:
            return None  # family unknown to this snapshot's registry
        want = {k: str(v) for k, v in (labels or {}).items()}
        # A declared family with no matching series yet is 0, not None: a
        # counter that first appears mid-window must rate from zero.
        total = 0.0
        for series in family["series"]:
            if all(series["labels"].get(k) == v for k, v in want.items()):
                total += series.get("value", series.get("sum", 0.0))
        return total

    def rate(self, name, labels=None, window_s=None):
        """Per-second delta of a counter (or histogram sum) over the last
        ``window_s`` seconds (default: the full ring). Series matching
        ``labels`` (a subset filter) are summed before differencing.
        ``None`` when fewer than two snapshots cover the series."""
        snaps = self.snapshots()
        if len(snaps) < 2:
            return None
        t1, newest = snaps[-1]
        t0, oldest = snaps[0]
        if window_s is not None:
            for t, snap in snaps[:-1]:
                if t1 - t <= window_s:
                    t0, oldest = t, snap
                    break
        if t1 <= t0:
            return None
        new = self._series_value(newest, name, labels)
        old = self._series_value(oldest, name, labels)
        if new is None or old is None:
            return None
        # Counters only move up within one process lifetime; a NEGATIVE
        # delta means the producer restarted and its counter reset to
        # zero mid-window. Clamp instead of reporting a negative fleet
        # rate in `status --watch` — the restart window's rate is
        # unknowable, and 0 is the honest floor.
        return max(0.0, new - old) / (t1 - t0)


#: The process-default registry every family in
#: :mod:`petastorm_tpu.telemetry.metrics` registers into.
REGISTRY = MetricsRegistry()
