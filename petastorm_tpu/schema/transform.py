"""TransformSpec: user transforms applied inside workers (in parallel).

Reference parity: ``petastorm/transform.py`` (``TransformSpec``,
``transform_schema``) — see SURVEY.md §2.1. The ``func`` operates on a row
dict (``make_reader`` path) or a pandas DataFrame (``make_batch_reader``
path); ``edit_fields``/``removed_fields`` describe the schema delta so
downstream adapters see post-transform dtypes/shapes.
"""

from __future__ import annotations

from petastorm_tpu.schema.unischema import Unischema, UnischemaField


class TransformSpec:
    def __init__(self, func=None, edit_fields=None, removed_fields=None, selected_fields=None):
        self.func = func
        self.edit_fields = list(edit_fields or [])
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None

        if self.selected_fields is not None and self.removed_fields:
            raise ValueError("Specify only one of selected_fields and removed_fields")

    def __eq__(self, other):
        return isinstance(other, TransformSpec) and self.__dict__ == other.__dict__

    def __repr__(self):
        # Deterministic (address-free): part of the persistent disk-cache key —
        # cached values are post-transform, so a changed transform must change
        # the key (same contract as PredicateBase reprs).
        from petastorm_tpu.predicates import _func_fingerprint

        func = _func_fingerprint(self.func) if self.func is not None else None
        return (f"TransformSpec({func}, edit={self.edit_fields!r}, "
                f"removed={self.removed_fields!r}, "
                f"selected={self.selected_fields!r})")


def _as_unischema_field(field_spec):
    if isinstance(field_spec, UnischemaField):
        return field_spec
    # reference accepts ('name', np_dtype, shape, nullable) tuples in edit_fields
    name, numpy_dtype, shape, nullable = field_spec
    return UnischemaField(name, numpy_dtype, shape, None, nullable)


def transform_schema(schema, transform_spec):
    """Apply a TransformSpec's schema delta to a Unischema.

    Reference parity: ``petastorm/transform.py::transform_schema``.
    """
    removed = set(transform_spec.removed_fields)
    edited = {f.name: f for f in (_as_unischema_field(e) for e in transform_spec.edit_fields)}

    fields = []
    for field in schema.fields.values():
        if field.name in removed:
            continue
        if field.name in edited:
            fields.append(edited.pop(field.name))
        else:
            fields.append(field)
    # brand-new fields appended in edit order
    fields.extend(edited.values())

    if transform_spec.selected_fields is not None:
        selected = set(transform_spec.selected_fields)
        unknown = selected - {f.name for f in fields}
        if unknown:
            raise ValueError(f"selected_fields not in post-transform schema: {sorted(unknown)}")
        fields = [f for f in fields if f.name in selected]

    return Unischema(f"transformed_{schema._name}", fields)
