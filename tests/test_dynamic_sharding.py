"""Dynamic sharding: work-stealing piece rebalancing + streaming engine.

Layers under test (docs/guides/service.md#sharding-modes):

- the pure work-stealing planner (``dispatcher.plan_steals``): drain and
  straggler triggers, midpoint convergence, stealable-only moves;
- the streaming piece engine (``service/piece_engine.py``): one reader
  pipeline per stream fed from a mutable queue — enqueue/revoke/finish
  semantics, lazy reader construction (a fully-warm stream builds none);
- dynamic mode end-to-end over loopback: same multiset as a local reader,
  steals away from a skewed worker shrink the epoch wall, multi-epoch
  streams, per-piece ``state_dict`` resume across a mid-epoch steal;
- the ISSUE acceptance numbers: with one of two workers skewed per batch,
  the dynamic epoch wall lands near the no-skew wall while static stays
  slow-worker-bound, with zero lost and zero duplicate rows;
- chaos runs (``worker-kill``, ``dispatcher-restart``, ``conn-drop``)
  under dynamic sharding keep the delivery invariants (slow).
"""

import time

import numpy as np
import pytest

from petastorm_tpu.service import BatchWorker, Dispatcher, ServiceBatchSource
from petastorm_tpu.service.dispatcher import plan_steals

pytestmark = pytest.mark.service


# ---------------------------------------------------------------------------
# work-stealing planner (pure)
# ---------------------------------------------------------------------------

def test_plan_steals_drained_worker_receives_from_most_backlogged():
    moves = plan_steals(
        pending={"w0": 6, "w1": 0, "w2": 2},
        stealable={"w0": [10, 11, 12, 13, 14], "w2": [20]},
        rates={})
    # w1 drained: pieces flow from w0 (most backlogged), tail first,
    # rebalancing toward the midpoint (6 vs 0 -> 3 moves).
    assert [(f, t) for _p, f, t in moves][:3] == [("w0", "w1")] * 3
    assert [p for p, _f, _t in moves][:3] == [14, 13, 12]


def test_plan_steals_straggler_rate_triggers_proactive_move():
    # Nobody drained, but w0 crawls at < half the fleet median while
    # holding stealable backlog: pieces move to a median-or-faster worker
    # with materially less backlog.
    moves = plan_steals(
        pending={"w0": 8, "w1": 2, "w2": 2},
        stealable={"w0": [1, 2, 3, 4, 5, 6]},
        rates={"w0": 10.0, "w1": 100.0, "w2": 120.0})
    assert moves, "straggler trigger planned no steals"
    assert all(f == "w0" for _p, f, t in moves)
    assert all(t in ("w1", "w2") for _p, _f, t in moves)


def test_plan_steals_balanced_fleet_plans_nothing():
    assert plan_steals(pending={"w0": 3, "w1": 3},
                       stealable={"w0": [1, 2], "w1": [5, 6]},
                       rates={"w0": 50.0, "w1": 55.0}) == []
    # A donor's LAST pending piece is never stolen (it is being served).
    assert plan_steals(pending={"w0": 1, "w1": 0},
                       stealable={"w0": [7]}, rates={}) == []


def test_plan_steals_rate_proportional_split_in_one_sync():
    # With measured rates the split is proportional, not midpoint: an
    # ~11x faster receiver takes all but one piece in a single sync
    # (every extra round leaves the straggler starting pieces that then
    # stop being stealable).
    moves = plan_steals(pending={"w0": 8, "w1": 0},
                        stealable={"w0": list(range(8))},
                        rates={"w0": 10.0, "w1": 110.0})
    assert len(moves) == 7
    assert all((f, t) == ("w0", "w1") for _p, f, t in moves)


def test_plan_steals_never_bounces_work_back_to_drained_straggler():
    # A drained straggler near the epoch tail: the fast donor's
    # proportional share is the whole remaining backlog, so nothing moves
    # — handing the slow worker one last piece would serialize the epoch
    # wall behind it.
    assert plan_steals(pending={"slow": 0, "fast": 4},
                       stealable={"fast": [1, 2]},
                       rates={"slow": 10.0, "fast": 110.0}) == []


def test_plan_steals_zero_rate_donor_sheds_to_one_piece_floor():
    # A donor that has delivered NOTHING while a receiver is demonstrably
    # moving sheds its backlog down to the piece it is serving in ONE
    # sync — halving would cost a round per factor of 2, and every round
    # the straggler starts another piece that stops being stealable.
    moves = plan_steals(pending={"w0": 16, "w1": 2},
                        stealable={"w0": list(range(16))},
                        rates={"w0": 0.0, "w1": 5000.0})
    assert len(moves) == 15
    assert all((f, t) == ("w0", "w1") for _p, f, t in moves)


def test_plan_steals_below_median_receiver_gets_probe_not_share():
    # A drained receiver whose own rate is below the straggler threshold
    # (it drained because it was shed, not because it is fast) gets a
    # 2-piece PROBE instead of the rate-proportional share: early-epoch
    # EMAs over-hand work back, and every piece handed back serves at the
    # slow rate or must be re-stolen.
    moves = plan_steals(pending={"slow": 0, "fast": 29},
                        stealable={"fast": list(range(29))},
                        rates={"slow": 4000.0, "fast": 10000.0})
    assert len(moves) == 2
    assert all((f, t) == ("fast", "slow") for _p, f, t in moves)


def test_plan_steals_small_share_to_below_median_receiver_stays_put():
    # Near the tail a 1-2 piece proportional share is not worth the
    # revoke/extend round trip plus the straggler's serve rate: the
    # healthy donor keeps it and the slow worker stays idle.
    assert plan_steals(pending={"slow": 0, "fast": 8},
                       stealable={"fast": list(range(8))},
                       rates={"slow": 2000.0, "fast": 10000.0}) == []


def test_plan_steals_moves_only_stealable_pieces():
    moves = plan_steals(pending={"w0": 9, "w1": 0},
                        stealable={"w0": [3]}, rates={})
    assert moves == [(3, "w0", "w1")]  # backlog says 4, stealable caps at 1


# ---------------------------------------------------------------------------
# streaming piece engine
# ---------------------------------------------------------------------------

def _dynamic_reader(url, pool="dummy"):
    from petastorm_tpu import make_batch_reader

    return make_batch_reader(url, dynamic_ventilation=True, num_epochs=1,
                             shuffle_row_groups=False, cur_shard=0,
                             shard_count=1, reader_pool_type=pool,
                             workers_count=2)


def _drain_engine(engine, timeout_s=30.0):
    """Pump the engine to completion; return (batch events, done events)."""
    batches, done = [], []
    deadline = time.monotonic() + timeout_s
    while not engine.finished:
        assert time.monotonic() < deadline, "engine did not drain"
        event = engine.next_event(timeout=0.2)
        if event is None:
            continue
        (batches if event[0] == "batch" else done).append(event)
    return batches, done


def _decode_rows(batches):
    from petastorm_tpu.reader_impl.framed_socket import decode_payload

    ids = []
    for _kind, _piece, _gen, _ordinal, _rows, fmt, frames, _s in batches:
        payload = decode_payload(fmt, [bytes(f) for f in frames])
        ids.extend(int(i) for i in payload["id"])
    return ids


def test_engine_serves_queue_through_one_reader(scalar_dataset_12pieces):
    from petastorm_tpu.service.piece_engine import StreamingPieceEngine

    url, rows = scalar_dataset_12pieces
    constructed = []

    def factory():
        constructed.append(1)
        return _dynamic_reader(url)

    engine = StreamingPieceEngine(factory, batch_size=5)
    try:
        for piece in range(12):
            engine.enqueue(piece, generation=7)
        engine.finish()
        batches, done = _drain_engine(engine)
        assert len(constructed) == 1  # ONE reader for 12 pieces
        assert sorted(_decode_rows(batches)) == list(range(rows))
        # Piece-aligned: every piece announces exactly one piece_done with
        # the generation it was granted under, after its batches.
        assert sorted(p for _k, p, _g, _r in done) == list(range(12))
        assert {g for _k, _p, g, _r in done} == {7}
        assert engine.diagnostics["engine_pieces_served"] == 12
    finally:
        engine.close()


def test_engine_revoke_removes_unsent_reenqueue_rearms(
        scalar_dataset_12pieces):
    from petastorm_tpu.service.piece_engine import StreamingPieceEngine

    url, _rows = scalar_dataset_12pieces
    engine = StreamingPieceEngine(lambda: _dynamic_reader(url), batch_size=5)
    try:
        for piece in range(12):
            engine.enqueue(piece, generation=1)
        # Deep-queued pieces (beyond the lookahead) have not started: a
        # revoke must drop them before anything is sent.
        removed = engine.revoke([9, 10, 11])
        assert sorted(removed) == [9, 10, 11]
        # Re-granting a revoked piece re-arms it (an aborted steal).
        assert engine.enqueue(10, generation=2)
        engine.finish()
        batches, done = _drain_engine(engine)
        served = {p for _k, p, _g, _r in done}
        assert served == set(range(9)) | {10}
        by_piece = {p: g for _k, p, g, _r in done}
        assert by_piece[10] == 2  # served under the re-grant's generation
        assert sorted(_decode_rows(batches)) == sorted(
            i for p in served for i in range(5 * p, 5 * p + 5))
        assert engine.diagnostics["engine_pieces_revoked"] == 3
    finally:
        engine.close()


def test_engine_lazy_reader_not_built_for_all_warm_stream(
        scalar_dataset_12pieces):
    """A fully-warm stream (every piece a cache hit) must not construct a
    reader at all — the PR 5 warm path's zero-spinup property."""
    from petastorm_tpu.cache_impl import BatchCache, batch_fingerprint
    from petastorm_tpu.service.piece_engine import StreamingPieceEngine

    url, _rows = scalar_dataset_12pieces
    cache = BatchCache(mem_budget_bytes=32 << 20)

    def key(piece):
        return batch_fingerprint(url, [int(piece)], 5)

    def fill(piece):
        builder = cache.begin_fill(key(piece))
        builder.add_batch({"id": np.arange(5 * piece, 5 * piece + 5)})
        builder.commit()

    for piece in (0, 1, 2):
        fill(piece)

    def factory():
        raise AssertionError("warm stream constructed a reader")

    engine = StreamingPieceEngine(factory, batch_size=5, cache=cache,
                                  cache_key_fn=key)
    try:
        for piece in (0, 1, 2):
            engine.enqueue(piece)
        engine.finish()
        batches, done = _drain_engine(engine)
        assert engine.reader is None
        assert sorted(_decode_rows(batches)) == list(range(15))
        assert len(done) == 3
    finally:
        engine.close()
        cache.cleanup()


# ---------------------------------------------------------------------------
# dynamic mode end-to-end (loopback fleet)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def scalar_dataset_12pieces(tmp_path_factory):
    """60 rows in 12 five-row row-group pieces: piece p holds ids
    [5p, 5p+5), so a batch's origin piece is identifiable from its ids."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    path = tmp_path_factory.mktemp("dynamic_ds")
    url = f"file://{path}/ds"
    create_test_scalar_dataset(url, rows_count=60, rows_per_row_group=5)
    return url, 60


def _dynamic_fleet(url, skew_worker_delay_s=0.0, num_epochs=1, n_workers=2,
                   batch_size=5):
    dispatcher = Dispatcher(port=0, mode="dynamic",
                            num_epochs=num_epochs).start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=batch_size, reader_factory="batch",
                    worker_id=f"w{i}",
                    batch_delay_s=(skew_worker_delay_s if i == 0 else 0.0),
                    reader_kwargs={"workers_count": 2}).start()
        for i in range(n_workers)]
    return dispatcher, workers


def _stop_fleet(dispatcher, workers):
    for worker in workers:
        worker.stop()
    dispatcher.stop()


def test_dynamic_loopback_matches_local_reader(scalar_dataset_12pieces):
    url, rows = scalar_dataset_12pieces
    dispatcher, workers = _dynamic_fleet(url)
    try:
        source = ServiceBatchSource(dispatcher.address,
                                    dynamic_sync_interval_s=0.1)
        got = [int(i) for batch in source() for i in batch["id"]]
        assert sorted(got) == list(range(rows))
        # The dispatcher's books closed: every piece reported done.
        status = source.dispatcher_status()
        dyn = status["dynamic"]
        assert dyn["clients"][source.client_id]["pieces_done"] == 12
    finally:
        _stop_fleet(dispatcher, workers)


@pytest.mark.parametrize("transport", ["tcp", "shm"])
def test_dynamic_steals_rebalance_skewed_worker_zero_dup_zero_loss(
        scalar_dataset_12pieces, transport):
    """ISSUE acceptance shape: one of two workers skewed per batch — work
    stealing moves its backlog to the fast worker, every row arrives
    exactly once, and the straggler ends up serving fewer pieces.
    Parametrized over the delivery tier: the steal handshake (revoke /
    extend control frames) rides TCP on both tiers, but the revoked and
    re-served batches ride the negotiated transport — dedup and piece
    accounting must not notice the difference."""
    url, rows = scalar_dataset_12pieces
    dispatcher, workers = _dynamic_fleet(url, skew_worker_delay_s=0.15)
    try:
        source = ServiceBatchSource(dispatcher.address,
                                    dynamic_sync_interval_s=0.1,
                                    transport=transport)
        got = [int(i) for batch in source() for i in batch["id"]]
        assert sorted(got) == list(range(rows))  # zero dup AND zero loss
        recovery = source.diagnostics["recovery"]
        assert recovery["steals_applied"] >= 1
        assert recovery["dedup_dropped"] == 0
        per_worker = source.diagnostics["per_worker"]
        slow = per_worker["w0"].get("pieces", 0)
        fast = per_worker["w1"].get("pieces", 0)
        assert slow + fast == 12
        assert fast > slow, (
            f"stealing did not shift pieces to the fast worker: "
            f"slow={slow} fast={fast}")
        # Steal accounting is visible in dispatcher status (the STEALS
        # column of `status --watch`).
        dyn = source.dispatcher_status()["dynamic"]
        assert dyn["per_worker"]["w0"]["steals_out"] >= 1
        assert dyn["per_worker"]["w1"]["steals_in"] >= 1
        assert dyn["generation"] >= 1
    finally:
        _stop_fleet(dispatcher, workers)


def test_dynamic_stream_extend_before_connect_is_queued_not_dropped(
        monkeypatch):
    """A steal grant can land before the stream's reader thread dials the
    worker (launch() registers the stream immediately; the TCP connect
    happens on the reader thread's first pull). The control edit must
    queue and flush right after the handshake, in order — dropping it
    orphans a piece both ownership maps already assign to this worker."""
    from petastorm_tpu.service import client as client_mod

    sent = []

    class _FakeConn:
        def send(self, message):
            sent.append(dict(message))

        def close(self):
            pass

    monkeypatch.setattr(client_mod.FramedConnection, "connect",
                        staticmethod(lambda *a, **kw: _FakeConn()))
    stream = client_mod._DynamicStream(
        "w0", ("127.0.0.1", 1), [(0, 1)], epoch=0, connect_timeout=1.0)
    stream.extend([(7, 3)])
    assert sent == []  # queued, not written onto a nonexistent socket
    stream._ensure_conn()
    assert [m["type"] for m in sent] == ["stream", "extend"]
    assert sent[1]["pieces"] == [[7, 3, 0]]
    stream.extend([(8, 4)])  # post-handshake edits go straight through
    assert sent[-1]["pieces"] == [[8, 4, 0]]


def test_dynamic_mid_epoch_worker_join_receives_steals(
        scalar_dataset_12pieces):
    """A worker that registers AFTER the epoch started is a legal steal
    receiver: the planner sees it as drained (it is alive with zero
    grants), ships its address with the delta, and the client opens a
    stream to it mid-epoch — with the multiset still exact."""
    url, rows = scalar_dataset_12pieces
    dispatcher = Dispatcher(port=0, mode="dynamic").start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=5, reader_factory="batch", worker_id="w0",
                    batch_delay_s=0.15,
                    reader_kwargs={"workers_count": 2}).start()]
    try:
        source = ServiceBatchSource(dispatcher.address,
                                    dynamic_sync_interval_s=0.1)
        got = []
        for batch in source():
            got.extend(int(i) for i in batch["id"])
            if len(workers) == 1:
                workers.append(
                    BatchWorker(url, dispatcher_address=dispatcher.address,
                                batch_size=5, reader_factory="batch",
                                worker_id="w1",
                                reader_kwargs={"workers_count": 2}).start())
        assert sorted(got) == list(range(rows))
        per_worker = source.diagnostics["per_worker"]
        joined = per_worker.get("w1", {}).get("pieces", 0)
        assert joined >= 1, (
            f"mid-epoch joiner served nothing: {per_worker}")
        assert source.diagnostics["recovery"]["steals_applied"] >= 1
    finally:
        _stop_fleet(dispatcher, workers)


def test_dynamic_multi_epoch_delivers_every_epoch(scalar_dataset_12pieces):
    """The fcfs single-epoch restriction does not apply to dynamic mode:
    num_epochs=2 delivers the full multiset twice."""
    url, rows = scalar_dataset_12pieces
    dispatcher, workers = _dynamic_fleet(url, num_epochs=2)
    try:
        source = ServiceBatchSource(dispatcher.address,
                                    dynamic_sync_interval_s=0.1)
        got = [int(i) for batch in source() for i in batch["id"]]
        assert sorted(got) == sorted(list(range(rows)) * 2)
    finally:
        _stop_fleet(dispatcher, workers)


def test_dynamic_steal_mid_epoch_preserves_state_dict_resume(
        scalar_dataset_12pieces):
    """Tier-1 ISSUE satellite: snapshot mid-epoch AFTER steals have moved
    pieces, resume from it — completed pieces are never re-served, v2
    watermarks resume mid-piece pieces at their next batch (not from the
    piece start), so first + resumed cover the dataset EXACTLY once."""
    url, rows = scalar_dataset_12pieces
    dispatcher, workers = _dynamic_fleet(url, skew_worker_delay_s=0.15)
    try:
        source = ServiceBatchSource(dispatcher.address,
                                    dynamic_sync_interval_s=0.1)
        first, state = [], None
        iterator = source()
        for batch in iterator:
            first.extend(int(i) for i in batch["id"])
            state = source.state_dict()
            if (len(first) >= rows // 2 and state["completed_pieces"]
                    and source.diagnostics["recovery"]["steals_applied"]):
                break
        else:
            pytest.fail("stream ended before a steal + snapshot landed")
        iterator.close()
        assert state["version"] == 2
        completed = set(state["completed_pieces"])
        # The snapshot's contract: every completed piece was fully
        # delivered in part one (a steal moves WHO serves a piece, never
        # whether it counts as completed).
        for piece in completed:
            for row in range(5 * piece, 5 * piece + 5):
                assert row in first, (
                    f"piece {piece} marked completed but row {row} was "
                    f"never delivered")
        resumed = ServiceBatchSource(dispatcher.address, resume_state=state,
                                     dynamic_sync_interval_s=0.1)
        second = [int(i) for batch in resumed() for i in batch["id"]]
        # Exactly-once resume: the two halves tile the dataset with zero
        # duplicates — mid-piece pieces continue at their watermark
        # instead of re-streaming whole (the pre-v2 at-least-once shape).
        assert sorted(first + second) == list(range(rows))
        assert resumed.diagnostics["recovery"]["duplicates_dropped"] == 0
    finally:
        _stop_fleet(dispatcher, workers)


def test_dynamic_cold_cache_fill_constructs_one_reader_per_stream(
        scalar_dataset_12pieces):
    """ISSUE acceptance: a cold cache-fill epoch over many small pieces
    shows reader constructions == streams, not pieces — and a warm epoch
    constructs none."""
    from petastorm_tpu.cache_impl import BatchCache

    url, rows = scalar_dataset_12pieces
    dispatcher = Dispatcher(port=0, mode="dynamic", num_epochs=2).start()
    worker = BatchWorker(url, dispatcher_address=dispatcher.address,
                         batch_size=5, reader_factory="batch",
                         worker_id="w0",
                         batch_cache=BatchCache(mem_budget_bytes=32 << 20),
                         reader_kwargs={"workers_count": 2}).start()
    try:
        baseline = worker._m_readers.value
        source = ServiceBatchSource(dispatcher.address,
                                    dynamic_sync_interval_s=0.1)
        got = [int(i) for batch in source() for i in batch["id"]]
        assert sorted(got) == sorted(list(range(rows)) * 2)
        constructed = worker._m_readers.value - baseline
        # 2 epochs = 2 streams over 12 pieces each: the cold epoch builds
        # ONE engine reader, the warm epoch builds none.
        assert constructed == 1, (
            f"expected 1 reader construction (cold stream), got "
            f"{constructed}")
        stats = worker.cache_stats()
        assert stats["misses"] == 12 and stats["hits"] >= 12
    finally:
        worker.stop()
        dispatcher.stop()


# ---------------------------------------------------------------------------
# scenario wiring (the bench A/B leg's substrate)
# ---------------------------------------------------------------------------

def test_scenario_rejects_multi_epoch_fcfs_pointing_at_dynamic():
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    with pytest.raises(ValueError, match="dynamic"):
        service_loopback_scenario(rows=100, epochs=2, sharding="fcfs")


def test_scenario_dynamic_multi_epoch_with_skew_reports_steals(tmp_path):
    """The `--sharding dynamic --skew-ms` A/B leg end-to-end: multi-epoch
    run under a straggler reports steals and per-worker piece counts, and
    the per-epoch breakdown stays intact."""
    import json

    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    json_out = tmp_path / "dyn.json"
    result = service_loopback_scenario(rows=2000, days=4, workers=2,
                                       batch_size=64, sharding="dynamic",
                                       skew_ms=30.0, epochs=2,
                                       json_out=str(json_out))
    assert result["mode"] == "dynamic"
    assert result["epochs"] == 2
    assert len(result["epochs_detail"]) == 2
    assert result["steals_applied"] >= 1
    assert result["dedup_dropped"] == 0
    assert sum(result["per_worker_pieces"].values()) > 0
    assert result["time_to_half_rows_s"] > 0
    assert json.loads(json_out.read_text().strip()) == result


def test_status_watch_renders_steals_and_backlog_columns():
    from petastorm_tpu.service.cli import render_fleet_status

    def sample(rows):
        return {
            "t": 10.0 + (2.0 if rows else 0.0),
            "status": {
                "mode": "dynamic", "fencing_epoch": 0,
                "workers": {"w0": {"alive": True}},
                "clients": {"c": {}},
                "recovery": {},
                "dynamic": {
                    "generation": 5,
                    "per_worker": {"w0": {"backlog": 3, "steals_in": 2,
                                          "steals_out": 1}},
                    "clients": {},
                },
            },
            "workers": {"w0": {"metrics": {
                "rows_sent_total": rows, "batches_sent_total": rows / 10,
                "credit_wait_seconds_total": 0.0, "active_streams": 1,
            }}},
        }

    text = render_fleet_status(sample(0), sample(500))
    assert "STEALS" in text and "BACKLOG" in text
    assert "generation=5" in text
    assert "2/1" in text  # steals in/out
    row = next(line for line in text.splitlines()
               if line.startswith("w0"))
    # backlog then the breaker column ("ok": no journaled exclusion).
    assert row.rstrip().endswith("3       ok")


# ---------------------------------------------------------------------------
# chaos under dynamic sharding (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_chaos_dynamic_dispatcher_restart_zero_dup_zero_loss():
    """Control-plane-only fault under dynamic sharding, multi-epoch: the
    journaled steals replay, and the multiset invariant must hold exactly
    (zero lost AND zero duplicate rows across both epochs)."""
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    result = service_loopback_scenario(rows=4000, days=4, workers=2,
                                       batch_size=32, sharding="dynamic",
                                       epochs=2, skew_ms=10.0,
                                       chaos="dispatcher-restart",
                                       chaos_interval_s=5.0)
    assert result["lost_rows"] == 0
    assert result["duplicate_rows"] == 0
    assert result["dispatcher_recovery"]["journal_replays"] >= 1
    assert result["chaos_events"], "no chaos event landed inside the run"


@pytest.mark.slow
def test_chaos_dynamic_worker_kill_no_loss():
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    result = service_loopback_scenario(rows=4000, days=4, workers=3,
                                       batch_size=32, sharding="dynamic",
                                       chaos="worker-kill",
                                       chaos_interval_s=5.0)
    assert result["lost_rows"] == 0  # duplicates allowed (at-least-once)
    assert result["chaos_events"]


@pytest.mark.slow
def test_chaos_dynamic_conn_drop_no_loss():
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    result = service_loopback_scenario(rows=4000, days=4, workers=2,
                                       batch_size=32, sharding="dynamic",
                                       epochs=2, chaos="conn-drop",
                                       chaos_interval_s=5.0)
    assert result["lost_rows"] == 0
    assert result["chaos_events"]
