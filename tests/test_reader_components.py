"""Unit tests: shuffling buffers, caches, weighted sampling, rowgroup indexes,
predicates, selectors."""

import numpy as np
import pyarrow as pa
import pyarrow.fs as pafs
import pytest

from petastorm_tpu.cache import NullCache
from petastorm_tpu.etl.rowgroup_indexers import FieldNotNullIndexer, SingleFieldIndexer
from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index, get_row_group_indexes
from petastorm_tpu.local_disk_arrow_table_cache import LocalDiskArrowTableCache
from petastorm_tpu.local_disk_cache import LocalDiskCache
from petastorm_tpu.predicates import (
    in_intersection,
    in_lambda,
    in_negate,
    in_pseudorandom_split,
    in_reduce,
    in_set,
)
from petastorm_tpu.reader import make_reader
from petastorm_tpu.reader_impl.shuffling_buffer import (
    NoopShufflingBuffer,
    RandomShufflingBuffer,
)
from petastorm_tpu.selectors import (
    IntersectIndexSelector,
    SingleIndexSelector,
    UnionIndexSelector,
)
from petastorm_tpu.test_util.reader_mock import ReaderMock
from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader
from petastorm_tpu.schema.codecs import ScalarCodec
from petastorm_tpu.schema.unischema import Unischema, UnischemaField


# ---- predicates ----------------------------------------------------------

def test_predicate_combinators():
    even = in_lambda(["x"], lambda v: v["x"] % 2 == 0)
    small = in_set(range(10), "x")
    both = in_reduce([even, small], all)
    either = in_reduce([even, small], any)
    neg = in_negate(even)
    assert both.get_fields() == {"x"}
    assert both.do_include({"x": 4}) and not both.do_include({"x": 11}) \
        and not both.do_include({"x": 12})
    assert either.do_include({"x": 12}) and either.do_include({"x": 9})
    assert not either.do_include({"x": 11})
    assert neg.do_include({"x": 3}) and not neg.do_include({"x": 4})


def test_in_intersection_collection_valued_field():
    pred = in_intersection({"cat", "dog"}, "tags")
    assert pred.get_fields() == {"tags"}
    # list-valued, ndarray-valued, scalar, and disjoint cases
    assert pred.do_include({"tags": ["bird", "dog"]})
    assert pred.do_include({"tags": np.asarray(["cat"])})
    assert pred.do_include({"tags": "dog"})  # scalar degrades to in_set
    assert not pred.do_include({"tags": ["bird", "fish"]})
    assert not pred.do_include({"tags": []})
    # deterministic repr (part of the disk-cache key)
    assert repr(pred) == repr(in_intersection({"dog", "cat"}, "tags"))
    # composes with the other combinators
    assert in_negate(pred).do_include({"tags": ["fish"]})


def test_vectorized_predicate_masks_match_row_path():
    import numpy as np

    column = np.array([1, 4, 7, 9, 12, 15])
    columns = {"x": column}
    small = in_set([1, 7, 12], "x")
    neg = in_negate(small)
    even = in_lambda(["x"], lambda v: v["x"] % 2 == 0)
    both = in_reduce([small, in_set(range(10), "x")], all)
    either = in_reduce([small, in_set([15], "x")], any)

    for predicate in (small, neg, both, either):
        mask = predicate.do_include_vectorized(columns, len(column))
        assert mask is not None
        expected = [predicate.do_include({"x": v}) for v in column]
        np.testing.assert_array_equal(mask, expected)
    # in_lambda(vectorized=True): the func sees whole columns.
    vec_even = in_lambda(["x"], lambda cols: cols["x"] % 2 == 0,
                         vectorized=True)
    np.testing.assert_array_equal(
        vec_even.do_include_vectorized(columns, len(column)),
        column % 2 == 0)
    with pytest.raises(ValueError, match="expected"):
        in_lambda(["x"], lambda cols: np.ones(3, bool),
                  vectorized=True).do_include_vectorized(
                      columns, len(column))
    # in_pseudorandom_split vectorizes too (column-loop hashing).
    split = in_pseudorandom_split([0.5, 0.5], 0, "x")
    mask = split.do_include_vectorized(columns, len(column))
    np.testing.assert_array_equal(
        mask, [split.do_include({"x": v}) for v in column])
    # Row-only predicates decline (and combinators containing them too).
    assert even.do_include_vectorized(columns, len(column)) is None
    assert in_reduce([small, even], all) \
        .do_include_vectorized(columns, len(column)) is None
    # Non-builtin reductions decline.
    assert in_reduce([small], lambda bools: bools[0]) \
        .do_include_vectorized(columns, len(column)) is None
    # Int<->float promotion past 2**53 loses exactness; vectorization
    # declines in every lossy direction (row path stays exact).
    float_cols = {"x": column.astype(np.float64)}
    big = 2 ** 53 + 1
    assert in_set([big], "x") \
        .do_include_vectorized(float_cols, len(column)) is None
    assert in_set([np.int64(big)], "x") \
        .do_include_vectorized(float_cols, len(column)) is None
    big_int_cols = {"x": np.array([big, 5], dtype=np.int64)}
    assert in_set([float(2 ** 53)], "x") \
        .do_include_vectorized(big_int_cols, 2) is None
    # in_negate tolerates list-returning user predicates.
    class ListMask(in_set):
        def do_include_vectorized(self, columns, n):
            return [True] * n
    neg_list = in_negate(ListMask([1], "x"))
    np.testing.assert_array_equal(
        neg_list.do_include_vectorized(columns, len(column)),
        [False] * len(column))


def test_batch_reader_uses_vectorized_in_set(scalar_dataset, monkeypatch):
    from petastorm_tpu import make_batch_reader

    row_calls = []
    monkeypatch.setattr(
        in_set, "do_include",
        lambda self, values: row_calls.append(1) or True)
    wanted = {0, 5, 10, 15, 20, 25}
    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           reader_pool_type="dummy",
                           predicate=in_set(wanted, "id")) as reader:
        ids = {int(i) for batch in reader for i in batch.id}
    assert ids == wanted
    # The vectorized mask handled everything: the row path never ran (if it
    # had, the patched do_include would also have kept every row).
    assert not row_calls


def test_pseudorandom_split_fractions():
    split = [0.6, 0.2, 0.2]
    counts = [0, 0, 0]
    for subset in range(3):
        predicate = in_pseudorandom_split(split, subset, "key")
        for i in range(3000):
            if predicate.do_include({"key": f"k{i}"}):
                counts[subset] += 1
    assert sum(counts) == 3000  # partition covers everything exactly once
    assert abs(counts[0] / 3000 - 0.6) < 0.05
    with pytest.raises(ValueError):
        in_pseudorandom_split([0.5, 0.6], 0, "key")
    with pytest.raises(ValueError):
        in_pseudorandom_split([0.5, 0.5], 2, "key")


# ---- shuffling buffers ---------------------------------------------------

def test_noop_buffer_fifo():
    buf = NoopShufflingBuffer()
    buf.add_many([1, 2, 3])
    assert [buf.retrieve() for _ in range(3)] == [1, 2, 3]
    assert not buf.can_retrieve()


def test_random_buffer_shuffles_and_drains():
    buf = RandomShufflingBuffer(100, min_after_retrieve=10, random_seed=0)
    buf.add_many(range(100))
    assert not buf.can_add()
    out = []
    while buf.can_retrieve():
        out.append(buf.retrieve())
    assert len(out) == 90  # min_after_retrieve floor holds while not finished
    buf.finish()
    while buf.can_retrieve():
        out.append(buf.retrieve())
    assert sorted(out) == list(range(100))
    assert out != sorted(out)


def test_random_buffer_overflow_guard():
    buf = RandomShufflingBuffer(10, extra_capacity=5)
    with pytest.raises(RuntimeError, match="overflow"):
        buf.add_many(range(20))
    with pytest.raises(ValueError):
        RandomShufflingBuffer(5, min_after_retrieve=6)


# ---- caches --------------------------------------------------------------

def test_null_cache_always_recomputes():
    calls = []
    cache = NullCache()
    assert cache.get("k", lambda: calls.append(1) or 42) == 42
    assert cache.get("k", lambda: calls.append(1) or 42) == 42
    assert len(calls) == 2


def test_local_disk_cache_hit_and_eviction(tmp_path):
    cache = LocalDiskCache(str(tmp_path / "cache"), size_limit=50_000)
    calls = []

    def load():
        calls.append(1)
        return np.zeros(1000)  # ~8KB pickled

    first = cache.get(("piece", 0), load)
    second = cache.get(("piece", 0), load)
    assert len(calls) == 1  # second hit served from disk
    assert np.array_equal(first, second)

    for i in range(20):  # ~160KB total >> 50KB limit
        cache.get(("piece", i + 1), lambda: np.zeros(1000))
    assert cache.size_on_disk() <= 50_000
    cache.cleanup()


def test_local_disk_cache_eviction_is_lru(tmp_path):
    """The shared eviction policy drops the LEAST recently used entry:
    after touching the oldest key, an overflow evicts the next-oldest
    instead."""
    import os

    cache = LocalDiskCache(str(tmp_path / "cache"), size_limit=30_000)
    for key in ("a", "b", "c"):       # ~8KB each: 3 entries fit the budget
        cache.get(key, lambda: np.zeros(1000))
    # Touch "a" (updates atime AND mtime, so noatime mounts still order
    # by recency): "b" becomes the LRU entry.
    os.utime(cache._key_path("a"))
    cache.get("overflow", lambda: np.zeros(1000))  # pushes past 30KB
    assert cache.size_on_disk() <= 30_000
    refills = []
    cache.get("a", lambda: refills.append("a") or np.zeros(1000))
    cache.get("b", lambda: refills.append("b") or np.zeros(1000))
    cache.cleanup()
    assert refills == ["b"], "LRU should have evicted 'b', kept 'a'"


def test_local_disk_cache_cleanup_flag_removes_directory(tmp_path):
    path = tmp_path / "ephemeral"
    cache = LocalDiskCache(str(path), size_limit=10**6, cleanup=True)
    cache.get("k", lambda: np.zeros(10))
    assert path.is_dir()
    cache.cleanup()
    assert not path.exists()


def test_local_disk_arrow_table_cache(tmp_path):
    cache = LocalDiskArrowTableCache(str(tmp_path / "acache"), size_limit=10**6)
    table = pa.table({"x": [1, 2, 3]})
    calls = []

    def load():
        calls.append(1)
        return table

    assert cache.get("k", load).equals(table)
    assert cache.get("k", load).equals(table)
    assert len(calls) == 1
    with pytest.raises(ValueError, match="pa.Table"):
        cache.get("bad", lambda: [1, 2, 3])
    cache.cleanup()


def test_local_disk_arrow_table_cache_honors_size_limit(tmp_path):
    """The arrow-table variant inherits the shared eviction budget."""
    cache = LocalDiskArrowTableCache(str(tmp_path / "acache"),
                                     size_limit=40_000)
    for i in range(20):  # ~8KB of float64 per table >> the 40KB budget
        cache.get(("t", i),
                  lambda: pa.table({"x": np.zeros(1000)}))
    assert cache.size_on_disk() <= 40_000
    cache.cleanup()


def test_reader_local_disk_cache_speeds_second_epoch(petastorm_dataset, tmp_path):
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     num_epochs=2, cache_type="local-disk",
                     cache_location=str(tmp_path / "rcache"),
                     cache_size_limit=10**8) as reader:
        ids = [row.id for row in reader]
    assert sorted(ids) == sorted(list(range(30)) * 2)
    # Reader.stop() released the cache (deregistered from the leak
    # tracker); files persist — cleanup=True is the deletion opt-in.
    assert (tmp_path / "rcache").is_dir()


def test_reader_local_disk_cache_enforces_size_limit(petastorm_dataset,
                                                     tmp_path):
    """Seed-parity coverage: `make_reader(cache_type="local-disk")` honors
    `cache_size_limit` as a real eviction budget (the directory never
    settles above it), and still serves every row."""
    from petastorm_tpu.cache_impl.eviction import dir_size

    location = tmp_path / "tiny_cache"
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     num_epochs=2, cache_type="local-disk",
                     cache_location=str(location),
                     cache_size_limit=20_000) as reader:
        ids = [row.id for row in reader]
    assert sorted(ids) == sorted(list(range(30)) * 2)
    assert dir_size(str(location), ".cache") <= 20_000


# ---- weighted sampling ---------------------------------------------------

SIMPLE = Unischema("Simple", [
    UnischemaField("source", np.int32, (), ScalarCodec(), False),
])


def test_weighted_sampling_mixes_readers():
    reader_a = ReaderMock(SIMPLE, lambda i: {"source": np.int32(0)})
    reader_b = ReaderMock(SIMPLE, lambda i: {"source": np.int32(1)})
    mixed = WeightedSamplingReader([reader_a, reader_b], [0.8, 0.2],
                                   random_seed=3)
    draws = [next(mixed).source for _ in range(2000)]
    share_a = draws.count(0) / len(draws)
    assert abs(share_a - 0.8) < 0.05
    mixed.stop()
    assert reader_a.stopped and reader_b.stopped


def test_weighted_sampling_stops_with_exhausted_reader():
    reader_a = ReaderMock(SIMPLE, lambda i: {"source": np.int32(0)}, num_rows=2)
    reader_b = ReaderMock(SIMPLE, lambda i: {"source": np.int32(1)})
    mixed = WeightedSamplingReader([reader_a, reader_b], [1.0, 0.0])
    assert next(mixed).source == 0
    assert next(mixed).source == 0
    with pytest.raises(StopIteration):
        while True:
            next(mixed)


def test_weighted_sampling_validation():
    reader = ReaderMock(SIMPLE, lambda i: {"source": np.int32(0)})
    with pytest.raises(ValueError):
        WeightedSamplingReader([reader], [0.5, 0.5])
    with pytest.raises(ValueError):
        WeightedSamplingReader([], [])


# ---- rowgroup indexing + selectors --------------------------------------

def test_rowgroup_index_and_selectors(petastorm_dataset):
    fs = pafs.LocalFileSystem()
    indexers = [
        SingleFieldIndexer("by_sensor", "sensor_name"),
        FieldNotNullIndexer("has_matrix_nullable", "matrix_nullable"),
    ]
    index_dict = build_rowgroup_index(petastorm_dataset.url, indexers)
    assert set(index_dict) == {"by_sensor", "has_matrix_nullable"}

    loaded = get_row_group_indexes(fs, petastorm_dataset.path)
    by_sensor = loaded["by_sensor"]
    # both sensors appear in every row group (ids alternate)
    assert by_sensor.get_row_group_indexes("sensor_0") == {0, 1, 2}
    assert by_sensor.get_row_group_indexes("nonexistent") == set()

    single = SingleIndexSelector("by_sensor", ["sensor_1"])
    assert single.select_row_groups(loaded) == {0, 1, 2}
    inter = IntersectIndexSelector([
        SingleIndexSelector("by_sensor", ["sensor_0"]),
        SingleIndexSelector("has_matrix_nullable", [None]),
    ])
    union = UnionIndexSelector([
        SingleIndexSelector("by_sensor", ["sensor_0"]),
        SingleIndexSelector("by_sensor", ["sensor_1"]),
    ])
    assert inter.select_row_groups(loaded) == {0, 1, 2}
    assert union.select_row_groups(loaded) == {0, 1, 2}


def test_reader_with_rowgroup_selector(petastorm_dataset, tmp_path):
    """Selector prunes row groups before any read: index id2 values."""
    from petastorm_tpu.test_util.dataset_factory import create_test_dataset

    path = tmp_path / "sel_ds"
    url = f"file://{path}"
    create_test_dataset(url, rows_count=30, rows_per_row_group=10)
    build_rowgroup_index(url, [SingleFieldIndexer("by_part", "partition_key")])

    with make_reader(url, reader_pool_type="dummy",
                     rowgroup_selector=SingleIndexSelector("by_part", ["p_0"])
                     ) as reader:
        ids = [row.id for row in reader]
    # every row group contains p_0 rows here, so selector keeps all groups;
    # assert it at least returned the whole set (pruning correctness is
    # covered by the direct selector assertions above)
    assert sorted(ids) == list(range(30))


def test_selector_missing_index_raises(petastorm_dataset):
    from petastorm_tpu.errors import PetastormMetadataError

    selector = SingleIndexSelector("no_such_index", ["v"])
    # ValueError when the index store exists but lacks the name;
    # PetastormMetadataError when no index store was ever built (run order)
    with pytest.raises((ValueError, PetastormMetadataError),
                       match="no rowgroup index|no_such_index"):
        with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         rowgroup_selector=selector):
            pass


def test_selector_combined_with_filters_matches_by_identity(tmp_path):
    """Selector ordinals are canonical; combining with ``filters`` must not
    shift them onto the wrong row groups (regression: selector indexed the
    filters-pruned list positionally)."""
    from petastorm_tpu.test_util.dataset_factory import TestSchema, make_test_row
    from petastorm_tpu.etl.metadata import materialize_rows

    path = tmp_path / "selfil_ds"
    url = f"file://{path}"
    rows = []
    for i in range(30):
        row = make_test_row(i)
        row["partition_key"] = f"p_{i // 10}"  # rg0=p_0, rg1=p_1, rg2=p_2
        rows.append(row)
    materialize_rows(url, TestSchema, rows, rows_per_row_group=10)
    build_rowgroup_index(url, [SingleFieldIndexer("by_part", "partition_key")])

    # Selector keeps rg0+rg1 (p_0, p_1); filters prune rg0 (id < 10).
    with make_reader(url, reader_pool_type="dummy", shuffle_row_groups=False,
                     rowgroup_selector=SingleIndexSelector("by_part",
                                                           ["p_0", "p_1"]),
                     filters=[("id", ">=", 10)]) as reader:
        ids = [row.id for row in reader]
    assert sorted(ids) == list(range(10, 20))


def test_empty_shard_yields_nothing_instead_of_raising(petastorm_dataset):
    """A shard with zero row groups is a valid (empty) reader — raising would
    kill one pod host and deadlock the SPMD step (review finding)."""
    with pytest.warns(UserWarning, match="zero row groups"):
        reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                             cur_shard=5, shard_count=6, num_epochs=1)
    with reader:
        assert list(reader) == []


def test_predicate_reprs_are_deterministic():
    """Predicate reprs feed persistent disk-cache keys — no memory addresses."""
    from petastorm_tpu.predicates import (in_lambda, in_negate,
                                          in_pseudorandom_split, in_reduce,
                                          in_set)

    preds = [
        in_set({3, 1, 2}, "id"),
        in_lambda(["id"], lambda v: v["id"] > 2),
        in_negate(in_set({1}, "id")),
        in_reduce([in_set({1}, "id"), in_set({2}, "id2")], all),
        in_pseudorandom_split([0.5, 0.5], 0, "id"),
    ]
    for pred in preds:
        assert "0x" not in repr(pred), repr(pred)
    # same-shaped lambdas fingerprint identically; different logic differs
    a = in_lambda(["id"], lambda v: v["id"] > 2)
    b = in_lambda(["id"], lambda v: v["id"] > 2)
    c = in_lambda(["id"], lambda v: v["id"] < 99)
    assert repr(a) == repr(b)
    assert repr(a) != repr(c)


def test_predicate_fingerprint_nested_lambdas_stable():
    """Nested code objects in co_consts used to be fingerprinted via repr()
    (memory address — new key every process, permanent disk-cache miss).
    Separately compiled but identical sources must fingerprint identically."""
    from petastorm_tpu.predicates import in_lambda

    src = "fn = lambda v: any(x > 2 for x in [v['id']])"
    ns_a, ns_b = {}, {}
    exec(src, ns_a)
    exec(src, ns_b)
    a = in_lambda(["id"], ns_a["fn"])
    b = in_lambda(["id"], ns_b["fn"])
    assert "0x" not in repr(a), repr(a)
    assert repr(a) == repr(b)
