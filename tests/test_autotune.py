"""Pipeline stage graph + online autotuner (docs/guides/pipeline.md).

Three layers:

- the PURE planner: golden decisions from canned profile snapshots
  (decode-bound, dispatch-bound, credit-wait-bound, worker-bound,
  already-balanced), hysteresis/oscillation guarantees, bound safety;
- the graph/knob bindings: live resizes actually land (thread pool,
  loader prefetch queues, client ready-queue/credits, transform
  placement round-trips through the service);
- the tier-1 smoke guard: a tiny synthetic pipeline with the autotuner
  enabled converges (trailing rounds become no-ops) and never leaves a
  knob outside its declared bounds.
"""

import queue
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.pipeline import (
    AutotuneController,
    Knob,
    PipelineGraph,
    Planner,
    StageNode,
    build_loader_graph,
    classify,
)

pytestmark = pytest.mark.autotune


# ---------------------------------------------------------------------------
# planner: golden decisions from canned profiles
# ---------------------------------------------------------------------------

KNOBS = {
    "workers_count": {"kind": "int", "lo": 1, "hi": 16, "applies": "live"},
    "host_prefetch": {"kind": "int", "lo": 1, "hi": 64, "applies": "live"},
    "device_prefetch": {"kind": "int", "lo": 1, "hi": 16, "applies": "live"},
    "credits": {"kind": "int", "lo": 1, "hi": 64, "applies": "next-stream"},
    "ready_queue_depth": {"kind": "int", "lo": 2, "hi": 256,
                          "applies": "live"},
    "transform_placement": {"kind": "choice",
                            "choices": ["remote", "local"],
                            "applies": "next-iteration"},
}


def _profile(*, wall=1.0, rows=10000, stall=0.0, queue_wait=0.0,
             decode=0.0, dispatch=0.0, credit_wait=None, recv_stall=None,
             knobs=None):
    out = {"wall_s": wall, "rows": rows, "stall_s": stall,
           "queue_wait_s": queue_wait, "decode_s": decode,
           "dispatch_s": dispatch,
           "knobs": dict(knobs or {"workers_count": 2, "host_prefetch": 4,
                                   "device_prefetch": 2, "credits": 8,
                                   "ready_queue_depth": 16,
                                   "transform_placement": "remote"})}
    if credit_wait is not None:
        out["credit_wait_s"] = credit_wait
    if recv_stall is not None:
        out["recv_stall_s"] = recv_stall
    return out


def _plan_until_decision(planner, profile, max_rounds=6):
    """Feed the same profile until hysteresis admits a decision."""
    for _ in range(max_rounds):
        decisions = planner.plan(profile)
        if decisions:
            return decisions
    return []


def test_classify_golden():
    assert classify(_profile(stall=0.5, decode=0.9,
                             dispatch=0.1)) == "decode-bound"
    assert classify(_profile(stall=0.5, decode=0.1,
                             dispatch=0.6)) == "dispatch-bound"
    assert classify(_profile(stall=0.5, decode=0.1, dispatch=0.0,
                             credit_wait=0.6)) == "credit-bound"
    assert classify(_profile(stall=0.5, decode=0.0, dispatch=0.0,
                             credit_wait=0.01,
                             recv_stall=0.9)) == "worker-bound"
    assert classify(_profile(stall=0.01)) == "balanced"
    assert classify(_profile(stall=0.01, queue_wait=0.5)) == \
        "consumer-bound"
    assert classify(_profile(rows=0)) == "idle"
    assert classify(_profile(wall=0.0)) == "idle"


def test_decode_bound_raises_workers_count():
    planner = Planner(KNOBS, hysteresis=2)
    decisions = _plan_until_decision(
        planner, _profile(stall=0.5, decode=0.9, dispatch=0.1))
    assert [(d["knob"], d["direction"], d["to"]) for d in decisions] == \
        [("workers_count", "up", 4)]


def test_dispatch_bound_raises_device_prefetch():
    planner = Planner(KNOBS, hysteresis=2)
    decisions = _plan_until_decision(
        planner, _profile(stall=0.5, decode=0.1, dispatch=0.6))
    assert [(d["knob"], d["direction"], d["to"]) for d in decisions] == \
        [("device_prefetch", "up", 4)]


def test_credit_bound_raises_credits():
    planner = Planner(KNOBS, hysteresis=2)
    decisions = _plan_until_decision(
        planner, _profile(stall=0.5, credit_wait=0.6))
    assert [(d["knob"], d["direction"], d["to"]) for d in decisions] == \
        [("credits", "up", 16)]


def test_worker_bound_flips_transform_local():
    planner = Planner(KNOBS, hysteresis=2, placement_hysteresis=3)
    profile = _profile(stall=0.6, recv_stall=0.9)
    decisions = _plan_until_decision(planner, profile)
    assert [(d["knob"], d["direction"], d["to"]) for d in decisions] == \
        [("transform_placement", "flip", "local")]
    assert decisions[0]["applies"] == "next-iteration"


def test_balanced_is_a_noop_forever():
    planner = Planner(KNOBS, hysteresis=1)
    for _ in range(10):
        assert planner.plan(_profile(stall=0.01)) == []
        assert planner.last_outcome == "noop"


def test_idle_windows_never_tune():
    planner = Planner(KNOBS, hysteresis=1)
    for _ in range(5):
        assert planner.plan(_profile(rows=0)) == []
        assert planner.last_outcome == "idle"


def test_hysteresis_requires_persistent_class():
    planner = Planner(KNOBS, hysteresis=3)
    decode_bound = _profile(stall=0.5, decode=0.9, dispatch=0.1)
    dispatch_bound = _profile(stall=0.5, decode=0.1, dispatch=0.6)
    # Alternating bottleneck classes never build the 3-round streak.
    for _ in range(6):
        assert planner.plan(decode_bound) == []
        assert planner.plan(dispatch_bound) == []


def test_regressing_probe_reverts_and_settles():
    """A probe that lowers throughput is rolled back and the knob is not
    probed again while the bottleneck class persists — two adjacent
    values cannot oscillate."""
    planner = Planner(KNOBS, hysteresis=1, tolerance=0.05)
    fast = _profile(stall=0.5, decode=0.9, dispatch=0.1, rows=10000)
    slow = _profile(stall=0.5, decode=0.9, dispatch=0.1, rows=5000)
    first = planner.plan(fast)
    assert first and first[0]["knob"] == "workers_count" \
        and first[0]["to"] == 4
    # Next window: throughput halved -> revert to the previous value.
    second = planner.plan(slow)
    assert [(d["knob"], d["direction"], d["to"]) for d in second] == \
        [("workers_count", "revert", 2)]
    # Same class keeps holding: workers_count is settled, the fallback
    # knob (host_prefetch) probes instead, and after IT settles the
    # planner goes quiet — workers_count is never touched again.
    later = []
    for _ in range(8):
        later.extend(planner.plan(fast))
    assert all(d["knob"] != "workers_count" for d in later)


def test_non_live_probe_defers_evaluation():
    """A knob whose change is not live (credits apply to the NEXT
    streams) is not judged on the windows before the change could have
    landed: evaluation waits ``probe_defer`` informative windows."""
    planner = Planner(KNOBS, hysteresis=1, probe_defer=2)
    credit_bound = _profile(stall=0.5, credit_wait=0.6)
    first = planner.plan(credit_bound)
    assert first[0]["knob"] == "credits" and first[0]["to"] == 16
    # The next two windows (pre-landing noise, here even a "regression")
    # are held, not evaluated.
    noisy = _profile(stall=0.5, credit_wait=0.6, rows=100)
    assert planner.plan(noisy) == [] and planner.last_outcome == "noop"
    assert planner.plan(noisy) == [] and planner.last_outcome == "noop"
    # The third window is the evaluation: a real regression now reverts.
    assert [(d["knob"], d["direction"], d["to"])
            for d in planner.plan(noisy)] == [("credits", "revert", 8)]


def test_neutral_probe_settles_without_oscillation():
    """Equal throughput across a probe keeps the value but stops probing
    the knob: the trail becomes a no-op stream, not an up/down ping-pong
    between two adjacent values."""
    planner = Planner(KNOBS, hysteresis=1, tolerance=0.05)
    profile = _profile(stall=0.5, decode=0.9, dispatch=0.1)
    decisions = [planner.plan(profile) for _ in range(12)]
    flat = [d for ds in decisions for d in ds]
    # One probe per candidate knob at most (workers_count, host_prefetch)
    # and never a revisit: no knob appears twice.
    assert len({d["knob"] for d in flat}) == len(flat)
    assert decisions[-1] == [] and planner.last_outcome == "noop"


def test_planner_never_leaves_declared_bounds():
    planner = Planner(KNOBS, hysteresis=1, tolerance=1e9)  # keep everything
    knobs = {"workers_count": 15, "host_prefetch": 63,
             "device_prefetch": 15, "credits": 63, "ready_queue_depth": 255,
             "transform_placement": "remote"}
    for _ in range(30):
        profile = _profile(stall=0.5, decode=0.9, dispatch=0.1,
                           knobs=dict(knobs))
        for decision in planner.plan(profile):
            desc = KNOBS[decision["knob"]]
            if desc["kind"] == "int":
                assert desc["lo"] <= decision["to"] <= desc["hi"]
            else:
                assert decision["to"] in desc["choices"]
            knobs[decision["knob"]] = decision["to"]


# ---------------------------------------------------------------------------
# graph + controller
# ---------------------------------------------------------------------------

def test_graph_rejects_bad_nodes_and_duplicate_knobs():
    with pytest.raises(ValueError, match="placement"):
        StageNode("x", "worker", "moon")
    with pytest.raises(ValueError, match="side"):
        StageNode("x", "elsewhere", "trainer")
    node = StageNode("x", "worker", "trainer")
    with pytest.raises(ValueError, match="unknown stage"):
        PipelineGraph([node], [("x", "y")])
    knob = Knob("k", get=lambda: 1, set=lambda v: None, lo=1, hi=4)
    with pytest.raises(ValueError, match="duplicate knob"):
        PipelineGraph([node], [], knobs=[knob, knob])


def test_controller_applies_and_journals_within_bounds():
    """One canned graph: the controller applies the planner's decision
    through the binding, clamps to bounds, and journals to the trail."""
    values = {"workers_count": 2}
    hist = {"count": 0, "sum": 0.0}
    signals = {"rows": lambda: sig["rows"], "stall_s": lambda: sig["stall"],
               "queue_wait_s": lambda: 0.0,
               "decode_s": lambda: sig["decode"],
               "dispatch_s": lambda: 0.0, "consumer_s": lambda: 0.0}
    sig = {"rows": 0, "stall": 0.0, "decode": 0.0}
    graph = PipelineGraph(
        [StageNode("decode", "worker", "trainer",
                   metric=lambda: (hist["count"], hist["sum"]))],
        [],
        knobs=[Knob("workers_count", get=lambda: values["workers_count"],
                    set=lambda v: values.__setitem__("workers_count", v),
                    lo=1, hi=16)],
        signals=signals)
    controller = AutotuneController(
        graph, interval_s=60,
        planner=Planner({"workers_count": KNOBS["workers_count"]},
                        hysteresis=1))
    controller._prev = (time.perf_counter() - 1.0, graph.snapshot())
    sig.update(rows=10000, stall=0.5, decode=0.9)
    applied = controller.step()
    assert values["workers_count"] == 4
    assert applied[0]["knob"] == "workers_count"
    report = controller.report()
    assert report["trail"][-1]["decisions"][0]["to"] == 4
    assert report["knobs"] == {"workers_count": 4}
    assert not controller.running  # step() never started the thread


def test_build_loader_graph_binds_local_knobs(petastorm_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader

    reader = make_reader(petastorm_dataset.url, reader_pool_type="thread",
                         workers_count=2, num_epochs=1)
    loader = make_jax_dataloader(reader, 5, stage_to_device=False)
    try:
        graph = build_loader_graph(loader)
        assert set(graph.knobs) == {"workers_count", "host_prefetch",
                                    "device_prefetch"}
        snapshot = graph.snapshot()
        assert snapshot["knobs"]["workers_count"] == 2
        assert snapshot["knobs"]["host_prefetch"] == 4
        # The declared chain covers both sides of the model.
        names = {name for _, name in graph.nodes}
        assert {"read", "decode", "transform", "collate", "serialize",
                "send", "recv", "queue", "device_put", "consume"} <= names
        # workers_count binding resizes the real pool.
        graph.knobs["workers_count"].set(3)
        assert reader.diagnostics["workers_count"] == 3
    finally:
        loader.stop()
        loader.join()
        reader.stop()
        reader.join()


# ---------------------------------------------------------------------------
# runtime-resizable bindings
# ---------------------------------------------------------------------------

def test_thread_pool_resize_grow_and_shrink(petastorm_dataset):
    """A live reader's pool grows and shrinks mid-iteration without
    dropping rows."""
    from petastorm_tpu import make_reader

    with make_reader(petastorm_dataset.url, reader_pool_type="thread",
                     workers_count=1, num_epochs=3) as reader:
        seen = []
        it = iter(reader)
        for _ in range(5):
            seen.append(int(next(it).id))
        reader.resize_workers(4)
        assert reader.diagnostics["workers_count"] == 4
        for _ in range(5):
            seen.append(int(next(it).id))
        reader.resize_workers(2)
        assert reader.diagnostics["workers_count"] == 2
        seen.extend(int(row.id) for row in it)
        assert len(seen) == 3 * len(petastorm_dataset.rows)


def test_thread_pool_resize_rejects_nonpositive():
    from petastorm_tpu.workers_pool.thread_pool import ThreadPool

    pool = ThreadPool(2)
    with pytest.raises(ValueError):
        pool.resize(0)
    pool.resize(5)  # pre-start resize just adjusts the constructed count
    assert pool.workers_count == 5


def test_process_pool_reader_refuses_resize(petastorm_dataset):
    from petastorm_tpu import make_reader

    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     num_epochs=1) as reader:
        with pytest.raises(NotImplementedError, match="thread"):
            reader.resize_workers(2)


def test_loader_prefetch_knobs_resize_live_queues():
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    def source():
        def gen():
            for i in range(50):
                yield {"x": np.full((4,), i)}
        return gen()

    loader = JaxDataLoader(None, 4, batch_source=source,
                           stage_to_device=False, host_prefetch=2)
    it = iter(loader)
    next(it)
    assert loader.host_prefetch == 2
    loader.host_prefetch = 6
    assert loader._queue.maxsize == 6
    loader.device_prefetch = 3
    assert loader.device_prefetch == 3
    with pytest.raises(ValueError):
        loader.host_prefetch = 0
    with pytest.raises(ValueError):
        loader.device_prefetch = 0
    loader.stop()
    loader.join()


def test_resize_bounded_queue_wakes_blocked_producer():
    from petastorm_tpu.utils import resize_bounded_queue

    q = queue.Queue(maxsize=1)
    q.put(1)
    landed = threading.Event()

    def blocked_put():
        q.put(2)
        landed.set()

    thread = threading.Thread(target=blocked_put, daemon=True)
    thread.start()
    time.sleep(0.05)
    assert not landed.is_set()
    resize_bounded_queue(q, 4)
    assert landed.wait(2.0)
    thread.join(timeout=2)


def test_client_ready_queue_depth_derives_from_credits():
    from petastorm_tpu.service.client import ServiceBatchSource

    source = ServiceBatchSource(("127.0.0.1", 1), credits=8)
    assert source._derived_ready_depth(2) == 16
    assert source._derived_ready_depth(1) == 8
    assert source._derived_ready_depth(100) == 256  # capped
    uncredited = ServiceBatchSource(("127.0.0.1", 1), credits=None)
    assert uncredited._derived_ready_depth(2) == 4   # legacy 2x streams
    assert uncredited._derived_ready_depth(5) == 10
    source.set_credits(2)
    assert source.credits == 2
    assert source._derived_ready_depth(2) == 4
    with pytest.raises(ValueError):
        source.set_credits(0)
    source.set_ready_queue_depth(32)
    assert source.ready_queue_depth == 32
    with pytest.raises(ValueError):
        ServiceBatchSource(("127.0.0.1", 1),
                           transform_placement="sideways")
    with pytest.raises(ValueError, match="transform"):
        ServiceBatchSource(("127.0.0.1", 1), transform_placement="local")


# ---------------------------------------------------------------------------
# transform placement through the service
# ---------------------------------------------------------------------------

def _double_ids(batch):
    out = dict(batch)
    out["id_double"] = np.asarray(batch["id"]) * 2
    return out


@pytest.mark.service
@pytest.mark.parametrize("placement", ["remote", "local"])
def test_transform_placement_round_trip(petastorm_dataset, placement):
    """The same batch transform produces identical data whether it runs
    worker-side (remote) or trainer-side (local), and the stage's time
    lands in the histogram of the side that ran it."""
    from petastorm_tpu.service import (BatchWorker, Dispatcher,
                                       ServiceBatchSource)
    from petastorm_tpu.telemetry.metrics import (
        CLIENT_TRANSFORM_SECONDS,
        WORKER_TRANSFORM_SECONDS,
    )

    worker_id = f"wt-{placement}"
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=1).start()
    worker = BatchWorker(petastorm_dataset.url,
                         dispatcher_address=dispatcher.address,
                         batch_size=7, worker_id=worker_id,
                         batch_transform=_double_ids,
                         reader_kwargs={"workers_count": 2}).start()
    try:
        source = ServiceBatchSource(dispatcher.address,
                                    transform=_double_ids,
                                    transform_placement=placement)
        got = {}
        client_before = CLIENT_TRANSFORM_SECONDS.labels().count
        for batch in source():
            for i, d in zip(batch["id"], batch["id_double"]):
                got[int(i)] = int(d)
        assert got == {int(i): 2 * int(i) for i in got}
        assert sorted(got) == sorted(
            int(row["id"]) for row in petastorm_dataset.rows)
        worker_count = WORKER_TRANSFORM_SECONDS.labels(worker_id).count
        client_count = CLIENT_TRANSFORM_SECONDS.labels().count \
            - client_before
        if placement == "remote":
            assert worker_count > 0 and client_count == 0
        else:
            assert worker_count == 0 and client_count > 0
    finally:
        worker.stop()
        dispatcher.stop()


# ---------------------------------------------------------------------------
# tier-1 smoke guard: the autotuned pipeline converges and stays bounded
# ---------------------------------------------------------------------------

def test_autotuned_pipeline_converges_and_stays_bounded(petastorm_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader

    bounds = {"workers_count": (1, 4), "host_prefetch": (1, 8),
              "device_prefetch": (1, 4)}
    reader = make_reader(petastorm_dataset.url, reader_pool_type="thread",
                         workers_count=1, num_epochs=40)
    loader = make_jax_dataloader(
        reader, 5, stage_to_device=False,
        autotune={"interval_s": 0.05, "hysteresis": 1, "bounds": bounds})
    rows = 0
    with loader:
        for batch in loader:
            rows += len(batch["id"])
    assert rows == 40 * len(petastorm_dataset.rows)
    controller = loader.autotune
    # Deterministic convergence gate (deflaked): the old assertions rode
    # the wall clock — on a loaded host the 0.05s window loop could fit
    # fewer than 4 rounds, or end mid-probe with noop_streak < 2. Gate on
    # the JOURNAL instead: drive the stopped controller's planning rounds
    # directly — post-iteration windows are idle (no rows moved), which
    # by the planner's contract never applies a decision and never resets
    # settled knobs, so the no-op streak grows deterministically.
    for _ in range(8):
        if controller.rounds >= 4 and controller.noop_streak >= 2:
            break
        controller.step()
    report = controller.report()
    assert report["rounds"] >= 4
    # Convergence: the decision trail went quiet — trailing rounds are
    # no-ops (the planner settled every candidate knob for the steady
    # bottleneck class).
    assert report["noop_streak"] >= 2
    # Bounded: no decision ever left the declared range, and the final
    # values sit inside it.
    for entry in report["trail"]:
        for decision in entry["decisions"]:
            lo, hi = bounds[decision["knob"]]
            assert lo <= decision["to"] <= hi
    for name, value in report["knobs"].items():
        lo, hi = bounds[name]
        assert lo <= value <= hi
    # The controller thread is gone once the iteration ended (the leak
    # guard would fail this test otherwise — but assert it explicitly).
    assert not loader.autotune.running


def test_autotune_disabled_is_default_and_inert(petastorm_dataset):
    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader

    reader = make_reader(petastorm_dataset.url, reader_pool_type="thread",
                         workers_count=1, num_epochs=1)
    loader = make_jax_dataloader(reader, 5, stage_to_device=False)
    with loader:
        rows = sum(len(b["id"]) for b in loader)
    assert rows == len(petastorm_dataset.rows)
    assert loader.autotune is None
    with pytest.raises(ValueError, match="autotune"):
        make_jax_dataloader(reader, 5, autotune="yes")


# ---------------------------------------------------------------------------
# telemetry journal + status rendering
# ---------------------------------------------------------------------------

def test_decisions_journaled_to_telemetry_and_status_renders():
    from petastorm_tpu.service.cli import render_autotune_status
    from petastorm_tpu.telemetry.metrics import (
        AUTOTUNE_DECISIONS,
        AUTOTUNE_KNOB_VALUE,
    )

    values = {"credits": 8}
    graph = PipelineGraph(
        [StageNode("decode", "worker", "trainer")], [],
        knobs=[Knob("credits", get=lambda: values["credits"],
                    set=lambda v: values.__setitem__("credits", v),
                    lo=1, hi=64, applies="next-stream")],
        signals={"rows": lambda: sig["rows"],
                 "stall_s": lambda: sig["stall"],
                 "queue_wait_s": lambda: 0.0, "decode_s": lambda: 0.0,
                 "dispatch_s": lambda: 0.0,
                 "credit_wait_s": lambda: sig["credit_wait"]})
    sig = {"rows": 0, "stall": 0.0, "credit_wait": 0.0}
    controller = AutotuneController(
        graph, interval_s=60,
        planner=Planner({"credits": KNOBS["credits"]}, hysteresis=1))
    before = AUTOTUNE_DECISIONS.labels("credits", "up").value
    controller._prev = (time.perf_counter() - 1.0, graph.snapshot())
    sig.update(rows=10000, stall=0.5, credit_wait=0.6)
    controller.step()
    assert values["credits"] == 16
    assert AUTOTUNE_DECISIONS.labels("credits", "up").value == before + 1
    assert AUTOTUNE_KNOB_VALUE.labels(controller._id,
                                      "credits").value == 16.0
    # The status tool's render, from the same shapes its /metrics.json
    # poll produces.
    text = render_autotune_status(
        {"knobs": {("0", "credits"): 8.0}, "decisions": {}},
        {"knobs": {("0", "credits"): 16.0},
         "decisions": {("credits", "up"): before + 1}})
    assert "credits=16" in text
    assert "credits:up" in text
    # Two controllers: values prefixed instead of merged.
    text = render_autotune_status(
        None, {"knobs": {("0", "credits"): 16.0, ("1", "credits"): 8.0},
               "decisions": {}})
    assert "0/credits=16" in text and "1/credits=8" in text
    assert "unreachable" in render_autotune_status(None, None)


def test_worker_bound_flips_packing_trainer_when_no_transform():
    """The packing stage's placement knob is the worker-bound class's
    lever when no batch transform is armed (docs/guides/llm.md): the
    planner falls through the absent transform knob and flips packing
    to the trainer; consumer-bound pushes it back."""
    knobs = {
        "credits": {"kind": "int", "lo": 1, "hi": 64,
                    "applies": "next-stream"},
        "packing_placement": {"kind": "choice",
                              "choices": ["worker", "trainer"],
                              "applies": "next-iteration"},
    }
    base = {"credits": 8, "packing_placement": "worker"}
    planner = Planner(knobs, hysteresis=2, placement_hysteresis=3)
    decisions = _plan_until_decision(
        planner, _profile(stall=0.6, recv_stall=0.9, knobs=dict(base)))
    assert [(d["knob"], d["direction"], d["to"]) for d in decisions] == \
        [("packing_placement", "flip", "trainer")]
    assert decisions[0]["applies"] == "next-iteration"

    back = Planner(knobs, hysteresis=2, placement_hysteresis=3)
    flipped = dict(base, packing_placement="trainer")
    decisions = _plan_until_decision(
        back, _profile(stall=0.01, queue_wait=0.5, knobs=flipped))
    assert [(d["knob"], d["direction"], d["to"]) for d in decisions] == \
        [("packing_placement", "flip", "worker")]
