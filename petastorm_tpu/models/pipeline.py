"""Pipeline-parallel encoder stack — the pp axis of the parallelism story.

The reference has no model compute at all (SURVEY.md §2: petastorm is a
data-input library); this module exists so the TPU delivery path exercises
every parallelism family a training stack uses: dp (batch sharding), tp
(tensor-parallel MLP in ``image_classifier``), sp (ring/Ulysses in
``sequence_model``), ep/model-parallel tables (``tabular_dlrm``) — and pp,
here.

The construction is the idiomatic JAX pipeline (scaling-book recipe):

- the stack's S homogeneous residual blocks live STACKED ``[S, ...]`` and
  shard over the mesh's ``"pp"`` axis — each device holds one stage's
  weights;
- inside ``shard_map``, a ``lax.scan`` over ``M + S - 1`` ticks runs the
  classic GPipe schedule: every tick each device applies its block to its
  current microbatch and ``ppermute``-shifts the activation to the next
  stage. Stage 0 injects microbatch ``t`` during the fill phase; stage
  S-1 records finished microbatches after the ``S-1``-tick bubble;
- ``lax.scan`` (not ``fori_loop``) keeps the whole schedule
  reverse-differentiable — backward is the same pipeline run by scan's
  transpose, with ``ppermute``'s transpose shifting gradients the other
  way. No hand-written backward schedule;
- warmup/drain ticks compute on clamped (repeated) microbatches whose
  outputs are never recorded, so they contribute exactly zero gradient.

Embed and classifier head are replicated (tiny next to the stack) and run
outside the shard_map; the pipeline maps ``[M, mb, d_model] →
[M, mb, d_model]``.

Two schedules:

- ``"gpipe"`` (above): all-forward then all-backward via ``lax.scan``'s
  transpose. Simple, but scan saves every tick's carry for the transpose —
  activation memory grows with M.
- ``"1f1b"`` (``pipeline_1f1b_loss_and_grads``): the fused
  one-forward-one-backward schedule — each tick every stage runs a (masked)
  forward for microbatch ``t - s`` AND a (masked) backward for microbatch
  ``t - (2S-1) + s``, with activations ppermuting down the pipeline and
  cotangents ppermuting back up. The backward is HAND-SCHEDULED (per-block
  vjp with the hidden activation rematerialized from the stashed input;
  gradients are returned directly, no outer autodiff), which is what makes
  the 1F1B memory claim real: the activation stash is a static
  ``[2S, mb, d]`` ring — O(S) regardless of M, where GPipe-via-scan holds
  O(M). Slot reuse is self-verifying: a live span ever exceeding 2S-1
  microbatches would corrupt gradients, so the oracle tests (grads ==
  ``jax.grad`` of the sequential stack, at M >> 2S) prove the bound.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_pipeline_params(rng, feature_dim, d_model=64, d_hidden=128,
                         num_stages=4, num_classes=10, dtype=jnp.float32):
    """Parameter pytree: replicated embed/head + ``[S, ...]``-stacked
    residual MLP blocks (shard the leading axis over ``"pp"``)."""
    keys = jax.random.split(rng, 4)
    s = lambda fan: 1.0 / jnp.sqrt(fan)  # noqa: E731
    return {
        "embed": jax.random.normal(keys[0], (feature_dim, d_model),
                                   dtype) * s(feature_dim),
        "w1": jax.random.normal(keys[1], (num_stages, d_model, d_hidden),
                                dtype) * s(d_model),
        "w2": jax.random.normal(keys[2], (num_stages, d_hidden, d_model),
                                dtype) * s(d_hidden),
        "head": jax.random.normal(keys[3], (d_model, num_classes),
                                  dtype) * s(d_model),
    }


def pipeline_param_partition_specs():
    """PartitionSpecs over a mesh with a ``"pp"`` axis: one stage's block
    per device; embed/head replicated."""
    return {"embed": P(), "w1": P("pp"), "w2": P("pp"), "head": P()}


def _block(w1, w2, x):
    """One pipeline stage: residual two-layer MLP (the stand-in for a
    transformer block — the schedule is what's under test here)."""
    return x + jax.nn.relu(x @ w1) @ w2


def _pipeline_body(w1, w2, x_mb, axis_name, num_stages, num_microbatches,
                   batch_axis=None):
    """Per-device pipeline schedule (runs inside shard_map).

    ``w1``/``w2``: this stage's block, ``[1, d, h]`` / ``[1, h, d]``.
    ``x_mb``: ``[M, mb, d]`` microbatches (replicated — every stage sees
    them, only stage 0 consumes them).
    Returns ``[1, M, mb, d]`` — garbage except on the last stage, whose
    copy the wrapper selects from the stacked ``out_specs=P("pp")`` result.
    """
    stage = jax.lax.axis_index(axis_name)
    last = num_stages - 1
    perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
    mb_shape = x_mb.shape[1:]

    def tick(carry, t):
        act, outs = carry
        idx = jnp.clip(t, 0, num_microbatches - 1)
        inp = jnp.where(stage == 0,
                        jax.lax.dynamic_index_in_dim(x_mb, idx, axis=0,
                                                     keepdims=False),
                        act)
        out = _block(w1[0], w2[0], inp)
        # Record finished microbatch t-(S-1) on the last stage only; the
        # masked update keeps warmup/drain compute out of the loss (and
        # therefore out of the gradients).
        out_t = t - last
        out_idx = jnp.clip(out_t, 0, num_microbatches - 1)
        record = (out_t >= 0) & (stage == last)
        current = jax.lax.dynamic_index_in_dim(outs, out_idx, axis=0,
                                               keepdims=False)
        outs = jax.lax.dynamic_update_index_in_dim(
            outs, jnp.where(record, out, current), out_idx, axis=0)
        act_next = jax.lax.ppermute(out, axis_name, perm)
        return (act_next, outs), None

    init_act = jnp.zeros(mb_shape, x_mb.dtype)
    init_outs = jnp.zeros_like(x_mb)

    from petastorm_tpu.models._shard_compat import mark_varying

    def varying(v):
        axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
        return mark_varying(v, axes)

    (_, outs), _ = jax.lax.scan(
        tick, (varying(init_act), varying(init_outs)),
        jnp.arange(num_microbatches + num_stages - 1))
    return outs[None]


def pipeline_forward(params, x_mb, mesh, axis_name="pp", batch_axis=None):
    """``[M, mb, d_model]`` microbatches → ``[M, mb, d_model]`` through the
    S-stage pipeline sharded over ``mesh[axis_name]``.

    ``batch_axis``: mesh axis the microbatch dim (axis 1) is sharded over —
    dp × pp: each (data, pp) device runs the same schedule on its slice of
    every microbatch; the ``ppermute`` shifts stay within each data group.
    """
    from jax import shard_map

    num_stages = mesh.shape[axis_name]
    if params["w1"].shape[0] != num_stages:
        raise ValueError(
            f"params stack {params['w1'].shape[0]} stages but the mesh's "
            f"{axis_name!r} axis has {num_stages} devices")
    body = functools.partial(_pipeline_body, axis_name=axis_name,
                             num_stages=num_stages,
                             num_microbatches=x_mb.shape[0],
                             batch_axis=batch_axis)
    x_spec = P(None, batch_axis, None)
    stacked = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), x_spec),
        out_specs=P(axis_name, None, batch_axis, None))(
        params["w1"], params["w2"], x_mb)
    return stacked[-1]  # the last stage's copy holds the real outputs


def apply_pipeline_model(params, features, mesh, axis_name="pp",
                         num_microbatches=4, batch_axis=None):
    """``features``: [B, F] → f32 logits [B, C]; B must divide into
    ``num_microbatches`` equal microbatches. ``batch_axis``: mesh axis for
    data parallelism over the microbatch dim (dp × pp)."""
    b = features.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} does not divide into "
                         f"{num_microbatches} microbatches")
    if batch_axis is not None and mesh is not None:
        data = mesh.shape[batch_axis]
        if (b // num_microbatches) % data:
            raise ValueError(
                f"microbatch size {b // num_microbatches} does not shard "
                f"over the {data}-device {batch_axis!r} axis")
    x = features @ params["embed"]
    x_mb = x.reshape(num_microbatches, b // num_microbatches, -1)
    out = pipeline_forward(params, x_mb, mesh, axis_name,
                           batch_axis=batch_axis)
    logits = out.reshape(b, -1) @ params["head"]
    return logits.astype(jnp.float32)


def reference_forward(params, features):
    """Sequential oracle: the same stack applied block by block on one
    device — the pipeline must match it exactly."""
    x = features @ params["embed"]
    for i in range(params["w1"].shape[0]):
        x = _block(params["w1"][i], params["w2"][i], x)
    return (x @ params["head"]).astype(jnp.float32)


def _1f1b_body(w1, w2, head, x_mb, labels_mb, mask_mb, *, axis_name,
               num_stages, num_microbatches, num_classes, batch_axis=None):
    """Per-device fused 1F1B schedule (runs inside shard_map).

    Tick ``t``: forward for microbatch ``m1 = t - s`` (stage ``s``) and
    backward for ``m2 = t - (2S-1) + s`` — the last stage turns around in
    one tick (fwd at ``m + S - 1``, bwd at ``m + S``), so in steady state
    it alternates fwd(m)/bwd(m-1), the classic 1F1B picture. The input
    activation of an in-flight microbatch waits in a ``[2S, mb, d]`` ring
    stash: the live span at stage ``s`` is ``m1 - m2 = 2(S - s) - 1 ≤
    2S - 1 < 2S`` slots, so first-writer-wins never collides.

    Returns per-device ``(dw1[1], dw2[1], dhead, dx[1], loss_sum, count)``
    with dhead/loss/count psum-replicated over the pipeline axis and
    ``dx`` returned UN-reduced (stacked by the wrapper's out_specs; only
    stage 0's slice is nonzero — select it, do not psum), plus weight
    grads psum-reduced over ``batch_axis`` when given.
    """
    stage = jax.lax.axis_index(axis_name)
    s_count, m_count = num_stages, num_microbatches
    last = s_count - 1
    k_slots = 2 * s_count
    fwd_perm = [(i, (i + 1) % s_count) for i in range(s_count)]
    bwd_perm = [(i, (i - 1) % s_count) for i in range(s_count)]
    mb_shape = x_mb.shape[1:]
    w1_s, w2_s = w1[0], w2[0]

    from petastorm_tpu.models._shard_compat import mark_varying

    def varying(v):
        axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
        return mark_varying(v, axes)

    def tick(carry, t):
        (act_in, cot_in, pending, stash, dx,
         dw1, dw2, dhead, lsum, cnt) = carry

        # ---- backward half (consumes the PREVIOUS tick's pending/cot) ---
        m2 = t - (2 * s_count - 1) + stage
        b_valid = (m2 >= 0) & (m2 < m_count)
        m2c = jnp.clip(m2, 0, m_count - 1)
        xb = jax.lax.dynamic_index_in_dim(stash, m2c % k_slots, axis=0,
                                          keepdims=False)
        g = jnp.where(stage == last, pending, cot_in)
        pre = xb @ w1_s
        hidden = jax.nn.relu(pre)  # rematerialized from the stashed input
        dh = g @ w2_s.T
        dpre = dh * (pre > 0)
        dxb = g + dpre @ w1_s.T
        dw1 = dw1 + jnp.where(b_valid, xb.T @ dpre, 0.0)
        dw2 = dw2 + jnp.where(b_valid, hidden.T @ g, 0.0)
        cur_dx = jax.lax.dynamic_index_in_dim(dx, m2c, axis=0,
                                              keepdims=False)
        dx = jax.lax.dynamic_update_index_in_dim(
            dx, jnp.where(b_valid & (stage == 0), dxb, cur_dx), m2c,
            axis=0)

        # ---- forward half ----------------------------------------------
        m1 = t - stage
        f_valid = (m1 >= 0) & (m1 < m_count)
        m1c = jnp.clip(m1, 0, m_count - 1)
        x = jnp.where(stage == 0,
                      jax.lax.dynamic_index_in_dim(x_mb, m1c, axis=0,
                                                   keepdims=False),
                      act_in)
        out = _block(w1_s, w2_s, x)
        slot = m1c % k_slots
        cur_slot = jax.lax.dynamic_index_in_dim(stash, slot, axis=0,
                                                keepdims=False)
        stash = jax.lax.dynamic_update_index_in_dim(
            stash, jnp.where(f_valid, x, cur_slot), slot, axis=0)

        # Last stage: loss for m1 + the cotangent seed its own backward
        # consumes NEXT tick (fwd at m+S-1, bwd at m+S).
        logits = out @ head
        label = jax.lax.dynamic_index_in_dim(labels_mb, m1c, axis=0,
                                             keepdims=False)
        msk = jax.lax.dynamic_index_in_dim(mask_mb, m1c, axis=0,
                                           keepdims=False)
        seed = f_valid & (stage == last)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, label[:, None], axis=1)[:, 0]
        lsum = lsum + jnp.where(seed, jnp.where(msk, nll, 0.0).sum(), 0.0)
        cnt = cnt + jnp.where(seed,
                              msk.sum().astype(jnp.float32), 0.0)
        onehot = jax.nn.one_hot(label, num_classes, dtype=logits.dtype)
        dlogits = jnp.where(seed,
                            (jax.nn.softmax(logits) - onehot)
                            * msk[:, None].astype(logits.dtype), 0.0)
        dhead = dhead + out.T @ dlogits
        pending = dlogits @ head.T

        act_out = jax.lax.ppermute(out, axis_name, fwd_perm)
        cot_out = jax.lax.ppermute(jnp.where(b_valid, dxb, 0.0),
                                   axis_name, bwd_perm)
        return (act_out, cot_out, pending, stash, dx,
                dw1, dw2, dhead, lsum, cnt), None

    zero = jnp.zeros(mb_shape, x_mb.dtype)
    init = (varying(zero), varying(zero), varying(zero),
            varying(jnp.zeros((k_slots,) + mb_shape, x_mb.dtype)),
            varying(jnp.zeros_like(x_mb)),
            varying(jnp.zeros_like(w1_s)), varying(jnp.zeros_like(w2_s)),
            varying(jnp.zeros_like(head)),
            varying(jnp.zeros((), jnp.float32)),
            varying(jnp.zeros((), jnp.float32)))
    carry, _ = jax.lax.scan(
        tick, init, jnp.arange(m_count + 2 * s_count - 1))
    (_, _, _, _, dx, dw1, dw2, dhead, lsum, cnt) = carry
    # dhead/loss/count live on one stage only — psum replicates the small
    # ones across the pipeline axis (zeros elsewhere). dx is [M, mb, d]
    # (only stage 0's copy is nonzero): return it STACKED over the pp axis
    # and let the wrapper select stage 0's slice — an allreduce of the
    # full-batch cotangent would move S copies of it to propagate one.
    dhead = jax.lax.psum(dhead, axis_name)
    lsum = jax.lax.psum(lsum, axis_name)
    cnt = jax.lax.psum(cnt, axis_name)
    if batch_axis:
        dw1 = jax.lax.psum(dw1, batch_axis)
        dw2 = jax.lax.psum(dw2, batch_axis)
        dhead = jax.lax.psum(dhead, batch_axis)
        lsum = jax.lax.psum(lsum, batch_axis)
        cnt = jax.lax.psum(cnt, batch_axis)
    return dw1[None], dw2[None], dhead, dx[None], lsum, cnt


def pipeline_1f1b_loss_and_grads(params, features, labels, mask, mesh,
                                 axis_name="pp", num_microbatches=4,
                                 batch_axis=None):
    """Fused 1F1B forward+backward over the stage-sharded stack: returns
    ``(loss, grads)`` with ``grads`` matching ``jax.grad`` of the
    sequential/GPipe loss (masked-mean cross-entropy) to float tolerance.

    Embed runs outside the schedule (its backward is
    ``features^T @ dx`` from the stage-0 input cotangents the schedule
    emits); the head's forward+backward ride the last stage's ticks, as in
    a real 1F1B deployment where the head lives on the final stage.
    """
    from jax import shard_map

    num_stages = mesh.shape[axis_name]
    if params["w1"].shape[0] != num_stages:
        raise ValueError(
            f"params stack {params['w1'].shape[0]} stages but the mesh's "
            f"{axis_name!r} axis has {num_stages} devices")
    b = features.shape[0]
    if b % num_microbatches:
        raise ValueError(f"batch {b} does not divide into "
                         f"{num_microbatches} microbatches")
    mb = b // num_microbatches
    if batch_axis is not None and mb % mesh.shape[batch_axis]:
        raise ValueError(
            f"microbatch size {mb} does not shard over the "
            f"{mesh.shape[batch_axis]}-device {batch_axis!r} axis")
    x = features @ params["embed"]
    d_model = x.shape[-1]
    x_mb = x.reshape(num_microbatches, mb, d_model)
    labels_mb = labels.reshape(num_microbatches, mb)
    mask_mb = mask.reshape(num_microbatches, mb)
    body = functools.partial(
        _1f1b_body, axis_name=axis_name, num_stages=num_stages,
        num_microbatches=num_microbatches,
        num_classes=params["head"].shape[-1], batch_axis=batch_axis)
    x_spec = P(None, batch_axis, None)
    row_spec = P(None, batch_axis)
    dw1, dw2, dhead, dx_stacked, lsum, cnt = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(), x_spec, row_spec,
                  row_spec),
        out_specs=(P(axis_name), P(axis_name), P(),
                   P(axis_name, None, batch_axis, None), P(), P()))(
        params["w1"], params["w2"], params["head"], x_mb, labels_mb,
        mask_mb)
    dx = dx_stacked[0]  # stage 0's copy holds the input cotangents
    denom = jnp.maximum(cnt, 1.0)
    loss = lsum / denom
    dx_flat = dx.reshape(b, d_model) / denom
    grads = {
        # Contraction over the batch dim — under jit XLA inserts the
        # data-parallel psum from the shardings.
        "embed": features.T @ dx_flat,
        "w1": dw1 / denom,
        "w2": dw2 / denom,
        "head": dhead / denom,
    }
    return loss, grads


def make_pipeline_train_step(learning_rate=0.05, mesh=None, axis_name="pp",
                             num_microbatches=4, batch_axis=None,
                             schedule="gpipe"):
    """``step(params, features, labels, mask) -> (params, loss)`` — masked
    cross-entropy + SGD through the pipeline schedule.

    ``schedule="gpipe"``: backward is the transposed scan (no hand-written
    schedule). ``schedule="1f1b"``: the fused hand-scheduled
    one-forward-one-backward pipeline (O(S) activation stash — see
    :func:`pipeline_1f1b_loss_and_grads`); gradients match gpipe's to
    float tolerance.
    """
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule {schedule!r} is not 'gpipe' or '1f1b'")
    if schedule == "1f1b":
        def step_1f1b(params, features, labels, mask):
            loss, grads = pipeline_1f1b_loss_and_grads(
                params, features, labels, mask, mesh, axis_name=axis_name,
                num_microbatches=num_microbatches, batch_axis=batch_axis)
            new_params = jax.tree_util.tree_map(
                lambda p, g: (p - learning_rate * g).astype(p.dtype),
                params, grads)
            return new_params, loss

        return step_1f1b

    def loss_fn(params, features, labels, mask):
        logits = apply_pipeline_model(params, features, mesh,
                                      axis_name=axis_name,
                                      num_microbatches=num_microbatches,
                                      batch_axis=batch_axis)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        nll = jnp.where(mask, nll, 0.0)
        return nll.sum() / jnp.maximum(mask.sum(), 1).astype(jnp.float32)

    def step(params, features, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, features, labels,
                                                  mask)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype),
            params, grads)
        return new_params, loss

    return step
