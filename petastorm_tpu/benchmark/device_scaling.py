"""MULTICHIP scaling measurement for the device decode stage.

Answers one question with a number: does the loader's sharding-aware
direct-to-device delivery + fused on-device decode scale with the device
count? Per-device batch is held FIXED while the mesh grows (weak scaling —
the deployment shape: more chips, more global rows per step), raw uint8
batches are pre-collated in memory so Parquet/codec throughput is not in
the loop (each host feeds only its own devices in production), and each
step's consumption is forced with ``block_until_ready``. Near-linear
aggregate rows/s from 1 → N devices means per-device delivery cost is flat:
every shard's H2D lands directly on its target device and the decode kernel
runs device-parallel, with no serial host stage growing with N.

Two numbers per device count, because the two halves of delivery scale
differently on a SINGLE-CONTROLLER host:

- ``rows_per_sec`` — end to end: per-shard ``device_put`` staging + the
  fused decode kernel + a consuming step. On one controller the staging
  memcpys are serial host work that grows with the global batch, so this
  number's scaling is bounded by host copy bandwidth (on a pod each host
  stages only its own devices and this term stays flat).
- ``decode_kernel_rows_per_sec`` — the device-parallel portion isolated:
  the fused decode/augment kernel executed over already-staged sharded
  raw batches. This is the work the stage moved ONTO the accelerators,
  and it scales with the device count.

Used by ``bench.py``'s ``multichip_scaling`` leg (a virtual-CPU-mesh
subprocess on the single-chip bench host) and by
``__graft_entry__.dryrun_multichip`` (the 8-device MULTICHIP artifact).
Genuinely parallel device execution needs >= N host cores when the
"devices" are virtual CPU devices — results carry ``host_cores`` so a
core-starved run is readable as such.
"""

from __future__ import annotations

import os
import time

import numpy as np


def measure_device_stage_scaling(device_counts=(1, 8), per_device_batch=64,
                                 steps=24, image_shape=(64, 64, 3),
                                 repeats=2, seed=0):
    """Aggregate rows/s of sharded device-stage delivery per device count.

    :return: dict with per-count ``rows_per_sec``, the end-to-end
        ``scaling`` ratio (largest vs smallest count), and environment
        facts (``host_cores``, ``device_platform``).
    """
    import jax

    from petastorm_tpu.jax_utils import (DeviceStage, JaxDataLoader,
                                         batch_sharding)

    devices = jax.devices()
    counts = sorted(set(int(n) for n in device_counts))
    if counts[-1] > len(devices):
        raise RuntimeError(
            f"scaling sweep needs {counts[-1]} devices, have {len(devices)}")
    rng = np.random.RandomState(seed)
    results, kernel_results = {}, {}
    for n in counts:
        from jax.sharding import Mesh

        mesh = Mesh(np.array(devices[:n]).reshape(n), ("data",))
        sharding = batch_sharding(mesh, "data")
        global_batch = per_device_batch * n
        # One raw batch reused every step: the measured loop pays the full
        # per-step delivery + on-device decode cost; synthesis does not.
        images = rng.randint(0, 255, (global_batch,) + tuple(image_shape),
                             dtype=np.uint8)
        labels = (np.arange(global_batch) % 10).astype(np.int32)

        def source():
            return iter([{"image": images, "label": labels}] * steps)

        consume = jax.jit(lambda x: x.sum())
        # One stage per device count, so the warm pass actually warms the
        # kernel: jax.jit caches per wrapped function, and a fresh
        # DeviceStage inside the pass would bill a retrace+compile to
        # every timed window (compressing the scaling ratio toward 1).
        stage = DeviceStage(normalize=(127.5, 127.5), seed=seed)

        def one_pass():
            loader = JaxDataLoader(None, global_batch, batch_source=source,
                                   sharding=sharding, device_stage=stage,
                                   max_batches=steps,
                                   non_tensor_policy="drop")
            rows = 0
            t0 = time.perf_counter()
            with loader:
                for batch in loader:
                    # Force execution of the decode kernel + the step on
                    # every shard — dispatch-only timing would flatter N.
                    jax.block_until_ready(consume(batch["image"]))
                    rows += global_batch
            return rows / (time.perf_counter() - t0)

        one_pass()  # warm: compile the decode kernel + consume at this N
        results[n] = max(one_pass() for _ in range(max(1, repeats)))

        # Device-parallel portion isolated: the fused decode kernel over
        # pre-staged sharded raw batches (donation off so the prestaged
        # inputs survive re-execution; a few distinct batches cycled so no
        # step reuses the previous step's output cache).
        from petastorm_tpu.jax_utils.sharding import (
            local_data_to_global_array,
        )

        kstage = DeviceStage(normalize=(127.5, 127.5), seed=seed,
                             donate=False)
        prestaged = [
            local_data_to_global_array(
                sharding, rng.randint(0, 255,
                                      (global_batch,) + tuple(image_shape),
                                      dtype=np.uint8))
            for _ in range(4)]

        def kernel_pass():
            outs = []
            t0 = time.perf_counter()
            for s in range(steps):
                outs.append(kstage.apply(
                    {"image": prestaged[s % len(prestaged)]}, s))
            jax.block_until_ready(outs)
            return steps * global_batch / (time.perf_counter() - t0)

        kernel_pass()  # warm/compile
        kernel_results[n] = max(kernel_pass()
                                for _ in range(max(1, repeats)))
    lo, hi = counts[0], counts[-1]
    return {
        "metric": "device_stage_scaling_rows_per_sec",
        "per_device_batch": per_device_batch,
        "steps": steps,
        "image_shape": list(image_shape),
        "device_counts": counts,
        "rows_per_sec": {str(n): round(results[n], 1) for n in counts},
        "scaling": round(results[hi] / results[lo], 2),
        "decode_kernel_rows_per_sec": {str(n): round(kernel_results[n], 1)
                                       for n in counts},
        "decode_kernel_scaling": round(kernel_results[hi]
                                       / kernel_results[lo], 2),
        "scaling_devices": f"{lo}->{hi}",
        "host_cores": os.cpu_count(),
        "device_platform": devices[0].platform,
        "note": "rows_per_sec includes the single-controller host's serial "
                "per-shard staging memcpys (flat per host on a pod); "
                "decode_kernel_* is the device-parallel decode itself — "
                "virtual CPU devices need >= device_count host cores to "
                "execute in parallel",
    }
