"""One structured logger helper for the whole service stack.

A chaos run interleaves log lines from a dispatcher, several workers, and a
client heartbeat thread in one stream; with each module configuring plain
``logging.getLogger(__name__)`` the reader has to infer *which* worker and
*which* fencing epoch a line belongs to from its message text. This helper
standardizes:

- **namespace** — every service logger lives under
  ``petastorm_tpu.service.<module>`` (so one
  ``logging.getLogger("petastorm_tpu.service").setLevel(...)`` governs the
  stack);
- **context fields** — ``bind(worker_id=..., fencing_epoch=...)`` attaches
  ``key=value`` pairs appended to every line (and per-call ``**fields`` add
  one-off pairs), machine-grepable: ``grep 'worker_id=bench-worker-1'``
  reconstructs one node's timeline from an interleaved run.

Usage::

    logger = service_logger(__name__)                 # module level
    self._log = logger.bind(worker_id=self.worker_id) # instance context
    self._log.warning("lease missed", fencing_epoch=7)
    # -> "lease missed | worker_id=w-1 fencing_epoch=7"
"""

from __future__ import annotations

import logging

_SERVICE_ROOT = "petastorm_tpu.service"


def _canonical_name(name):
    """Map any module name to its ``petastorm_tpu.service.*`` namespace
    (idempotent for names already under it; other callers keep their own)."""
    if name.startswith(_SERVICE_ROOT) or not name.startswith("petastorm_tpu"):
        return name
    return f"{_SERVICE_ROOT}.{name.rsplit('.', 1)[-1]}"


class StructuredLogger:
    """A thin wrapper over :mod:`logging` that appends bound + per-call
    context fields as ``key=value`` pairs. Cheap by construction: fields
    are formatted only when the record will actually be emitted."""

    __slots__ = ("_logger", "_context")

    def __init__(self, logger, context=None):
        self._logger = logger
        self._context = dict(context or {})

    def bind(self, **fields):
        """A child logger with ``fields`` merged into the bound context."""
        merged = dict(self._context)
        merged.update(fields)
        return StructuredLogger(self._logger, merged)

    @property
    def name(self):
        return self._logger.name

    def _log(self, level, msg, args, exc_info=False, **fields):
        if not self._logger.isEnabledFor(level):
            return
        # %-format the caller's args BEFORE appending context fields, and
        # hand logging a fully-formatted string with no args: a field
        # value containing '%' (a client_id off the wire, a reason
        # string) must never be re-interpreted as a format directive —
        # that would raise inside logging and DROP the line.
        if args:
            try:
                msg = msg % args
            except (TypeError, ValueError):  # malformed caller format:
                msg = f"{msg} {args!r}"      # degrade, never drop the line
        context = dict(self._context)
        context.update(fields)
        if context:
            suffix = " ".join(f"{k}={v}" for k, v in context.items())
            msg = f"{msg} | {suffix}"
        self._logger.log(level, msg, exc_info=exc_info)

    def debug(self, msg, *args, **fields):
        self._log(logging.DEBUG, msg, args, **fields)

    def info(self, msg, *args, **fields):
        self._log(logging.INFO, msg, args, **fields)

    def warning(self, msg, *args, **fields):
        self._log(logging.WARNING, msg, args, **fields)

    def error(self, msg, *args, **fields):
        self._log(logging.ERROR, msg, args, **fields)

    def exception(self, msg, *args, **fields):
        self._log(logging.ERROR, msg, args, exc_info=True, **fields)

    def isEnabledFor(self, level):  # noqa: N802 - logging API parity
        return self._logger.isEnabledFor(level)


def service_logger(name, **context):
    """The structured logger for a service module: canonical
    ``petastorm_tpu.service.*`` namespace plus optional bound context."""
    return StructuredLogger(logging.getLogger(_canonical_name(name)),
                            context)
