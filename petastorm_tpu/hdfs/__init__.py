"""HDFS namenode resolution (reference parity: ``petastorm/hdfs/``)."""
