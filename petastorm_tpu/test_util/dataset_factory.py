"""Synthetic dataset factory — the fixture nearly every behavioral test reads.

Reference parity: ``petastorm/tests/test_common.py`` (``TestSchema``,
``create_test_dataset``, ``create_test_scalar_dataset``) — SURVEY.md §2.7.
Differences: materialization is pyarrow-native (no Spark) and the schema is
arrow-typed.
"""

from __future__ import annotations

from decimal import Decimal

import numpy as np

from petastorm_tpu.etl.metadata import materialize_rows, write_rows
from petastorm_tpu.schema.codecs import (
    CompressedImageCodec,
    CompressedNdarrayCodec,
    NdarrayCodec,
    ScalarCodec,
)
from petastorm_tpu.schema.unischema import Unischema, UnischemaField

TestSchema = Unischema("TestSchema", [
    UnischemaField("id", np.int64, (), ScalarCodec(), False),
    UnischemaField("id2", np.int32, (), ScalarCodec(), False),
    UnischemaField("partition_key", str, (), ScalarCodec(), False),
    UnischemaField("python_primitive_uint8", np.uint8, (), ScalarCodec(), False),
    UnischemaField("image_png", np.uint8, (16, 32, 3), CompressedImageCodec("png"), False),
    UnischemaField("matrix", np.float32, (4, 8), NdarrayCodec(), False),
    UnischemaField("matrix_nullable", np.float64, (2, 3), CompressedNdarrayCodec(), True),
    UnischemaField("decimal", Decimal, (), ScalarCodec(), False),
    UnischemaField("string_value", str, (), ScalarCodec(), False),
    UnischemaField("sensor_name", str, (), ScalarCodec(), False),
    UnischemaField("timestamp_s", np.int64, (), ScalarCodec(), False),
])


def make_test_row(index, rng=None):
    rng = rng or np.random.RandomState(index)
    return {
        "id": index,
        "id2": index % 5,
        "partition_key": f"p_{index % 4}",
        "python_primitive_uint8" : np.uint8(index % 255),
        "image_png": rng.randint(0, 255, (16, 32, 3), dtype=np.uint8),
        "matrix": rng.rand(4, 8).astype(np.float32),
        "matrix_nullable": (rng.rand(2, 3).astype(np.float64)
                            if index % 3 else None),
        "decimal": Decimal(f"{index}.{index % 10}"),
        "string_value": f"string_{index}",
        "sensor_name": f"sensor_{index % 2}",
        "timestamp_s": 1_000_000 + index,
    }


def create_test_dataset(dataset_url, rows_count=30, rows_per_row_group=10,
                        rows_per_file=None, **write_kwargs):
    """Write a petastorm-format synthetic dataset; returns the source rows."""
    rows = [make_test_row(i) for i in range(rows_count)]
    materialize_rows(dataset_url, TestSchema, rows,
                     rows_per_row_group=rows_per_row_group,
                     rows_per_file=rows_per_file, **write_kwargs)
    return rows


ScalarSchema = Unischema("ScalarSchema", [
    UnischemaField("id", np.int64, (), None, False),
    UnischemaField("float_col", np.float64, (), None, False),
    UnischemaField("int_col", np.int32, (), None, False),
    UnischemaField("string_col", str, (), None, False),
])


def create_test_scalar_dataset(dataset_url, rows_count=30,
                               rows_per_row_group=10, **write_kwargs):
    """Plain-Parquet dataset (no petastorm metadata) for make_batch_reader."""
    rows = [{
        "id": i,
        "float_col": i * 1.5,
        "int_col": np.int32(i * 2),
        "string_col": f"value_{i}",
    } for i in range(rows_count)]
    write_rows(dataset_url, ScalarSchema, rows,
               rows_per_row_group=rows_per_row_group, **write_kwargs)
    return rows
