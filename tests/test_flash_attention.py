"""Pallas flash-attention numerics vs the dense reference (interpret mode on
the CPU test backend; Mosaic lowering exercises on real TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from petastorm_tpu.models.sequence_model import attention_reference
from petastorm_tpu.ops import flash_attention


def _qkv(b=2, t=48, h=2, d=16, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, t, h, d).astype(dtype))
                 for _ in range(3))


def test_matches_reference_single_block():
    q, k, v = _qkv(t=16)
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(q, k, v)),
                               rtol=1e-5, atol=1e-5)


def test_matches_reference_multi_block_online_softmax():
    q, k, v = _qkv(t=64)
    # 4 K blocks: the online max/sum rescaling path is exercised.
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(q, k, v)),
                               rtol=1e-5, atol=1e-5)


def test_ragged_lengths_are_padded_and_masked():
    q, k, v = _qkv(t=50)  # not a multiple of the block
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(q, k, v)),
                               rtol=1e-5, atol=1e-5)


def test_cross_attention_lengths():
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 24, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 40, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 40, 2, 8).astype(np.float32))
    out = flash_attention(q, k, v, block_q=8, block_k=16)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(attention_reference(q, k, v)),
                               rtol=1e-5, atol=1e-5)


def test_bfloat16_inputs():
    q, k, v = _qkv(t=32, dtype=np.float32)
    q, k, v = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(q, k, v, block_q=16, block_k=16)
    assert out.dtype == jnp.bfloat16
    ref = attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_gradients_flow_and_match_reference():
    q, k, v = _qkv(t=32, d=8)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, 16, 16) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(q, k, v) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_causal_matches_masked_reference():
    from petastorm_tpu.ops.flash_attention import _attention_reference

    q, k, v = _qkv(t=48, seed=8)
    out = flash_attention(q, k, v, block_q=16, block_k=16, causal=True)
    ref = _attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # Causal must differ from full attention (sanity that the mask bites).
    full = flash_attention(q, k, v, block_q=16, block_k=16)
    assert not np.allclose(np.asarray(out), np.asarray(full))


def test_causal_cross_lengths_suffix_alignment():
    from petastorm_tpu.ops.flash_attention import _attention_reference

    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 8, 2, 8).astype(np.float32))   # suffix
    k = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
    out = flash_attention(q, k, v, block_q=8, block_k=16, causal=True)
    ref = _attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_causal_more_queries_than_keys_is_nan_free():
    from petastorm_tpu.ops.flash_attention import _attention_reference

    rng = np.random.RandomState(11)
    q = jnp.asarray(rng.randn(1, 16, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 8, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 8, 2, 8).astype(np.float32))
    # Suffix alignment: the first 8 query rows precede every key -> fully
    # masked -> must be exactly zero, nan-free, in forward AND backward,
    # and kernel and oracle must agree.
    out = flash_attention(q, k, v, block_q=8, block_k=8, causal=True)
    ref = _attention_reference(q, k, v, causal=True)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(ref)).all()
    np.testing.assert_allclose(np.asarray(out[:, :8]), 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    grads = jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, 8, 8, None, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()


def test_causal_gradients_match_reference():
    from petastorm_tpu.ops.flash_attention import _attention_reference

    q, k, v = _qkv(t=32, d=8, seed=10)
    g_flash = jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, 16, 16, None, True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda a, b, c: jnp.sum(
        _attention_reference(a, b, c, causal=True).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_seq_model_flash_path_matches_dense():
    from petastorm_tpu.models.sequence_model import (apply_seq_model,
                                                     init_seq_params)

    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=6,
                             d_model=32, num_heads=4)
    windows = np.random.RandomState(5).randn(4, 24, 6).astype(np.float32)
    dense = apply_seq_model(params, jnp.asarray(windows), num_heads=4,
                            compute_dtype=jnp.float32)
    flash = apply_seq_model(params, jnp.asarray(windows), num_heads=4,
                            compute_dtype=jnp.float32, attn_impl="flash")
    np.testing.assert_allclose(np.asarray(flash), np.asarray(dense),
                               rtol=1e-4, atol=1e-4)


def test_jit_composes():
    q, k, v = _qkv(t=32)
    f = jax.jit(lambda a, b, c: flash_attention(a, b, c, 16, 16))
    np.testing.assert_allclose(np.asarray(f(q, k, v)),
                               np.asarray(attention_reference(q, k, v)),
                               rtol=1e-5, atol=1e-5)


# --- hand-tiled flash backward (round 4) ----------------------------------

def _ref_grads(q, k, v, causal=False):
    from petastorm_tpu.ops.flash_attention import _attention_reference
    return jax.grad(lambda a, b, c: jnp.sum(
        _attention_reference(a, b, c, causal=causal).astype(jnp.float32)
        ** 2), argnums=(0, 1, 2))(q, k, v)


def _flash_grads(q, k, v, bq, bk, causal=False, bwd_impl="flash"):
    return jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, bq, bk, None, causal, bwd_impl) ** 2),
        argnums=(0, 1, 2))(q, k, v)


def test_flash_bwd_ragged_lengths():
    q, k, v = _qkv(t=50, d=8, seed=21)  # t does not divide the block
    for a, b in zip(_flash_grads(q, k, v, 16, 16), _ref_grads(q, k, v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bwd_causal_ragged():
    q, k, v = _qkv(t=50, d=8, seed=22)
    for a, b in zip(_flash_grads(q, k, v, 16, 16, causal=True),
                    _ref_grads(q, k, v, causal=True)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bwd_causal_cross_lengths():
    rng = np.random.RandomState(23)
    q = jnp.asarray(rng.randn(2, 24, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(2, 40, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(2, 40, 2, 8).astype(np.float32))
    for a, b in zip(_flash_grads(q, k, v, 8, 16, causal=True),
                    _ref_grads(q, k, v, causal=True)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bwd_causal_more_queries_than_keys():
    rng = np.random.RandomState(24)
    q = jnp.asarray(rng.randn(1, 16, 2, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 8, 2, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 8, 2, 8).astype(np.float32))
    flash = _flash_grads(q, k, v, 8, 8, causal=True)
    for g in flash:
        assert np.isfinite(np.asarray(g)).all()
    for a, b in zip(flash, _ref_grads(q, k, v, causal=True)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bwd_bfloat16():
    q, k, v = _qkv(t=32, d=8, seed=25)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    flash = _flash_grads(qb, kb, vb, 16, 16)
    ref = _ref_grads(q, k, v)
    for a, b in zip(flash, ref):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b), rtol=6e-2, atol=6e-2)


def test_flash_bwd_reference_oracle_path():
    q, k, v = _qkv(t=48, d=8, seed=26)
    flash = _flash_grads(q, k, v, 16, 16, causal=True, bwd_impl="flash")
    oracle = _flash_grads(q, k, v, 16, 16, causal=True,
                          bwd_impl="reference")
    for a, b in zip(flash, oracle):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bwd_under_jit():
    q, k, v = _qkv(t=32, d=8, seed=27)
    f = jax.jit(lambda a, b, c: jax.grad(
        lambda x, y, z: jnp.sum(flash_attention(x, y, z, 16, 16) ** 2),
        argnums=(0, 1, 2))(a, b, c))
    for a, b in zip(f(q, k, v), _ref_grads(q, k, v)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


# --- per-example kv_lengths (round 4) -------------------------------------

def _lens_oracle(q, k, v, lengths, causal=False):
    from petastorm_tpu.models.sequence_model import attention_reference
    return attention_reference(q, k, v, causal=causal, lengths=lengths)


def test_kv_lengths_forward_matches_oracle():
    q, k, v = _qkv(t=48, d=8, seed=30)
    lengths = jnp.asarray([48, 17, 33][:q.shape[0]], jnp.int32)
    out = flash_attention(q, k, v, 16, 16, kv_lengths=lengths)
    ref = _lens_oracle(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # and it actually bites vs unmasked
    full = flash_attention(q, k, v, 16, 16)
    assert not np.allclose(np.asarray(out), np.asarray(full))


def test_kv_lengths_with_causal():
    q, k, v = _qkv(t=32, d=8, seed=31)
    lengths = jnp.asarray([32, 20], jnp.int32)
    out = flash_attention(q, k, v, 16, 16, causal=True, kv_lengths=lengths)
    ref = _lens_oracle(q, k, v, lengths, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_kv_lengths_backward_matches_oracle():
    q, k, v = _qkv(t=40, d=8, seed=32)
    lengths = jnp.asarray([40, 13], jnp.int32)

    def loss_flash(a, b, c):
        return jnp.sum(flash_attention(a, b, c, 16, 16,
                                       kv_lengths=lengths) ** 2)

    def loss_ref(a, b, c):
        return jnp.sum(_lens_oracle(a, b, c, lengths)
                       .astype(jnp.float32) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)
    # masked-out keys must receive exactly zero dk/dv
    np.testing.assert_array_equal(np.asarray(gf[1][1, 13:]), 0.0)
    np.testing.assert_array_equal(np.asarray(gf[2][1, 13:]), 0.0)


def test_kv_lengths_under_jit():
    q, k, v = _qkv(t=32, d=8, seed=33)
    lengths = jnp.asarray([10, 32], jnp.int32)
    f = jax.jit(lambda a, b, c, le: flash_attention(a, b, c, 16, 16,
                                                    kv_lengths=le))
    np.testing.assert_allclose(np.asarray(f(q, k, v, lengths)),
                               np.asarray(_lens_oracle(q, k, v, lengths)),
                               rtol=1e-5, atol=1e-5)


def test_with_lse_matches_dense_and_grads_flow_through_lse():
    """flash_attention_with_lse: the lse output equals the dense
    log-sum-exp (with -inf empty-set convention), and gradients flow
    through BOTH outputs (the backward's dlse term)."""
    from petastorm_tpu.ops.flash_attention import flash_attention_with_lse

    rng = np.random.RandomState(5)
    b, t, h, d = 2, 40, 2, 16
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
               for _ in range(3))

    def dense(q, k, v, shift=0):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
        iq = jnp.arange(t)[:, None] + shift
        s = jnp.where((jnp.arange(t)[None, :] <= iq)[None, None], s,
                      -jnp.inf)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        safe = jnp.where(jnp.isneginf(lse), 0.0, lse)
        p = jnp.where(jnp.isneginf(lse)[..., None], 0.0,
                      jnp.exp(s - safe[..., None]))
        return (jnp.einsum("bhqk,bkhd->bqhd", p, v),
                lse.transpose(0, 2, 1))

    for shift in (0, -1):
        got_o, got_l = flash_attention_with_lse(
            q, k, v, block_q=16, block_k=16, causal=True,
            causal_shift=shift)
        want_o, want_l = dense(q, k, v, shift)
        np.testing.assert_allclose(np.asarray(got_o), np.asarray(want_o),
                                   rtol=1e-5, atol=1e-5)
        finite = np.isfinite(np.asarray(want_l))
        np.testing.assert_allclose(np.asarray(got_l)[finite],
                                   np.asarray(want_l)[finite],
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(np.isneginf(np.asarray(got_l)),
                                      ~finite)

    def loss_flash(q, k, v):
        o, l = flash_attention_with_lse(q, k, v, block_q=16, block_k=16,
                                        causal=True)
        return (o ** 2).sum() + (jnp.tanh(l) ** 2).sum()

    def loss_dense(q, k, v):
        o, l = dense(q, k, v)
        return (o ** 2).sum() + (jnp.tanh(l) ** 2).sum()

    gf = jax.grad(loss_flash, (0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, (0, 1, 2))(q, k, v)
    for a, b_, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_segment_id_pair_form_grads_through_public_api():
    """flash_attention accepts the (q_ids, kv_ids) pair form and its
    backward handles the tuple cotangent (float0 per element)."""
    rng = np.random.RandomState(7)
    b, t, h, d = 1, 24, 2, 8
    q, k, v = (jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
               for _ in range(3))
    ids = jnp.asarray(np.array([[0] * 10 + [1] * 14]), jnp.int32)

    out_pair = flash_attention(q, k, v, block_q=8, block_k=24,
                               segment_ids=(ids, ids))
    out_single = flash_attention(q, k, v, block_q=8, block_k=24,
                                 segment_ids=ids)
    np.testing.assert_allclose(np.asarray(out_pair),
                               np.asarray(out_single), rtol=1e-6)
    g = jax.grad(lambda q: (flash_attention(
        q, k, v, block_q=8, block_k=24,
        segment_ids=(ids, ids)) ** 2).sum())(q)
    assert np.isfinite(np.asarray(g)).all()


def test_segment_id_shape_validation_both_entry_points():
    from petastorm_tpu.ops.flash_attention import flash_attention_with_lse

    rng = np.random.RandomState(8)
    q = jnp.asarray(rng.randn(1, 16, 1, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 24, 1, 8).astype(np.float32))
    ids16 = jnp.zeros((1, 16), jnp.int32)
    ids24 = jnp.zeros((1, 24), jnp.int32)
    # single array + cross-length → both entry points raise
    with pytest.raises(ValueError, match="T_q == T_kv"):
        flash_attention(q, k, k, segment_ids=ids16)
    with pytest.raises(ValueError, match="T_q == T_kv"):
        flash_attention_with_lse(q, k, k, segment_ids=ids16)
    # swapped pair → raises rather than silently mis-masking
    with pytest.raises(ValueError, match="swapped"):
        flash_attention_with_lse(q, k, k, segment_ids=(ids24, ids16))
    # correct pair → runs
    out, lse = flash_attention_with_lse(q, k, k, block_q=16, block_k=24,
                                        segment_ids=(ids16, ids24))
    assert out.shape == (1, 16, 1, 8)


def test_single_segment_ids_length_mismatch_raises():
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 16, 1, 8).astype(np.float32))
    bad_ids = jnp.zeros((1, 24), jnp.int32)
    with pytest.raises(ValueError, match="does not match the sequence"):
        flash_attention(q, q, q, segment_ids=bad_ids)


# ---------------------------------------------------------------------------
# Grouped-query attention (GQA / MQA): k/v carry fewer heads than q
# ---------------------------------------------------------------------------

def _gqa_qkv(h=4, h_kv=2, b=2, t=48, d=16, seed=9):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    return q, k, v


def _repeat_kv_oracle(h, h_kv, **okw):
    """GQA's defining equivalence: attention with the K/V heads repeated
    to the query head count."""
    g = h // h_kv

    def fn(q, k, v):
        return attention_reference(q, jnp.repeat(k, g, axis=2),
                                   jnp.repeat(v, g, axis=2), **okw)

    return fn


@pytest.mark.parametrize("h_kv", [2, 1])
@pytest.mark.parametrize("causal", [False, True])
def test_gqa_matches_repeated_kv_reference(h_kv, causal):
    q, k, v = _gqa_qkv(h_kv=h_kv)
    out = flash_attention(q, k, v, block_q=16, block_k=16, causal=causal)
    want = _repeat_kv_oracle(4, h_kv, causal=causal)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gqa_gradients_group_sum_matches_repeated_kv_autodiff():
    """dK/dV must come back at the K/V head count as the SUM over each
    group's q-heads — exactly what autodiff through the repeated-KV
    oracle produces for the un-repeated tensors."""
    q, k, v = _gqa_qkv(h_kv=2, seed=10)
    oracle = _repeat_kv_oracle(4, 2, causal=True)

    def loss_fl(q, k, v):
        return (flash_attention(q, k, v, block_q=16, block_k=16,
                                causal=True) ** 2).sum()

    def loss_ref(q, k, v):
        return (oracle(q, k, v) ** 2).sum()

    got = jax.grad(loss_fl, (0, 1, 2))(q, k, v)
    want = jax.grad(loss_ref, (0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), got, want):
        assert a.shape == b.shape, name
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


def test_gqa_composes_with_lengths_and_segments():
    q, k, v = _gqa_qkv(h_kv=2, seed=11)
    t = q.shape[1]
    lens = jnp.asarray([t - 10, t], jnp.int32)
    out = flash_attention(q, k, v, block_q=16, block_k=16, causal=True,
                          kv_lengths=lens)
    want = _repeat_kv_oracle(4, 2, causal=True, lengths=lens)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    segs = jnp.asarray(np.repeat(np.arange(4), t // 4)[None]
                       .repeat(2, 0), jnp.int32)
    out = flash_attention(q, k, v, block_q=16, block_k=16, causal=True,
                          segment_ids=segs)
    want = _repeat_kv_oracle(4, 2, causal=True, segment_ids=segs)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gqa_with_lse_and_cotangent():
    from petastorm_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = _gqa_qkv(h_kv=2, seed=12)
    out, lse = flash_attention_with_lse(q, k, v, block_q=16, block_k=16,
                                        causal=True)
    want = _repeat_kv_oracle(4, 2, causal=True)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    assert lse.shape == (2, q.shape[1], 4)  # lse is per QUERY head

    def loss(q, k, v):
        o, l = flash_attention_with_lse(q, k, v, block_q=16, block_k=16,
                                        causal=True)
        return (o ** 2).sum() + (l * 0.01).sum()

    grads = jax.grad(loss, (0, 1, 2))(q, k, v)
    assert grads[1].shape == k.shape and grads[2].shape == v.shape
    assert all(bool(jnp.isfinite(g).all()) for g in grads)


def test_gqa_rejects_bad_head_ratios_and_reference_bwd():
    q, k, v = _gqa_qkv(h_kv=2)
    with pytest.raises(ValueError, match="group"):
        flash_attention(q, k[:, :, :1].repeat(3, axis=2),
                        v[:, :, :1].repeat(3, axis=2))  # 4 % 3 != 0
    with pytest.raises(ValueError, match="share"):
        flash_attention(q, k, v[:, :, :1])  # k/v head mismatch
    with pytest.raises(NotImplementedError, match="reference"):
        flash_attention(q, k, v, bwd_impl="reference")


# ---------------------------------------------------------------------------
# Length-aware block_k default (512 at T >= 4096, measured faster on v5e)
# ---------------------------------------------------------------------------

def test_default_blocks_resolution():
    from petastorm_tpu.ops.flash_attention import _default_blocks

    assert _default_blocks(1024, None, None) == (128, 128)
    assert _default_blocks(4095, None, None) == (128, 128)
    assert _default_blocks(4096, None, None) == (128, 512)
    assert _default_blocks(8192, 64, None) == (64, 512)
    # explicit sizes always win
    assert _default_blocks(8192, None, 128) == (128, 128)
    assert _default_blocks(8192, 256, 256) == (256, 256)


def test_long_t_auto_block_matches_reference():
    """T=4096 crosses the auto threshold — the shipping default
    (block_k=512) must stay oracle-exact, forward and backward."""
    rng = np.random.RandomState(40)
    q, k, v = (jnp.asarray(rng.randn(1, 4096, 1, 8).astype(np.float32))
               for _ in range(3))
    out = flash_attention(q, k, v, causal=True)
    want = attention_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    got = jax.grad(lambda a, b, c: (flash_attention(a, b, c, causal=True)
                                    ** 2).sum(), (0, 1, 2))(q, k, v)
    ref = jax.grad(lambda a, b, c: (attention_reference(a, b, c,
                                                        causal=True)
                                    ** 2).sum(), (0, 1, 2))(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), got, ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4, err_msg=name)
