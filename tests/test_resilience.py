"""Overload-robust serving: the resilience layer's golden tests.

Pure state machines first (``service/resilience.py`` keeps time and I/O
out, so canned sequences pin every transition exactly — the
``plan_fair_shares`` discipline): deadline propagation helpers,
:class:`RetryBudget`, :class:`CircuitBreaker`, :class:`GapTracker`,
:class:`BrownoutConfig`/:class:`BrownoutPlanner`, and the level-2
optional-stage shed. Then the journaled wiring: breaker-open and
brownout transitions are WAL ops replayed byte-identically across a
dispatcher restart (the quarantine contract, at worker granularity), the
deadline gate refuses an expired budget retryably on the live socket,
and a hedged watermark re-serve under a targeted ``slow-peer`` failpoint
delivers exactly once with a stream digest byte-identical to the
unhedged same-seed run (docs/guides/service.md#failure-model-and-recovery).
"""

import json

import pytest

from petastorm_tpu.reader_impl.framed_socket import FramedConnection
from petastorm_tpu.service import Dispatcher
from petastorm_tpu.service.resilience import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    BROWNOUT_MAX_LEVEL,
    DEADLINE_FIELD,
    BrownoutConfig,
    BrownoutPlanner,
    CircuitBreaker,
    GapTracker,
    RetryBudget,
    arrival_deadline,
    attach_deadline,
    brownout_level,
    deadline_exceeded_reply,
    deadline_expired,
    note_brownout_level,
    optional_stages_shed,
)

pytestmark = pytest.mark.service


def _request(address, header):
    with FramedConnection.connect(address) as conn:
        reply, _ = conn.request(header)
    return reply


def _register(dispatcher, worker_id, num_pieces, port=1):
    return _request(dispatcher.address, {
        "type": "register_worker", "worker_id": worker_id,
        "host": "127.0.0.1", "port": port, "num_pieces": num_pieces})


# ---------------------------------------------------------------------------
# deadline propagation helpers (pure; clocks injected)
# ---------------------------------------------------------------------------

def test_attach_deadline_stamps_remaining_budget():
    header = {"type": "get_assignment"}
    attach_deadline(header, deadline=12.5, clock=lambda: 10.0)
    assert header[DEADLINE_FIELD] == 2.5


def test_attach_deadline_restamps_smaller_budget_per_attempt():
    """A retry after backoff ships the SMALLER remaining budget — the
    header is derived from the one deadline the retry loop enforces,
    never reset to the original budget."""
    header = {}
    attach_deadline(header, deadline=13.0, clock=lambda: 10.0)
    assert header[DEADLINE_FIELD] == 3.0
    attach_deadline(header, deadline=13.0, clock=lambda: 12.0)
    assert header[DEADLINE_FIELD] == 1.0


def test_attach_deadline_clamps_expired_budget_to_zero():
    header = {}
    attach_deadline(header, deadline=9.0, clock=lambda: 10.0)
    assert header[DEADLINE_FIELD] == 0.0


def test_attach_deadline_none_is_a_no_op():
    header = {"type": "heartbeat"}
    attach_deadline(header, deadline=None, clock=lambda: 10.0)
    assert DEADLINE_FIELD not in header


def test_arrival_deadline_reanchors_locally():
    """The wire field is RELATIVE (monotonic clocks do not transfer
    across hosts); the handler re-anchors it on its own clock."""
    assert arrival_deadline({DEADLINE_FIELD: 2.0},
                            clock=lambda: 100.0) == 102.0
    assert arrival_deadline({}, clock=lambda: 100.0) is None


def test_arrival_deadline_tolerates_unparseable_values():
    # An old or foreign caller must not be refused over an optional field.
    assert arrival_deadline({DEADLINE_FIELD: "soon"},
                            clock=lambda: 0.0) is None
    assert arrival_deadline({DEADLINE_FIELD: None},
                            clock=lambda: 0.0) is None


def test_deadline_expired():
    assert not deadline_expired(None, clock=lambda: 99.0)
    assert not deadline_expired(100.0, clock=lambda: 99.0)
    assert deadline_expired(100.0, clock=lambda: 100.0)
    assert deadline_expired(100.0, clock=lambda: 101.0)


def test_deadline_exceeded_reply_is_retryable():
    reply = deadline_exceeded_reply("dispatcher.get_assignment")
    assert reply["type"] == "error"
    assert reply["retryable"] is True
    assert reply["error"].startswith(
        "DEADLINE_EXCEEDED: dispatcher.get_assignment")


# ---------------------------------------------------------------------------
# retry budget (token bucket: retries spend, successes refill)
# ---------------------------------------------------------------------------

def test_retry_budget_spends_and_denies():
    budget = RetryBudget(capacity=2.0)
    assert budget.try_spend()
    assert budget.try_spend()
    assert not budget.try_spend()       # bucket empty: retry refused
    assert budget.denied == 1
    assert budget.balance == 0.0


def test_retry_budget_refills_on_success_capped_at_capacity():
    budget = RetryBudget(capacity=2.0, refill_per_success=0.5)
    assert budget.try_spend()
    budget.record_success()
    assert budget.balance == 1.5
    for _ in range(10):
        budget.record_success()
    assert budget.balance == 2.0        # never above capacity


def test_retry_budget_bounds_retry_rate_against_failing_peer():
    """After the initial burst, the sustained retry rate is
    refill_per_success retries per success — a degraded peer sees a
    bounded ratio, never a storm."""
    budget = RetryBudget(capacity=3.0, refill_per_success=0.5, initial=0.0)
    granted = 0
    for _ in range(10):                 # 10 successes interleaved...
        budget.record_success()
        if budget.try_spend():          # ...each tried to fund a retry
            granted += 1
    assert granted == 5                 # exactly 0.5 retries per success


def test_retry_budget_rejects_nonpositive_capacity():
    with pytest.raises(ValueError, match="capacity"):
        RetryBudget(capacity=0)


def test_retry_budget_snapshot():
    budget = RetryBudget(capacity=4.0, initial=1.25)
    assert not budget.try_spend(cost=2.0)
    assert budget.snapshot() == {"capacity": 4.0, "balance": 1.25,
                                 "denied": 1}


# ---------------------------------------------------------------------------
# circuit breaker (closed -> open -> half-open; time is an argument)
# ---------------------------------------------------------------------------

def test_breaker_trips_on_consecutive_failures_exactly_at_threshold():
    breaker = CircuitBreaker(threshold=3, cooldown_s=5.0)
    assert breaker.state == BREAKER_CLOSED
    assert not breaker.record_failure(now=0.0)
    assert not breaker.record_failure(now=0.1)
    assert breaker.record_failure(now=0.2)      # True ONLY on the trip edge
    assert breaker.state == BREAKER_OPEN
    # Further failures while open are not fresh trips (no re-journal).
    assert not breaker.record_failure(now=0.3)


def test_breaker_success_resets_streak_so_flapping_never_trips():
    breaker = CircuitBreaker(threshold=2, cooldown_s=5.0)
    for i in range(10):                 # fail, succeed, fail, succeed...
        assert not breaker.record_failure(now=float(i))
        breaker.record_success()
    assert breaker.state == BREAKER_CLOSED
    assert breaker.consecutive_failures == 0


def test_breaker_open_refuses_until_cooldown():
    breaker = CircuitBreaker(threshold=1, cooldown_s=5.0)
    assert breaker.record_failure(now=10.0)
    assert not breaker.allow(now=10.0)
    assert not breaker.allow(now=14.9)
    assert breaker.allow(now=15.0)      # cooldown elapsed: half-open probe
    assert breaker.state == BREAKER_HALF_OPEN


def test_breaker_half_open_admits_exactly_one_probe():
    breaker = CircuitBreaker(threshold=1, cooldown_s=1.0)
    breaker.record_failure(now=0.0)
    assert breaker.allow(now=2.0)       # the probe
    assert not breaker.allow(now=2.0)   # concurrent calls refused
    assert not breaker.allow(now=3.0)   # ...until the probe resolves


def test_breaker_probe_failure_reopens_and_restarts_cooldown():
    breaker = CircuitBreaker(threshold=1, cooldown_s=5.0)
    breaker.record_failure(now=0.0)
    assert breaker.allow(now=5.0)                   # probe admitted
    assert not breaker.record_failure(now=5.0)      # probe fails: not a
    assert breaker.state == BREAKER_OPEN            # fresh trip edge
    assert not breaker.allow(now=9.9)               # cooldown RESTARTED
    assert breaker.allow(now=10.0)


def test_breaker_probe_success_closes():
    breaker = CircuitBreaker(threshold=1, cooldown_s=5.0)
    breaker.record_failure(now=0.0)
    assert breaker.allow(now=5.0)
    breaker.record_success()
    assert breaker.state == BREAKER_CLOSED
    assert breaker.allow(now=5.0)
    assert breaker.snapshot() == {"state": "closed",
                                  "consecutive_failures": 0}


def test_breaker_rejects_threshold_below_one():
    with pytest.raises(ValueError, match="threshold"):
        CircuitBreaker(threshold=0)


# ---------------------------------------------------------------------------
# gap tracker (hedge threshold fit from the gap histogram)
# ---------------------------------------------------------------------------

BUCKETS = (0.1, 0.2, 0.4, 0.8, 1.6)


def test_gap_tracker_disarmed_below_min_samples():
    tracker = GapTracker(min_samples=4, buckets=BUCKETS)
    for _ in range(3):
        tracker.observe(0.05)
    assert tracker.threshold_s() is None
    tracker.observe(0.05)
    assert tracker.threshold_s() is not None
    assert tracker.count == 4


def test_gap_tracker_threshold_is_clamped_multiple_of_quantile():
    # All 20 gaps in the first bucket; q=1.0 interpolates to its upper
    # bound (0.1), multiplier 4 -> 0.4, above the 0.25 floor.
    tracker = GapTracker(quantile=1.0, multiplier=4.0, min_samples=16,
                         floor_s=0.25, cap_s=30.0, buckets=BUCKETS)
    for _ in range(20):
        tracker.observe(0.05)
    assert tracker.threshold_s() == pytest.approx(0.4)


def test_gap_tracker_floor_clamps_fast_fleets():
    # A fast fleet's fitted p99 would hedge on micro-jitter; the floor
    # keeps the trigger at a humane minimum.
    tracker = GapTracker(quantile=1.0, multiplier=1.0, min_samples=4,
                         floor_s=0.25, cap_s=30.0, buckets=BUCKETS)
    for _ in range(8):
        tracker.observe(0.01)
    assert tracker.threshold_s() == 0.25


def test_gap_tracker_cap_clamps_slow_fleets():
    # Overflow-bucket gaps fit to the last bound; the cap bounds how long
    # a stream may stay silent before the hedge fires regardless.
    tracker = GapTracker(quantile=1.0, multiplier=100.0, min_samples=4,
                         floor_s=0.25, cap_s=30.0, buckets=BUCKETS)
    for _ in range(8):
        tracker.observe(50.0)
    assert tracker.threshold_s() == 30.0


def test_gap_tracker_rejects_bad_params():
    with pytest.raises(ValueError, match="quantile"):
        GapTracker(quantile=0.0)
    with pytest.raises(ValueError, match="multiplier"):
        GapTracker(multiplier=0.0)


# ---------------------------------------------------------------------------
# brownout planner (shed order, hysteresis, symmetric recovery)
# ---------------------------------------------------------------------------

def _cfg(**overrides):
    base = dict(interval_s=0.0, enter_credit_wait_s=0.5,
                enter_ready_saturation=0.9, exit_fraction=0.5,
                up_windows=2, down_windows=2, cooldown_windows=1,
                max_level=2)
    base.update(overrides)
    return BrownoutConfig(**base)


OVERLOADED = {"credit_wait_rate": 1.0, "ready_saturation": 0.0}
CALM = {"credit_wait_rate": 0.0, "ready_saturation": 0.0}


def test_brownout_sheds_after_up_windows_one_level_at_a_time():
    planner = BrownoutPlanner(_cfg())
    assert planner.plan(dict(OVERLOADED, level=0)) == []
    actions = planner.plan(dict(OVERLOADED, level=0))
    assert actions == [{"action": "shed", "level": 1,
                        "reason": actions[0]["reason"]}]
    assert "overload for 2 windows" in actions[0]["reason"]


def test_brownout_cooldown_window_emits_nothing():
    planner = BrownoutPlanner(_cfg())
    planner.plan(dict(OVERLOADED, level=0))
    assert planner.plan(dict(OVERLOADED, level=0))  # shed to 1
    # The transition started a cooldown: this round accumulates nothing.
    assert planner.plan(dict(OVERLOADED, level=1)) == []
    # Streaks then rebuild from zero toward level 2.
    assert planner.plan(dict(OVERLOADED, level=1)) == []
    actions = planner.plan(dict(OVERLOADED, level=1))
    assert actions[0] == {"action": "shed", "level": 2,
                          "reason": actions[0]["reason"]}


def test_brownout_saturation_alone_is_overload():
    planner = BrownoutPlanner(_cfg(up_windows=1, cooldown_windows=0))
    actions = planner.plan({"level": 0, "credit_wait_rate": 0.0,
                            "ready_saturation": 0.95})
    assert actions[0]["action"] == "shed"


def test_brownout_never_sheds_past_max_level():
    planner = BrownoutPlanner(_cfg(up_windows=1, cooldown_windows=0))
    for _ in range(5):
        assert planner.plan(dict(OVERLOADED, level=2)) == []
    assert BROWNOUT_MAX_LEVEL == 2


def test_brownout_recovers_symmetrically_after_down_windows():
    planner = BrownoutPlanner(_cfg())
    assert planner.plan(dict(CALM, level=2)) == []
    actions = planner.plan(dict(CALM, level=2))
    assert actions == [{"action": "recover", "level": 1,
                        "reason": actions[0]["reason"]}]
    assert "calm for 2 windows" in actions[0]["reason"]
    assert planner.plan(dict(CALM, level=1)) == []      # cooldown
    assert planner.plan(dict(CALM, level=1)) == []
    assert planner.plan(dict(CALM, level=1))[0]["level"] == 0


def test_brownout_exit_bar_is_strictly_below_entry():
    # Hovering just under the enter threshold is NOT calm (exit needs
    # both signals below exit_fraction x enter) — the level cannot flap.
    planner = BrownoutPlanner(_cfg())
    hover = {"credit_wait_rate": 0.4, "ready_saturation": 0.0}
    for _ in range(6):
        assert planner.plan(dict(hover, level=1)) == []


def test_brownout_mixed_round_resets_both_streaks():
    planner = BrownoutPlanner(_cfg())
    planner.plan(dict(OVERLOADED, level=0))             # up streak = 1
    hover = {"credit_wait_rate": 0.4, "ready_saturation": 0.0}
    assert planner.plan(dict(hover, level=0)) == []     # resets streaks
    assert planner.plan(dict(OVERLOADED, level=0)) == []  # restarts at 1
    assert planner.plan(dict(OVERLOADED, level=0))[0]["action"] == "shed"


def test_brownout_config_coerce():
    assert BrownoutConfig.coerce(True).up_windows == 3
    cfg = BrownoutConfig.coerce({"up_windows": 7})
    assert cfg.up_windows == 7
    assert BrownoutConfig.coerce(cfg) is cfg
    with pytest.raises(TypeError, match="brownout"):
        BrownoutConfig.coerce("on")
    with pytest.raises(ValueError, match="exit_fraction"):
        BrownoutConfig(exit_fraction=1.0)
    with pytest.raises(ValueError, match="max_level"):
        BrownoutConfig(max_level=0)


def test_note_brownout_level_sheds_and_restores_tracing():
    """Level 2 sheds the trace collector; recovery restores it ONLY if
    the brownout disabled it — an operator's own disable is respected."""
    from petastorm_tpu.telemetry import tracing

    prior = tracing.COLLECTOR.enabled
    try:
        tracing.COLLECTOR.enabled = True
        note_brownout_level(2)
        assert brownout_level() == 2
        assert optional_stages_shed()
        assert tracing.COLLECTOR.enabled is False
        note_brownout_level(1)
        assert not optional_stages_shed()
        assert tracing.COLLECTOR.enabled is True        # restored
        # Operator disabled it themselves: a brownout cycle leaves it off.
        tracing.COLLECTOR.enabled = False
        note_brownout_level(2)
        note_brownout_level(0)
        assert tracing.COLLECTOR.enabled is False
    finally:
        note_brownout_level(0)
        tracing.COLLECTOR.enabled = prior


# ---------------------------------------------------------------------------
# journaled wiring: breaker + brownout WAL ops replay byte-identically
# ---------------------------------------------------------------------------

def test_breaker_open_replays_byte_identical_across_restart(tmp_path):
    journal_dir = str(tmp_path / "journal")
    with Dispatcher(port=0, mode="static", num_epochs=1,
                    journal_dir=journal_dir,
                    breaker_cooldown_s=600.0).start() as disp:
        _register(disp, "w0", 6)
        _register(disp, "w1", 6)
        reply = _request(disp.address, {
            "type": "report_breaker", "worker_id": "w1",
            "client_id": "c0", "error": "5 consecutive stream failures",
            "epoch": 0})
        assert reply["fresh"] is True
        assert reply["breaker_open"] == ["w1"]
        # Idempotent: a second client's report journals nothing new.
        again = _request(disp.address, {
            "type": "report_breaker", "worker_id": "w1",
            "client_id": "c1", "error": "timeout", "epoch": 0})
        assert again["fresh"] is False
        status = _request(disp.address, {"type": "status"})
        assert sorted(status["fleet"]["breaker_open"]) == ["w1"]
        before = disp.state_snapshot()

    with Dispatcher(port=0, mode="static", num_epochs=1,
                    journal_dir=journal_dir,
                    breaker_cooldown_s=600.0).start() as restarted:
        after = restarted.state_snapshot()
        volatile = ("fencing_epoch", "recovery")
        plan_before = {k: v for k, v in before.items() if k not in volatile}
        plan_after = {k: v for k, v in after.items() if k not in volatile}
        assert (json.dumps(plan_before, sort_keys=True)
                == json.dumps(plan_after, sort_keys=True))
        assert after["breaker_open"]["w1"]["client_id"] == "c0"
        assert after["recovery"]["journal_replays"] == 1


def test_report_breaker_unknown_worker_rejected(tmp_path):
    with Dispatcher(port=0, mode="static", num_epochs=1).start() as disp:
        _register(disp, "w0", 3)
        reply = _request(disp.address, {
            "type": "report_breaker", "worker_id": "ghost",
            "client_id": "c0", "error": "x"})
        assert reply["type"] == "error"
        assert "unknown worker" in reply["error"]


def test_brownout_transitions_replay_byte_identical_across_restart(
        tmp_path):
    journal_dir = str(tmp_path / "journal")
    with Dispatcher(port=0, mode="static", num_epochs=1,
                    journal_dir=journal_dir).start() as disp:
        _register(disp, "w0", 6)
        assert disp.apply_brownout("shed", 1, reason="credit_wait 1.2s/s")
        assert disp.apply_brownout("shed", 2, reason="still overloaded")
        assert disp.apply_brownout("recover", 1, reason="calm")
        # Out-of-order transitions are refused, live and on replay alike.
        assert not disp.apply_brownout("shed", 3, reason="skip a level")
        status = _request(disp.address, {"type": "status"})
        assert status["fleet"]["brownout"]["level"] == 1
        assert status["fleet"]["brownout"]["counts"] == {"shed": 2,
                                                         "recover": 1}
        before = disp.state_snapshot()

    with Dispatcher(port=0, mode="static", num_epochs=1,
                    journal_dir=journal_dir).start() as restarted:
        after = restarted.state_snapshot()
        volatile = ("fencing_epoch", "recovery")
        plan_before = {k: v for k, v in before.items() if k not in volatile}
        plan_after = {k: v for k, v in after.items() if k not in volatile}
        assert (json.dumps(plan_before, sort_keys=True)
                == json.dumps(plan_after, sort_keys=True))
        assert after["brownout"] == {"level": 1,
                                     "counts": {"shed": 2, "recover": 1},
                                     "reason": "calm"}
        assert after["recovery"]["journal_replays"] == 1


# ---------------------------------------------------------------------------
# live deadline gate (the wire contract, one round-trip)
# ---------------------------------------------------------------------------

def test_dispatcher_refuses_expired_deadline_retryably():
    with Dispatcher(port=0, mode="static", num_epochs=1).start() as disp:
        _register(disp, "w0", 3)
        reply = _request(disp.address, {"type": "status",
                                        DEADLINE_FIELD: 0.0})
        assert reply["type"] == "error"
        assert reply["retryable"] is True
        assert "DEADLINE_EXCEEDED: dispatcher.status" in reply["error"]
        # Without the field there is no gate.
        assert _request(disp.address, {"type": "status"})["type"] == "status"


# ---------------------------------------------------------------------------
# hedged watermark re-serve: exactly-once, digest-invariant
# ---------------------------------------------------------------------------

def test_hedged_reserve_exactly_once_and_digest_invariant(tmp_path):
    """A targeted ``slow-peer`` failpoint stalls one worker's sends past
    the hedge floor; the client hedges the in-flight piece at its
    watermark from the peer. The contract: hedges LAUNCH (the trigger
    fired), zero lost and zero duplicate rows (first-wins + watermark
    dedup), and the delivered stream digest is byte-identical to the
    unhedged same-seed run — hedging changes tail latency, never
    content."""
    from petastorm_tpu.benchmark.scenarios import service_loopback_scenario

    geometry = dict(
        rows=1536, days=8, workers=2, batch_size=64, credits=4,
        ordered=True, shuffle_seed=7, chaos="failpoints", chaos_seed=11,
        failpoint_points=("slow-peer",), failpoint_window=10,
        failpoint_delay_s=0.6, failpoint_max_fires=3,
        failpoint_targets={"slow-peer": "bench-worker-0"})
    plain = service_loopback_scenario(**geometry)
    hedged = service_loopback_scenario(
        **geometry, hedging=True, hedge_floor_s=0.2, hedge_min_samples=6,
        # Short epoch: the injected stalls ARE the tail, so the median —
        # not the p99 — is the honest baseline to hedge against.
        hedge_quantile=0.5)

    for result in (plain, hedged):
        assert result["lost_rows"] == 0
        assert result["duplicate_rows"] == 0
    assert [tuple(e) for e in plain["failpoint_injections"]] \
        == [tuple(e) for e in hedged["failpoint_injections"]]
    counts = hedged["hedge_counts"]
    assert counts["launched"] >= 1
    assert counts["won"] + counts["lost"] <= counts["launched"]
    assert plain["hedge_counts"]["launched"] == 0
    assert hedged["stream_digest"] == plain["stream_digest"]
