"""Pipeline-parallel schedule tests over the virtual CPU mesh: the shard_map
+ ppermute + scan GPipe schedule must match the sequential stack exactly,
forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.models.pipeline import (
    apply_pipeline_model,
    init_pipeline_params,
    make_pipeline_train_step,
    pipeline_param_partition_specs,
    reference_forward,
)


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("pp",))


def _params(n_stages, seed=0):
    return init_pipeline_params(jax.random.PRNGKey(seed), feature_dim=6,
                                d_model=16, d_hidden=32,
                                num_stages=n_stages, num_classes=3)


def test_pipeline_forward_matches_sequential_stack():
    mesh = _mesh(4)
    params = _params(4)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 6).astype(np.float32))
    got = apply_pipeline_model(params, x, mesh, num_microbatches=4)
    want = reference_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_forward_more_microbatches_than_stages():
    mesh = _mesh(2)
    params = _params(2, seed=1)
    x = jnp.asarray(np.random.RandomState(1).randn(12, 6).astype(np.float32))
    got = apply_pipeline_model(params, x, mesh, num_microbatches=6)
    want = reference_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential_stack():
    """The transposed schedule (scan+ppermute autodiff) must equal the
    sequential stack's gradients — including zero contribution from
    warmup/drain bubble compute."""
    mesh = _mesh(4)
    params = _params(4, seed=2)
    x = jnp.asarray(np.random.RandomState(2).randn(8, 6).astype(np.float32))
    labels = jnp.asarray(np.arange(8) % 3, jnp.int32)

    def loss_pp(p):
        logits = apply_pipeline_model(p, x, mesh, num_microbatches=4)
        return -jnp.take_along_axis(jax.nn.log_softmax(logits),
                                    labels[:, None], 1).mean()

    def loss_ref(p):
        logits = reference_forward(p, x)
        return -jnp.take_along_axis(jax.nn.log_softmax(logits),
                                    labels[:, None], 1).mean()

    g_pp = jax.grad(loss_pp)(params)
    g_ref = jax.grad(loss_ref)(params)
    for key in params:
        np.testing.assert_allclose(np.asarray(g_pp[key]),
                                   np.asarray(g_ref[key]),
                                   rtol=1e-4, atol=1e-5, err_msg=key)


def test_pipeline_train_step_descends_sharded():
    mesh = _mesh(4)
    params = _params(4, seed=3)
    specs = pipeline_param_partition_specs()
    params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
    step = jax.jit(make_pipeline_train_step(0.1, mesh=mesh,
                                            num_microbatches=4))
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 6).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 3, 8), jnp.int32)
    mask = jnp.ones(8, bool)
    losses = []
    for _ in range(5):
        params, loss = step(params, x, labels, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_pipeline_rejects_mismatched_stage_count():
    mesh = _mesh(4)
    params = _params(2)
    x = jnp.zeros((8, 6), jnp.float32)
    with pytest.raises(ValueError, match="stages"):
        apply_pipeline_model(params, x, mesh, num_microbatches=4)


def test_pipeline_rejects_indivisible_batch():
    mesh = _mesh(2)
    params = _params(2)
    with pytest.raises(ValueError, match="microbatches"):
        apply_pipeline_model(params, jnp.zeros((7, 6), jnp.float32), mesh,
                             num_microbatches=4)


def test_pipeline_dp_x_pp_mesh():
    """Combined data x pipeline mesh: batch sharded over "data", stages
    over "pp" — must still match the sequential stack, and train."""
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pp"))
    params = _params(4, seed=5)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(8, 6).astype(np.float32))
    got = apply_pipeline_model(params, x, mesh, num_microbatches=4,
                               batch_axis="data")
    want = reference_forward(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    specs = pipeline_param_partition_specs()
    sharded_params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
                      for k, v in params.items()}
    step = jax.jit(make_pipeline_train_step(0.1, mesh=mesh,
                                            num_microbatches=4,
                                            batch_axis="data"))
    labels = jnp.asarray(rng.randint(0, 3, 8), jnp.int32)
    losses = []
    p = sharded_params
    for _ in range(4):
        p, loss = step(p, x, labels, jnp.ones(8, bool))
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


# ---------------------------------------------------------------------------
# 1F1B schedule (hand-scheduled fused forward+backward, O(S) stash)
# ---------------------------------------------------------------------------

def _seq_loss(params, feats, labels, mask):
    logits = reference_forward(params, feats)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    nll = jnp.where(mask, nll, 0.0)
    return nll.sum() / jnp.maximum(mask.sum(), 1).astype(jnp.float32)


def _grad_case(seed, n_stages, num_microbatches, mb):
    params = _params(n_stages, seed=seed)
    rng = np.random.RandomState(seed)
    b = num_microbatches * mb
    feats = jnp.asarray(rng.randn(b, 6).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 3, b), jnp.int32)
    mask = jnp.asarray(rng.rand(b) > 0.25)
    return params, feats, labels, mask


def test_1f1b_matches_sequential_gradients_with_slot_reuse():
    """M=16 >> 2S=8: the ring stash wraps multiple times — gradient parity
    with the sequential stack proves the slot-reuse schedule never
    overwrites a live activation."""
    from petastorm_tpu.models.pipeline import pipeline_1f1b_loss_and_grads

    mesh = _mesh(4)
    params, feats, labels, mask = _grad_case(11, 4, 16, 2)
    ref_loss, ref_grads = jax.value_and_grad(_seq_loss)(params, feats,
                                                        labels, mask)
    loss, grads = jax.jit(lambda p, f, l, m: pipeline_1f1b_loss_and_grads(
        p, f, l, m, mesh, num_microbatches=16))(params, feats, labels, mask)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    for k in ref_grads:
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(ref_grads[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_1f1b_dp_x_pp_mesh_gradients():
    from petastorm_tpu.models.pipeline import pipeline_1f1b_loss_and_grads

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pp"))
    params, feats, labels, mask = _grad_case(12, 4, 8, 2)
    ref_loss, ref_grads = jax.value_and_grad(_seq_loss)(params, feats,
                                                        labels, mask)
    loss, grads = jax.jit(lambda p, f, l, m: pipeline_1f1b_loss_and_grads(
        p, f, l, m, mesh, num_microbatches=8,
        batch_axis="data"))(params, feats, labels, mask)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-6)
    for k in ref_grads:
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(ref_grads[k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_1f1b_train_step_matches_gpipe_step():
    """One SGD step under each schedule from identical params must land on
    identical weights (the schedules are two executions of one program)."""
    mesh = _mesh(4)
    params, feats, labels, mask = _grad_case(13, 4, 8, 2)
    step_g = jax.jit(make_pipeline_train_step(0.05, mesh=mesh,
                                              num_microbatches=8))
    step_f = jax.jit(make_pipeline_train_step(0.05, mesh=mesh,
                                              num_microbatches=8,
                                              schedule="1f1b"))
    pg, lg = step_g(dict(params), feats, labels, mask)
    pf, lf = step_f(dict(params), feats, labels, mask)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-6)
    for k in pg:
        np.testing.assert_allclose(np.asarray(pg[k]), np.asarray(pf[k]),
                                   rtol=1e-5, atol=1e-6, err_msg=k)


def test_1f1b_train_step_descends_sharded():
    mesh = _mesh(4)
    params = _params(4, seed=14)
    specs = pipeline_param_partition_specs()
    params = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
              for k, v in params.items()}
    step = jax.jit(make_pipeline_train_step(0.1, mesh=mesh,
                                            num_microbatches=8,
                                            schedule="1f1b"))
    rng = np.random.RandomState(14)
    feats = jnp.asarray(rng.randn(16, 6).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 3, 16), jnp.int32)
    mask = jnp.ones(16, bool)
    losses = []
    for _ in range(5):
        params, loss = step(params, feats, labels, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_1f1b_rejects_unknown_schedule():
    with pytest.raises(ValueError, match="schedule"):
        make_pipeline_train_step(schedule="interleaved")
