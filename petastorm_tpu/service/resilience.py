"""Overload-robust serving: deadlines, retry budgets, breakers, hedging,
brownout.

The service's crash-fault story (leases, takeover, journal replay, poison
quarantine) treats peers as binary — dead or healthy. Production input
services mostly fail the OTHER way (tf.data service, PAPERS.md
2210.14826): a peer is slow, overloaded, or flapping, and the binary
machinery answers with unbounded retry loops, no deadline anywhere in the
RPC chain, and a p99 set by the single worst stream. This module holds
the PURE pieces of the resilience layer — no sockets, no wall clock
unless injected — in the same golden-testable discipline as
:func:`petastorm_tpu.service.fleet.plan_fair_shares` and
:class:`~petastorm_tpu.service.fleet.AutoscalePlanner`:

- **Deadline propagation** helpers: every control RPC (and stream open)
  carries the caller's remaining budget as a RELATIVE ``deadline_left_s``
  header field (absolute wall-clock does not transfer across hosts);
  handlers convert it to a local monotonic deadline on arrival, check it
  before and during expensive work, and answer a retryable
  ``DEADLINE_EXCEEDED`` instead of doing work nobody will wait for.
  ``retry_with_backoff(deadline_s=)`` is the budget's source of truth:
  the header is stamped per attempt from the same deadline the retry
  loop enforces client-side.
- :class:`RetryBudget` — a per-peer token bucket spent by retries and
  refilled by successes, so a failing peer gets a bounded retry RATE
  (ratio of retries to successes), never a storm.
- :class:`CircuitBreaker` — consecutive-failure trip, cooldown, one
  half-open probe, symmetric close. Time is an explicit ``now`` argument.
- :class:`BrownoutConfig` / :class:`BrownoutPlanner` — the dispatcher's
  degraded state machine under sustained overload (credit-wait +
  ready-queue-saturation streaks, the autoscaler's hysteresis idiom),
  shedding in priority order: level 1 scales low-weight/sideband jobs'
  credit windows (:func:`petastorm_tpu.service.fleet.credit_scales` with
  the brownout factor), level 2 also sheds optional stages (tracing
  spans, autotune probes). Recovery is symmetric; every transition is a
  WAL op.
- :class:`GapTracker` — the hedged-re-serve trigger: a per-stream
  inter-batch-gap threshold FIT from the observed gap distribution using
  the telemetry registry's log-spaced latency buckets (the PR 4
  histogram scheme), not a magic constant.

Wiring lives in ``client.py`` (per-peer breakers/budgets, hedged
re-serves in the static drain), ``dispatcher.py`` (deadline gate, the
journaled ``breaker``/``brownout`` WAL ops, serving-set exclusion),
``worker.py`` (deadline gate, the ``slow-peer`` failpoint), and
``fleet.py`` (brownout-aware credit scales).
See ``docs/guides/service.md#failure-model-and-recovery``.
"""

from __future__ import annotations

import threading
import time

from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.telemetry.metrics import RESILIENCE_DEADLINE_EXCEEDED
from petastorm_tpu.telemetry.registry import log_buckets

logger = service_logger(__name__)

#: The wire field carrying the caller's REMAINING budget in seconds.
#: Relative, not absolute: monotonic clocks (and wall clocks, under NTP
#: steps) do not transfer across hosts, so the caller ships "how long I
#: will still wait" and the handler re-anchors it locally on arrival.
DEADLINE_FIELD = "deadline_left_s"


# -- deadline propagation ----------------------------------------------------

def attach_deadline(header, deadline, clock=time.monotonic):
    """Stamp the remaining budget onto an outbound header (in place).

    ``deadline`` is a LOCAL monotonic deadline (``None`` = no budget —
    the field is omitted and handlers apply no gate). Stamped per
    attempt, so a retry after backoff ships the smaller remaining
    budget, never the original one.
    """
    if deadline is not None:
        header[DEADLINE_FIELD] = max(0.0, round(deadline - clock(), 4))
    return header


def arrival_deadline(header, clock=time.monotonic):
    """The caller's budget as a LOCAL monotonic deadline, or ``None``
    when the request carries none (or an unparseable value — an old or
    foreign caller must not be refused over an optional field)."""
    left = header.get(DEADLINE_FIELD)
    if left is None:
        return None
    try:
        return clock() + max(0.0, float(left))
    except (TypeError, ValueError):
        return None


def deadline_expired(deadline, clock=time.monotonic):
    """``True`` when a (local monotonic) deadline has passed."""
    return deadline is not None and clock() >= deadline


def deadline_exceeded_reply(site, clock=time.monotonic):
    """The retryable error reply a handler returns instead of starting
    (or continuing) work the caller has stopped waiting for. Retryable:
    the CALLER's ``retry_with_backoff(deadline_s=)`` is the budget's
    source of truth — it re-attempts while its own budget lasts and
    raises the moment it is exhausted."""
    RESILIENCE_DEADLINE_EXCEEDED.labels(site).inc()
    return {"type": "error", "retryable": True,
            "error": (f"DEADLINE_EXCEEDED: {site}: the request's "
                      f"propagated budget expired before the work "
                      f"finished — refused so capacity goes to requests "
                      f"someone still waits for")}


# -- retry budget ------------------------------------------------------------

class RetryBudget:
    """Per-peer retry token bucket: retries SPEND, successes REFILL.

    Bounds the retry rate against a failing peer to
    ``refill_per_success`` retries per successful call (plus the initial
    ``capacity`` burst) — the standard antidote to retry storms: when a
    peer degrades, clients collectively stop multiplying its load.
    Thread-safe; arithmetic only, no clocks.
    """

    def __init__(self, capacity=10.0, refill_per_success=0.5,
                 initial=None):
        if capacity <= 0:
            raise ValueError("capacity must be > 0")
        self.capacity = float(capacity)
        self.refill_per_success = float(refill_per_success)
        self._balance = float(capacity if initial is None else initial)
        self._denied = 0
        self._lock = threading.Lock()

    @property
    def balance(self):
        with self._lock:
            return self._balance

    @property
    def denied(self):
        """Retries refused because the bucket was empty."""
        with self._lock:
            return self._denied

    def try_spend(self, cost=1.0):
        """Take ``cost`` tokens for one retry; ``False`` (and nothing
        taken) when the bucket cannot cover it."""
        with self._lock:
            if self._balance < cost:
                self._denied += 1
                return False
            self._balance -= cost
            return True

    def record_success(self):
        """A successful call refills a fraction of the bucket."""
        with self._lock:
            self._balance = min(self.capacity,
                                self._balance + self.refill_per_success)

    def snapshot(self):
        with self._lock:
            return {"capacity": self.capacity,
                    "balance": round(self._balance, 3),
                    "denied": self._denied}


# -- circuit breaker ---------------------------------------------------------

#: Breaker states, with the numeric codes the
#: ``petastorm_resilience_breaker_state`` gauge exports.
BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"
BREAKER_STATE_CODES = {BREAKER_CLOSED: 0, BREAKER_OPEN: 1,
                       BREAKER_HALF_OPEN: 2}


class CircuitBreaker:
    """Consecutive-failure circuit breaker (closed → open → half-open).

    Pure and golden-testable: time enters ONLY as the explicit ``now``
    argument (any monotonic float), so canned-sequence tests drive the
    full state machine deterministically — the
    :func:`~petastorm_tpu.service.fleet.plan_fair_shares` discipline.

    - **closed**: calls allowed; ``threshold`` CONSECUTIVE failures trip
      it open (one success resets the streak — a flapping peer must
      actually fail in a row to trip).
    - **open**: calls refused (fail fast, route around) until
      ``cooldown_s`` has elapsed since the trip.
    - **half-open**: after the cooldown, exactly ONE probe call is
      allowed through; its success closes the breaker, its failure
      re-opens (and restarts the cooldown).
    """

    def __init__(self, threshold=5, cooldown_s=5.0):
        if threshold < 1:
            raise ValueError("threshold must be >= 1")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self._state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = None
        self._probe_inflight = False
        self._lock = threading.Lock()

    @property
    def state(self):
        with self._lock:
            return self._state

    @property
    def state_code(self):
        return BREAKER_STATE_CODES[self.state]

    @property
    def consecutive_failures(self):
        with self._lock:
            return self._failures

    def allow(self, now):
        """Whether a call to the peer may proceed at ``now``. Moving an
        open breaker past its cooldown transitions to half-open and
        admits exactly one probe."""
        with self._lock:
            if self._state == BREAKER_CLOSED:
                return True
            if self._state == BREAKER_OPEN:
                if now - self._opened_at < self.cooldown_s:
                    return False
                self._state = BREAKER_HALF_OPEN
                self._probe_inflight = True
                return True
            # half-open: one probe at a time.
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_failure(self, now):
        """Count one failure; ``True`` exactly when this failure TRIPPED
        the breaker open (the caller's report/journal edge)."""
        with self._lock:
            if self._state == BREAKER_HALF_OPEN:
                # The probe failed: back to open, cooldown restarts.
                self._state = BREAKER_OPEN
                self._opened_at = now
                self._probe_inflight = False
                return False
            if self._state == BREAKER_OPEN:
                return False
            self._failures += 1
            if self._failures >= self.threshold:
                self._state = BREAKER_OPEN
                self._opened_at = now
                self._failures = 0
                return True
            return False

    def record_success(self, now=None):
        """A successful call: closes a half-open breaker, resets the
        failure streak of a closed one. (``now`` accepted for signature
        symmetry; the transition needs no clock.)"""
        del now
        with self._lock:
            self._state = BREAKER_CLOSED
            self._failures = 0
            self._opened_at = None
            self._probe_inflight = False

    def snapshot(self):
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._failures}


# -- hedged re-serve threshold ------------------------------------------------

class GapTracker:
    """Dynamic hedge threshold fit from observed inter-batch gaps.

    Counts every observed gap into the telemetry registry's log-spaced
    latency buckets (:func:`petastorm_tpu.telemetry.registry.log_buckets`
    — the PR 4 histogram scheme) and derives the hedge trigger as
    ``clamp(multiplier × quantile(q), floor_s, cap_s)``: a stream whose
    silence exceeds several times the fleet's own p99 gap is an outlier
    worth hedging, whatever that p99 happens to be — no magic latency
    constant that would misfire on both a fast local fleet and a slow
    remote one.

    Returns ``None`` (hedging disarmed) until ``min_samples`` gaps have
    been observed: an empty histogram has no p99 to fit.
    """

    def __init__(self, quantile=0.99, multiplier=4.0, min_samples=16,
                 floor_s=0.25, cap_s=30.0, buckets=None):
        if not 0.0 < quantile <= 1.0:
            raise ValueError("quantile must be in (0, 1]")
        if multiplier <= 0:
            raise ValueError("multiplier must be > 0")
        self.quantile = float(quantile)
        self.multiplier = float(multiplier)
        self.min_samples = int(min_samples)
        self.floor_s = float(floor_s)
        self.cap_s = float(cap_s)
        self._bounds = tuple(buckets) if buckets is not None \
            else log_buckets()
        self._counts = [0] * (len(self._bounds) + 1)
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, gap_s):
        gap_s = float(gap_s)
        with self._lock:
            for i, bound in enumerate(self._bounds):
                if gap_s <= bound:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1
            self._count += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    def threshold_s(self):
        """The current hedge trigger in seconds, or ``None`` while too
        few gaps have been observed to fit one."""
        with self._lock:
            total = self._count
            if total < self.min_samples:
                return None
            # Histogram-quantile estimate: linear interpolation inside
            # the bucket that crosses rank q×count (the registry
            # HistogramChild.quantile construction).
            rank = self.quantile * total
            seen = 0
            prev_bound = 0.0
            fitted = self._bounds[-1]
            for i, bound in enumerate(self._bounds):
                in_bucket = self._counts[i]
                if seen + in_bucket >= rank:
                    if in_bucket:
                        frac = (rank - seen) / in_bucket
                        fitted = prev_bound + frac * (bound - prev_bound)
                    else:
                        fitted = bound
                    break
                seen += in_bucket
                prev_bound = bound
        return min(max(fitted * self.multiplier, self.floor_s), self.cap_s)


# -- brownout ----------------------------------------------------------------

#: Brownout levels, in shed order. Level 1 sheds low-weight/sideband
#: jobs' credit windows (fleet.credit_scales' brownout factor); level 2
#: also sheds optional stages (tracing spans, autotune probes).
BROWNOUT_MAX_LEVEL = 2


class BrownoutConfig:
    """Knobs of the brownout state machine (windows are evaluation
    rounds — the dispatcher evaluates at most once per
    ``interval_s``).

    :param interval_s: minimum seconds between evaluations.
    :param enter_credit_wait_s: overload when the fleet's credit-wait
        accumulates faster than this many seconds per second (workers
        blocked on client flow control — consumers can't keep up).
    :param enter_ready_saturation: overload when any client reports its
        ready queue at or above this fullness fraction.
    :param exit_fraction: calm when BOTH signals sit below this fraction
        of their enter thresholds (a strictly lower bar, so the machine
        cannot flap on a signal hovering at the threshold).
    :param up_windows/down_windows: hysteresis streak lengths for
        entering/recovering one level.
    :param cooldown_windows: evaluation rounds after any transition in
        which neither streak accumulates.
    :param max_level: deepest shed level.
    """

    def __init__(self, interval_s=1.0, enter_credit_wait_s=0.5,
                 enter_ready_saturation=0.9, exit_fraction=0.5,
                 up_windows=3, down_windows=3, cooldown_windows=1,
                 max_level=BROWNOUT_MAX_LEVEL):
        if not 0.0 < exit_fraction < 1.0:
            raise ValueError("exit_fraction must be in (0, 1)")
        if max_level < 1:
            raise ValueError("max_level must be >= 1")
        self.interval_s = float(interval_s)
        self.enter_credit_wait_s = float(enter_credit_wait_s)
        self.enter_ready_saturation = float(enter_ready_saturation)
        self.exit_fraction = float(exit_fraction)
        self.up_windows = int(up_windows)
        self.down_windows = int(down_windows)
        self.cooldown_windows = int(cooldown_windows)
        self.max_level = int(max_level)

    @classmethod
    def coerce(cls, value):
        """``True``/dict/config → a :class:`BrownoutConfig`."""
        if isinstance(value, cls):
            return value
        if value is True:
            return cls()
        if isinstance(value, dict):
            return cls(**value)
        raise TypeError(
            f"brownout must be True, a dict of BrownoutConfig kwargs, or "
            f"a BrownoutConfig — got {value!r}")


class BrownoutPlanner:
    """Pure shed/recover planner over one overload-signals snapshot.

    ``plan(signals)`` consumes::

        {"level": int,                   # current (journaled) level
         "credit_wait_rate": float,      # fleet credit-wait s/s
         "ready_saturation": float}      # max client queue fullness 0..1

    and returns at most one transition,
    ``[{"action": "shed"|"recover", "level": new_level, "reason": str}]``
    — the dispatcher applies it through a journaled ``brownout`` WAL op,
    exactly like the autoscaler's decisions. Stateful only in its
    hysteresis streaks; no clocks, no randomness — canned-signal goldens
    pin shed order, hysteresis, and symmetric recovery exactly.

    Hysteresis mirrors :class:`~petastorm_tpu.service.fleet
    .AutoscalePlanner`: ``up_windows`` consecutive overloaded rounds shed
    one level, ``down_windows`` consecutive calm rounds recover one, a
    round that is neither resets both streaks, and any transition starts
    a cooldown in which neither streak accumulates. Recovery requires
    BOTH signals below ``exit_fraction`` of their enter thresholds — a
    strictly lower bar than entry, so a signal hovering at the threshold
    cannot flap the level.
    """

    def __init__(self, config=None):
        self.config = config or BrownoutConfig()
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0

    def plan(self, signals):
        cfg = self.config
        level = int(signals.get("level", 0))
        wait_rate = float(signals.get("credit_wait_rate", 0.0))
        saturation = float(signals.get("ready_saturation", 0.0))
        if self._cooldown > 0:
            self._cooldown -= 1
            return []
        overloaded = (wait_rate >= cfg.enter_credit_wait_s
                      or saturation >= cfg.enter_ready_saturation)
        calm = (wait_rate < cfg.enter_credit_wait_s * cfg.exit_fraction
                and saturation < (cfg.enter_ready_saturation
                                  * cfg.exit_fraction))
        if overloaded and level < cfg.max_level:
            self._up_streak += 1
            self._down_streak = 0
            if self._up_streak >= cfg.up_windows:
                self._up_streak = 0
                self._cooldown = cfg.cooldown_windows
                return [{
                    "action": "shed", "level": level + 1,
                    "reason": (f"overload for {cfg.up_windows} windows "
                               f"(credit_wait {wait_rate:.2f}s/s, "
                               f"ready {saturation:.0%})")}]
        elif calm and level > 0:
            self._down_streak += 1
            self._up_streak = 0
            if self._down_streak >= cfg.down_windows:
                self._down_streak = 0
                self._cooldown = cfg.cooldown_windows
                return [{
                    "action": "recover", "level": level - 1,
                    "reason": (f"calm for {cfg.down_windows} windows "
                               f"(credit_wait {wait_rate:.2f}s/s, "
                               f"ready {saturation:.0%})")}]
        else:
            self._up_streak = 0
            self._down_streak = 0
        return []


# -- optional-stage shedding (brownout level 2) ------------------------------

#: Process-local view of the dispatcher's brownout level, updated by
#: clients/workers from reply fields. Read by the optional stages the
#: level-2 brownout sheds: batch-lifecycle trace spans and autotune
#: probes consult :func:`optional_stages_shed` before doing optional
#: work. A plain int behind a lock — the hot-path read is one attribute
#: load.
_BROWNOUT_LEVEL = 0
_BROWNOUT_LOCK = threading.Lock()
_SHED_TRACING = False  # we disabled the trace collector; restore on recovery


def note_brownout_level(level):
    """Record the dispatcher-reported brownout level (idempotent).

    Level 2 sheds the process's batch-lifecycle trace collector (span
    recording is pure overhead when the fleet is drowning); recovery
    below 2 restores it IF this function disabled it — an operator's own
    enable/disable outside a brownout is never overridden."""
    global _BROWNOUT_LEVEL, _SHED_TRACING
    level = int(level)
    with _BROWNOUT_LOCK:
        changed, _BROWNOUT_LEVEL = (_BROWNOUT_LEVEL != level), level
        if changed:
            from petastorm_tpu.telemetry import tracing
            if level >= 2 and tracing.COLLECTOR.enabled:
                _SHED_TRACING = True
                tracing.COLLECTOR.enabled = False
            elif level < 2 and _SHED_TRACING:
                _SHED_TRACING = False
                tracing.COLLECTOR.enabled = True
    if changed:
        logger.warning("brownout level is now %d (%s)", level,
                       "optional stages shed" if level >= 2 else
                       "low-weight jobs' credits scaled" if level == 1
                       else "normal service")


def brownout_level():
    return _BROWNOUT_LEVEL


def optional_stages_shed():
    """Whether level-2 brownout is in force: optional stages (tracing
    spans, autotune probes) should skip their work this call."""
    return _BROWNOUT_LEVEL >= 2
