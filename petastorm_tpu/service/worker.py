"""Batch workers: the data plane of the disaggregated data service.

A worker wraps the ordinary single-process input pipeline — a
``make_reader``-family Reader plus ``batch_iterator`` collation — and serves
the resulting ready-to-stage numpy batch dicts over framed TCP. Each
``stream`` request names an explicit set of row-group piece indices (the
dispatcher's split plan), which the worker turns into a Reader via the
reader layer's ``piece_indices=`` planning hook; the stream then carries one
``batch`` message per collated batch and a final ``end`` message with the
row total, all payload-encoded by the pool serializers
(:mod:`petastorm_tpu.reader_impl.framed_socket`).

Remote observability: a ``diagnostics`` request snapshots every active
stream's ``Reader.diagnostics`` (and the final snapshot of recently finished
streams), so a trainer-side client can root-cause a remote input stall the
same way it would a local one (``docs/guides/diagnostics.md``).
"""

from __future__ import annotations

import threading
import time
import uuid

from petastorm_tpu.reader_impl.framed_socket import (
    ConnectionClosedError,
    FramedReader,
    FramedServer,
    send_framed,
)
from petastorm_tpu.telemetry import tracing
from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.telemetry.metrics import (
    WORKER_ACTIVE_STREAMS,
    WORKER_BATCHES_SENT,
    WORKER_CREDIT_WAIT,
    WORKER_DECODE_SECONDS,
    WORKER_ROWS_SENT,
    WORKER_STREAMS,
)

logger = service_logger(__name__)

_FACTORIES = ("row", "batch", "columnar")

#: Final diagnostics snapshots kept for the ``diagnostics`` request.
_COMPLETED_SNAPSHOTS_KEPT = 16


def _resolve_factory(reader_factory):
    if callable(reader_factory):
        return reader_factory
    from petastorm_tpu.reader.reader import (
        make_batch_reader,
        make_columnar_reader,
        make_reader,
    )

    factories = {"row": make_reader, "batch": make_batch_reader,
                 "columnar": make_columnar_reader}
    if reader_factory not in factories:
        raise ValueError(
            f"reader_factory must be a callable or one of {_FACTORIES}, "
            f"got {reader_factory!r}")
    return factories[reader_factory]


class BatchWorker:
    """Serve collated batches of ``dataset_url`` over TCP.

    :param dataset_url: the dataset every stream reads (workers in one
        service must all point at the same dataset).
    :param dispatcher_address: ``(host, port)`` to register with (optional —
        a worker can be addressed directly in tests).
    :param batch_size: rows per collated batch. The last batch of a stream
        is ragged (``last_batch="keep"``): the service must not drop rows —
        equal-step SPMD shaping stays the trainer-side loader's concern.
    :param reader_factory: ``"row"`` (make_reader), ``"batch"``
        (make_batch_reader), ``"columnar"`` (make_columnar_reader), or any
        callable with the same signature.
    :param reader_kwargs: extra kwargs for the factory (``workers_count``,
        ``reader_pool_type``, ``filters``, ...). ``piece_indices``,
        ``num_epochs`` and ``shuffle_row_groups`` are owned by the stream
        protocol.
    :param batch_delay_s: fault injection for benchmarks/tests — sleep this
        long before each ``batch`` send, simulating a slow worker (the
        ``--skew-ms`` knob of the ``service`` benchmark scenario).
    :param heartbeat_interval_s: renew the dispatcher lease this often; a
        worker that misses its lease (``Dispatcher(lease_timeout_s=...)``)
        is evicted. The loop also heals restarts: an ``unknown_worker``
        reply (dispatcher came back without this worker's state) triggers
        automatic re-registration under the same ``worker_id``. ``None``
        disables the loop (direct-addressed test workers).
    :param rpc_deadline_s: total time budget for each control RPC against
        the dispatcher (registration, heartbeats) across all its retries —
        the shared ``retry_with_backoff`` deadline policy.
    :param max_frame_bytes: per-connection receive frame cap (requests to
        a worker are small control messages; batches only flow OUT).
    """

    def __init__(self, dataset_url, dispatcher_address=None,
                 host="127.0.0.1", port=0, batch_size=64,
                 reader_factory="row", reader_kwargs=None, worker_id=None,
                 register_retries=5, register_backoff=0.2,
                 batch_delay_s=0.0, heartbeat_interval_s=5.0,
                 rpc_deadline_s=30.0, max_frame_bytes=None):
        self.dataset_url = dataset_url
        self.worker_id = worker_id or f"worker-{uuid.uuid4().hex[:8]}"
        self._dispatcher_address = (tuple(dispatcher_address)
                                    if dispatcher_address else None)
        self._batch_size = batch_size
        self._factory = _resolve_factory(reader_factory)
        self._reader_kwargs = dict(reader_kwargs or {})
        # piece_indices/num_epochs/shuffle_row_groups belong to the stream
        # protocol; rowgroup_selector and cur_shard/shard_count/shard_seed
        # would change (selector) or silently re-shard (sharding) the piece
        # universe the dispatcher's plan is denominated in — sample loss or
        # out-of-range splits. Split planning is the dispatcher's job.
        for owned in ("piece_indices", "num_epochs", "shuffle_row_groups",
                      "rowgroup_selector", "cur_shard", "shard_count",
                      "shard_seed"):
            if owned in self._reader_kwargs:
                raise ValueError(
                    f"reader_kwargs[{owned!r}] is owned by the service's "
                    f"split protocol (the dispatcher plans row-group "
                    f"assignment), not worker construction")
        self._register_retries = register_retries
        self._register_backoff = register_backoff
        self._batch_delay_s = float(batch_delay_s)
        self._heartbeat_interval_s = heartbeat_interval_s
        self._rpc_deadline_s = rpc_deadline_s
        self._max_frame_bytes = max_frame_bytes
        self.num_pieces = None
        self._lock = threading.Lock()
        self._active = {}            # stream key -> {"reader", "flow"}
        self._completed = {}         # stream key -> final diagnostics dict
        self._log = logger.bind(worker_id=self.worker_id)
        # Interned registry children (telemetry.metrics): typed, scrapeable
        # counters behind the legacy diagnostics snapshots.
        self._m_batches = WORKER_BATCHES_SENT.labels(self.worker_id)
        self._m_rows = WORKER_ROWS_SENT.labels(self.worker_id)
        self._m_credit_wait = WORKER_CREDIT_WAIT.labels(self.worker_id)
        self._m_active = WORKER_ACTIVE_STREAMS.labels(self.worker_id)
        self._m_decode = WORKER_DECODE_SECONDS.labels(self.worker_id)
        self._heartbeat_thread = None
        self._heartbeat_stop = threading.Event()
        self._heartbeat_paused = threading.Event()  # test hook: hung worker
        self._server = FramedServer(self._serve_connection, host=host,
                                    port=port,
                                    name=f"service-worker-{self.worker_id}")

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self.num_pieces = self._count_pieces()
        self._server.start()
        if self._dispatcher_address is not None:
            self._register()
            if self._heartbeat_interval_s is not None:
                self._heartbeat_thread = threading.Thread(
                    target=self._heartbeat_loop, daemon=True,
                    name=f"service-worker-{self.worker_id}-heartbeat")
                self._heartbeat_thread.start()
        return self

    @property
    def address(self):
        return self._server.address

    def stop(self, drain_timeout_s=5.0):
        """Graceful teardown, in dependency order: stop accepting and close
        the listener + open connections FIRST (stream threads blocked in
        ``recv``/``send`` exit on the closed socket instead of raising into
        a half-torn worker), then drain in-flight stream threads with a
        bounded join, and only then stop any reader a straggler thread left
        behind — a stop during an active stream can't leak a thread or
        race reader teardown against a live send loop."""
        self._server.stopped.set()
        self._heartbeat_stop.set()
        self._server.stop()
        stragglers = self._server.join(timeout=drain_timeout_s)
        if stragglers:
            self._log.warning(
                "%d stream thread(s) still alive after the %.1fs stop "
                "drain — stopping their readers under them",
                len(stragglers), drain_timeout_s)
        with self._lock:
            readers = [entry["reader"] for entry in self._active.values()]
        for reader in readers:
            try:
                reader.stop()
            except Exception:
                pass
        if self._heartbeat_thread is not None:
            self._heartbeat_thread.join(timeout=drain_timeout_s)

    def kill(self):
        """Abrupt failure injection (tests): drop every open connection
        without sending ``end``, then tear down — clients see a mid-stream
        :class:`ConnectionClosedError`, exactly like a worker host dying."""
        self._server.stopped.set()
        self._heartbeat_stop.set()
        self._server.close_connections()
        self.stop()

    def drop_connections(self):
        """Drop every open connection without stopping the server (fault
        injection: a network blip — clients reconnect and re-stream)."""
        self._server.close_connections()

    def pause_heartbeats(self):
        """Test hook: stop renewing the dispatcher lease while the server
        keeps running — simulates a hung-but-connected worker so lease
        expiry (not connection failure) is what evicts it."""
        self._heartbeat_paused.set()

    def resume_heartbeats(self):
        self._heartbeat_paused.clear()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()

    # -- registration / planning ------------------------------------------

    def _count_pieces(self):
        """Enumerate the dataset's row-group pieces with the same planning
        config every stream reader will use — the count the dispatcher's
        split plan is denominated in."""
        from petastorm_tpu.fs_utils import get_filesystem_and_path_or_paths
        from petastorm_tpu.reader.reader import enumerate_row_group_pieces

        fs, path = get_filesystem_and_path_or_paths(
            self.dataset_url,
            storage_options=self._reader_kwargs.get("storage_options"),
            filesystem=self._reader_kwargs.get("filesystem"))
        return len(enumerate_row_group_pieces(
            fs, path, self._reader_kwargs.get("filters")))

    def _register(self, re_register=False, retries=None):
        host, port = self.address
        reply = self._control_rpc({
            "type": "register_worker",
            "worker_id": self.worker_id,
            "host": host,
            "port": port,
            "num_pieces": self.num_pieces,
            "re_register": re_register,
        }, description=f"worker {self.worker_id} registration",
            retries=retries)
        if reply.get("type") != "ok":
            raise RuntimeError(
                f"dispatcher rejected registration: "
                f"{reply.get('error', reply)}")
        return reply

    def _control_rpc(self, header, description, retries=None):
        """One control request/reply against the dispatcher under the
        shared retry policy: bounded attempts, exponential backoff with
        jitter, and a total ``rpc_deadline_s`` budget. Heartbeat ticks
        pass ``retries=0`` — their loop IS the retry, and a stop() must
        not wait out a backoff budget against a dead dispatcher."""
        from petastorm_tpu.reader_impl.framed_socket import FramedConnection
        from petastorm_tpu.utils import retry_with_backoff

        def attempt():
            with FramedConnection.connect(self._dispatcher_address,
                                          timeout=10.0) as conn:
                reply, _ = conn.request(header)
            return reply

        return retry_with_backoff(
            attempt,
            retries=self._register_retries if retries is None else retries,
            base_delay=self._register_backoff,
            retry_on=(OSError,), deadline_s=self._rpc_deadline_s,
            description=description)

    def _heartbeat_loop(self):
        """Renew the dispatcher lease every ``heartbeat_interval_s``; an
        ``unknown_worker`` reply (the dispatcher restarted without this
        worker's state, or evicted it) triggers re-registration under the
        same ``worker_id``. A dispatcher outage is just a missed tick —
        the loop keeps trying until the dispatcher returns."""
        while not self._heartbeat_stop.wait(self._heartbeat_interval_s):
            if self._heartbeat_paused.is_set():
                continue
            try:
                reply = self._control_rpc(
                    {"type": "worker_heartbeat", "worker_id": self.worker_id},
                    description=f"worker {self.worker_id} heartbeat",
                    retries=0)
            except OSError:
                continue  # dispatcher down: retry next tick
            if reply.get("type") == "unknown_worker" \
                    and not self._heartbeat_stop.is_set():
                self._log.warning(
                    "dispatcher no longer knows this worker — "
                    "re-registering",
                    fencing_epoch=reply.get("fencing_epoch"))
                try:
                    # retries=0 keeps the tick bounded by one dial: the
                    # loop itself is the retry, and stop() must not wait
                    # out a 30s backoff budget against a dead dispatcher.
                    self._register(re_register=True, retries=0)
                except (OSError, RuntimeError):
                    continue  # registration retried on the next tick

    # -- serving -----------------------------------------------------------

    def _serve_connection(self, sock):
        reader = FramedReader(sock,  # buffered, per-connection
                              max_frame_bytes=self._max_frame_bytes)
        while not self._server.stopped.is_set():
            header, _ = reader.recv()
            kind = header.get("type")
            if kind == "stream":
                self._stream(sock, header, conn_reader=reader)
            elif kind == "credit":
                # A replenishment raced the stream's `end` (the client sends
                # credits as it consumes, and the tail of those can land
                # after the stream finished) — stale, not an error.
                pass
            elif kind == "diagnostics":
                send_framed(sock, {"type": "diagnostics",
                                   "worker_id": self.worker_id},
                            self.diagnostics_snapshot())
            elif kind == "ping":
                send_framed(sock, {"type": "pong",
                                   "worker_id": self.worker_id})
            else:
                send_framed(sock, {"type": "error",
                                   "error": f"unknown request {kind!r}"})

    def _stream(self, sock, header, conn_reader):
        """Serve one ``stream`` request: batches of the named pieces, then
        ``end``. A reader/collation error becomes an ``error`` message (the
        client re-raises it — a bad plan is not a transient failure).

        Flow control: a ``credits`` field in the request bounds the window
        of un-acknowledged batches. Each ``batch`` send spends one credit;
        the client replenishes with ``credit`` messages as it consumes. Out
        of credits, the worker blocks reading the replenishment stream —
        per-worker in-flight batches stay <= the window instead of growing
        with the socket buffer (unbounded push) or collapsing to
        request/response lockstep. Without the field the stream is
        unbounded (pre-credit clients).

        Telemetry: each batch gets an id minted here
        (``<worker_id>:<stream>:<seq>``) and carried in the ``batch``
        header — the cross-process key batch-lifecycle tracing correlates
        spans on (decode/send worker-side; recv/queue/dispatch
        client-side). Decode and send times land in the registry whether or
        not tracing is armed."""
        from petastorm_tpu.jax_utils.batcher import batch_iterator

        pieces = [int(p) for p in header["pieces"]]
        credits = header.get("credits")
        credits = int(credits) if credits is not None else None
        flow = {"credits_window": credits, "credits_left": credits,
                "batches_sent": 0, "credit_wait_s": 0.0}
        stream_key = f"{uuid.uuid4().hex[:8]}"
        reader = None
        rows_sent = 0
        # "aborted" covers the early returns (worker stop mid-stream, no
        # `end` frame sent); only the `end` send flips it to "completed".
        outcome = "aborted"
        collector = tracing.COLLECTOR
        try:
            # cur_shard=0/shard_count=1 pins sharding OFF: the factory
            # defaults would silently fill jax.process_index()/count() on a
            # host with multi-process JAX initialized, dropping (N-1)/N of
            # the assigned pieces AFTER piece_indices selection — the
            # dispatcher's plan is the only sharding a worker applies.
            reader = self._factory(self.dataset_url, piece_indices=pieces,
                                   num_epochs=1, shuffle_row_groups=False,
                                   cur_shard=0, shard_count=1,
                                   **self._reader_kwargs)
            with self._lock:
                self._active[stream_key] = {"reader": reader, "flow": flow}
            self._m_active.inc()
            batches = iter(batch_iterator(reader, self._batch_size,
                                          last_batch="keep"))
            while True:
                # Manual iteration so the pull itself (read + collate) is
                # a measured decode span, attributable per batch id.
                t_decode = time.perf_counter()
                batch = next(batches, None)
                t_decoded = time.perf_counter()
                if batch is None:
                    break
                self._m_decode.observe(t_decoded - t_decode)
                bid = f"{self.worker_id}:{stream_key}:{flow['batches_sent']}"
                if collector.enabled:
                    collector.record_span("worker.decode", t_decode,
                                          t_decoded, bid=bid)
                if self._server.stopped.is_set():
                    return
                if credits is not None:
                    # Drain replenishments OPPORTUNISTICALLY every batch,
                    # not only when starved: un-read credit messages would
                    # otherwise pile up in the TCP buffers all stream long
                    # until the client's blocking ack send wedges against
                    # this worker's blocking batch send (a four-way
                    # distributed deadlock on long streams).
                    while conn_reader.data_pending():
                        reply, _ = conn_reader.recv()
                        if reply.get("type") == "credit":
                            flow["credits_left"] += int(reply.get("n", 1))
                        # anything else mid-stream is out of protocol; skip
                if credits is not None and flow["credits_left"] <= 0:
                    t0 = time.perf_counter()
                    while flow["credits_left"] <= 0:
                        if self._server.stopped.is_set():
                            return
                        reply, _ = conn_reader.recv()
                        if reply.get("type") == "credit":
                            flow["credits_left"] += int(reply.get("n", 1))
                    waited = time.perf_counter() - t0
                    flow["credit_wait_s"] += waited
                    self._m_credit_wait.inc(waited)
                if self._batch_delay_s:
                    time.sleep(self._batch_delay_s)
                n = self._batch_rows(batch)
                t_send = time.perf_counter()
                send_framed(sock, {"type": "batch", "rows": n, "bid": bid},
                            batch)
                if collector.enabled:
                    collector.record_span("worker.send", t_send,
                                          time.perf_counter(), bid=bid)
                rows_sent += n
                flow["batches_sent"] += 1
                self._m_batches.inc()
                self._m_rows.inc(n)
                if credits is not None:
                    flow["credits_left"] -= 1
            send_framed(sock, {"type": "end", "rows": rows_sent,
                               "pieces": pieces})
            outcome = "completed"
        except (ConnectionClosedError, OSError):
            outcome = "disconnected"
            raise  # client hung up — nothing to tell it
        except Exception as exc:
            outcome = "error"
            self._log.exception("stream failed", stream=stream_key,
                                pieces=pieces)
            send_framed(sock, {"type": "error", "error": str(exc)})
        finally:
            with self._lock:
                started = stream_key in self._active
                self._active.pop(stream_key, None)
                if reader is not None:
                    self._completed[stream_key] = dict(reader.diagnostics,
                                                       **flow)
                    while len(self._completed) > _COMPLETED_SNAPSHOTS_KEPT:
                        self._completed.pop(next(iter(self._completed)))
            if started:
                self._m_active.dec()
            WORKER_STREAMS.labels(self.worker_id, outcome).inc()
            if reader is not None:
                reader.stop()
                reader.join()

    @staticmethod
    def _batch_rows(batch):
        for value in batch.values():
            return int(len(value))
        return 0

    def diagnostics_snapshot(self):
        """``Reader.diagnostics`` of every active stream (merged with its
        flow-control state — credits window/left, batches sent, seconds
        blocked waiting for replenishment) plus the final snapshot of
        recently finished ones — what a remote client sees. The
        ``metrics`` block carries this worker's lifetime registry counters
        (monotonic, so two probes give fleet rates — what ``python -m
        petastorm_tpu.service status --watch`` renders)."""
        with self._lock:
            active = {key: dict(entry["reader"].diagnostics,
                                **entry["flow"])
                      for key, entry in self._active.items()}
            completed = {key: dict(diag)
                         for key, diag in self._completed.items()}
        return {
            "worker_id": self.worker_id,
            "num_pieces": self.num_pieces,
            "active_streams": active,
            "completed_streams": completed,
            "metrics": {
                "batches_sent_total": self._m_batches.value,
                "rows_sent_total": self._m_rows.value,
                "credit_wait_seconds_total": self._m_credit_wait.value,
                "active_streams": self._m_active.value,
            },
        }
