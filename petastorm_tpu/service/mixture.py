"""Deterministic weighted mixture sampling with hot-reloadable weights.

``weighted_sampling_reader.py`` is the reference's answer to multi-corpus
mixing: an ad-hoc ``random.Random`` draw loop that is nondeterministic by
default (``random_seed=None``), not checkpointable (the RNG state is
implicit in how many draws happened), not subset-stable (remove one corpus
and every later draw changes), and ends by silently propagating a
``StopIteration`` from whichever corpus exhausts first. None of that
survives contact with the service's contracts — byte-identical streams
across kills, resumes, and fleet reshapes.

This module is the service-grade replacement:

- :class:`MixtureSpec` — named corpora with weights; the thing
  ``set_mixture_weights`` rebalances.
- :class:`MixtureSampler` — every draw is a pure function of
  ``(seed, epoch, draw ordinal)`` via the seed-tree fold-in
  (:mod:`petastorm_tpu.service.seedtree`): draw ``i`` lands on the same
  corpus in every run of the same seed and weight log, regardless of
  process, prefetch depth, or what happened to other draws —
  checkpointable by construction (``state_dict`` is a handful of
  ordinals). An explicit seed is REQUIRED; there is no nondeterministic
  default to forget. Exhaustion is a declared policy (``stop`` /
  ``exhaust`` / ``reweight``), not an escaped exception.
- :class:`MixedBatchSource` — the trainer-side composition: one batch
  source per corpus (``ServiceBatchSource`` over per-corpus fleets of one
  dispatcher — workers register with ``corpus=`` names), batches drawn per
  the sampler. Weight changes journaled at the dispatcher
  (``set_mixture_weights``) are fetched and applied at epoch boundaries,
  so the delivered stream is a pure function of
  ``(seed, weight-change log)`` — rebalance the data mix mid-run without
  restarting the fleet, reproducibly (``docs/guides/llm.md``).
"""

from __future__ import annotations

import threading

from petastorm_tpu.service.seedtree import fold_in
from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.telemetry.metrics import (
    MIXTURE_DRAWS,
    MIXTURE_EXHAUSTED,
    MIXTURE_WEIGHT,
    MIXTURE_WEIGHT_RELOADS,
)

logger = service_logger(__name__)

_U64 = float(1 << 64)

#: Process-wide count of mixture passes whose iterator is open (for the
#: test suite's leak guard): a pass holds N live per-corpus sources —
#: stream threads, heartbeats, sockets — so abandoning one mid-iteration
#: without ``close()`` leaks a whole fleet's worth of client state.
_OPEN_PASSES_LOCK = threading.Lock()
_OPEN_PASSES = 0


def open_mixture_passes():
    """Live (un-closed, un-exhausted) mixture passes in this process —
    read by ``tests/conftest.py``'s resource-leak guard."""
    with _OPEN_PASSES_LOCK:
        return _OPEN_PASSES


def _note_pass(delta):
    global _OPEN_PASSES
    with _OPEN_PASSES_LOCK:
        _OPEN_PASSES += delta

#: Exhaustion policies (what happens when a drawn corpus has no next
#: batch). ``stop``: the mix ends at the first exhausted draw — every
#: corpus contributes its weighted share right up to a clean, deterministic
#: end. ``exhaust``: the exhausted corpus drops out and the draw re-rolls
#: deterministically among survivors (their relative weights preserved);
#: the mix ends when every corpus is dry. ``reweight``: like ``exhaust``,
#: but the drop-out is recorded as an explicit weight-log entry (corpus →
#: 0, applied at that exact draw ordinal) so the full mixing history reads
#: as one weight-change log.
EXHAUSTION_POLICIES = ("stop", "exhaust", "reweight")


class MixtureExhausted(Exception):
    """The mix ended per its exhaustion policy (a clean end-of-stream,
    not an error)."""


class MixtureSpec:
    """Named corpora and their sampling weights.

    :param corpora: ordered ``[{"name", "url", "weight"}, ...]`` (or
        ``(name, url, weight)`` tuples). Names must be unique and
        non-empty; weights non-negative with a positive sum. Order is
        canonical — it is part of the determinism contract (draws walk
        the cumulative weights in this order).
    """

    def __init__(self, corpora):
        entries = []
        for corpus in corpora or ():
            if isinstance(corpus, dict):
                entry = {"name": str(corpus["name"]),
                         "url": corpus.get("url"),
                         "weight": float(corpus["weight"])}
            else:
                name, url, weight = corpus
                entry = {"name": str(name), "url": url,
                         "weight": float(weight)}
            if not entry["name"]:
                raise ValueError("corpus names must be non-empty")
            if entry["weight"] < 0:
                raise ValueError(
                    f"corpus {entry['name']!r} has negative weight "
                    f"{entry['weight']}")
            entries.append(entry)
        if not entries:
            raise ValueError("a mixture needs at least one corpus")
        names = [e["name"] for e in entries]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate corpus names: {names}")
        if sum(e["weight"] for e in entries) <= 0:
            raise ValueError("mixture weights must sum to a positive value")
        self.corpora = entries

    @property
    def names(self):
        return [e["name"] for e in self.corpora]

    def weights(self):
        return {e["name"]: e["weight"] for e in self.corpora}

    def to_dict(self):
        return {"corpora": [dict(e) for e in self.corpora]}

    @classmethod
    def from_dict(cls, d):
        if isinstance(d, MixtureSpec):
            return d
        return cls(d["corpora"])


def validate_weights(weights, names=None):
    """Validate a ``{corpus: weight}`` reload payload (shared by the
    dispatcher handler and the trainer helper): non-negative floats with
    a positive sum, and — when ``names`` is given — only known corpora."""
    if not isinstance(weights, dict) or not weights:
        raise ValueError("weights must be a non-empty {corpus: weight} map")
    out = {}
    for name, weight in weights.items():
        weight = float(weight)
        if weight < 0:
            raise ValueError(
                f"weight for corpus {name!r} is negative ({weight})")
        out[str(name)] = weight
    if sum(out.values()) <= 0:
        raise ValueError("weights must sum to a positive value")
    if names is not None:
        unknown = sorted(set(out) - set(names))
        if unknown:
            raise ValueError(
                f"unknown corpora in weights: {unknown} (mixture has "
                f"{sorted(names)})")
    return out


class MixtureSampler:
    """Seed-tree corpus sampler: deterministic, checkpointable, policy-
    aware.

    Draw ``i`` of epoch ``e`` maps to the unit interval via
    ``fold_in(fold_in(fold_in(seed, ("mixture",)), ("epoch", e)),
    ("draw", i))`` and walks the cumulative weights in canonical corpus
    order — a pure function of ``(seed, epoch, ordinal, weights)``. The
    weight map in force may change between draws only through
    :meth:`set_weights` (a journaled reload, applied at a deterministic
    boundary) or the exhaustion policy; both are recorded in
    :meth:`state_dict`'s applied-log, so a resumed sampler replays the
    exact sequence.

    :param seed: REQUIRED explicit seed (``None`` raises — the service's
        determinism lint bans hidden RNG state in the data path).
    :param weights: ``{corpus: weight}`` in canonical order (dict order
        is the draw order).
    :param epoch: the epoch folded into every draw key.
    :param exhaustion: one of :data:`EXHAUSTION_POLICIES`.
    """

    def __init__(self, seed, weights, epoch=0, exhaustion="stop"):
        if seed is None:
            raise ValueError(
                "MixtureSampler requires an explicit seed: deterministic "
                "mixing is the contract (an unseeded mix cannot be "
                "checkpointed, resumed, or reproduced — see "
                "docs/guides/llm.md#mixtures)")
        if exhaustion not in EXHAUSTION_POLICIES:
            raise ValueError(
                f"exhaustion must be one of {EXHAUSTION_POLICIES}, got "
                f"{exhaustion!r}")
        self.seed = int(seed)
        self.exhaustion = exhaustion
        self._names = [str(n) for n in weights]
        self._weights = validate_weights(dict(weights), self._names)
        self._epoch = int(epoch)
        self._epoch_key = fold_in(fold_in(self.seed, ("mixture",)),
                                  ("epoch", self._epoch))
        self._ordinal = 0
        self._exhausted = set()
        self._draw_counts = {n: 0 for n in self._names}
        #: applied weight-change events: (ordinal, {corpus: weight}, why)
        self._applied = []
        for name in self._names:
            MIXTURE_WEIGHT.labels(name).set(self._weights[name])

    # -- draws ------------------------------------------------------------

    @property
    def ordinal(self):
        """The next draw's ordinal."""
        return self._ordinal

    @property
    def epoch(self):
        return self._epoch

    def weights(self):
        return dict(self._weights)

    def live_names(self):
        return [n for n in self._names if n not in self._exhausted
                and self._weights[n] > 0]

    def _pick(self, key):
        names = self.live_names()
        if not names:
            raise MixtureExhausted("every corpus is exhausted")
        total = sum(self._weights[n] for n in names)
        u = (key / _U64) * total
        acc = 0.0
        for name in names:
            acc += self._weights[name]
            if u < acc:
                return name
        return names[-1]  # fp rounding guard at the top of the interval

    def draw(self):
        """The corpus of the next draw; advances the ordinal. Raises
        :class:`MixtureExhausted` when the policy says the mix is over."""
        if not self.live_names():
            raise MixtureExhausted("every corpus is exhausted")
        key = fold_in(self._epoch_key, ("draw", self._ordinal))
        name = self._pick(key)
        self._ordinal += 1
        self._draw_counts[name] += 1
        MIXTURE_DRAWS.labels(name).inc()
        return name

    def mark_exhausted(self, name):
        """The named corpus has no next batch. Applies the exhaustion
        policy; returns the corpus to RE-DRAW from for this same slot
        (``exhaust``/``reweight``), or raises :class:`MixtureExhausted`
        (``stop``, or nothing left). The re-draw derives from the
        original draw's key with a retry fold-in — deterministic, and no
        new ordinal is consumed."""
        name = str(name)
        if name not in self._names:
            raise ValueError(f"unknown corpus {name!r}")
        if name not in self._exhausted:
            self._exhausted.add(name)
            MIXTURE_EXHAUSTED.labels(name).inc()
            logger.info("mixture: corpus %r exhausted at draw %d "
                        "(policy=%s)", name, self._ordinal - 1,
                        self.exhaustion)
        if self.exhaustion == "stop":
            raise MixtureExhausted(
                f"corpus {name!r} exhausted at draw {self._ordinal - 1} "
                f"(policy 'stop' ends the mix at the first exhaustion)")
        if self.exhaustion == "reweight":
            new_weights = dict(self._weights)
            new_weights[name] = 0.0
            if any(w > 0 for w in new_weights.values()):
                self._record_weights(new_weights, why=f"exhausted:{name}")
            else:
                # The LAST live corpus drained: there is nothing left to
                # reweight toward — this is the clean end of the mix,
                # not an invalid weight map.
                raise MixtureExhausted("every corpus is exhausted")
        if not self.live_names():
            raise MixtureExhausted("every corpus is exhausted")
        # Deterministic re-roll of the SAME slot: retry indices fold into
        # the failed draw's key, so the substitution is reproducible.
        base = fold_in(self._epoch_key, ("draw", self._ordinal - 1))
        for attempt in range(1, len(self._names) + 2):
            candidate = self._pick(fold_in(base, ("retry", attempt)))
            if candidate not in self._exhausted:
                self._draw_counts[candidate] += 1
                MIXTURE_DRAWS.labels(candidate).inc()
                return candidate
        # _pick over live_names() cannot return an exhausted corpus; the
        # loop bound is sheer paranoia.
        raise MixtureExhausted("every corpus is exhausted")

    # -- weight changes ----------------------------------------------------

    def _record_weights(self, weights, why):
        self._weights = validate_weights(weights, self._names)
        self._applied.append((self._ordinal, dict(self._weights), why))
        for name in self._names:
            MIXTURE_WEIGHT.labels(name).set(self._weights[name])

    def set_weights(self, weights, why="reload"):
        """Apply a weight change at the CURRENT draw boundary (callers —
        :class:`MixedBatchSource` — invoke this only at deterministic
        boundaries; the applied-log records the exact ordinal so a
        resume replays it)."""
        self._record_weights(weights, why)
        MIXTURE_WEIGHT_RELOADS.inc()
        logger.info("mixture: weights now %s (at draw %d, %s)",
                    self._weights, self._ordinal, why)

    # -- checkpointing -----------------------------------------------------

    def state_dict(self):
        return {
            "version": 1,
            "seed": self.seed,
            "epoch": self._epoch,
            "exhaustion": self.exhaustion,
            "names": list(self._names),
            "weights": dict(self._weights),
            "ordinal": self._ordinal,
            "exhausted": sorted(self._exhausted),
            "draw_counts": dict(self._draw_counts),
            "applied": [[o, dict(w), why] for o, w, why in self._applied],
        }

    def load_state_dict(self, state):
        if state.get("version") != 1:
            raise ValueError(
                f"unsupported sampler state version "
                f"{state.get('version')!r}")
        if int(state["seed"]) != self.seed:
            raise ValueError(
                f"sampler state was saved under seed {state['seed']!r}; "
                f"this sampler runs {self.seed!r}")
        if list(state["names"]) != self._names:
            raise ValueError(
                f"sampler state names {state['names']} != {self._names} "
                f"(corpus order is part of the determinism contract)")
        self._epoch = int(state["epoch"])
        self._epoch_key = fold_in(fold_in(self.seed, ("mixture",)),
                                  ("epoch", self._epoch))
        self._weights = validate_weights(state["weights"], self._names)
        self._ordinal = int(state["ordinal"])
        self._exhausted = set(state.get("exhausted") or ())
        self._draw_counts = {n: int(state["draw_counts"].get(n, 0))
                             for n in self._names}
        self._applied = [(int(o), dict(w), why)
                         for o, w, why in state.get("applied") or ()]
        for name in self._names:
            MIXTURE_WEIGHT.labels(name).set(self._weights[name])


def set_mixture_weights(dispatcher_address, weights, job_id="default",
                        effective_epoch=None, rpc_deadline_s=30.0):
    """Journal a mixture weight change at the dispatcher — the hot-reload
    lever: every :class:`MixedBatchSource` of ``job_id`` applies it at
    the ``effective_epoch`` boundary (default: the next epoch any source
    starts after the change lands), WITHOUT restarting the fleet or the
    trainer. The change is a WAL op: a dispatcher restart replays it
    byte-identically, so the served mix remains a pure function of
    ``(seed, weight-change log)``.

    Returns the dispatcher's reply (carries the change's ``seq`` and the
    job's full weight log).
    """
    import uuid

    from petastorm_tpu.service.fleet import _job_rpc

    payload = validate_weights(weights)
    header = {"type": "set_mixture_weights", "job_id": str(job_id),
              "weights": payload,
              # Per-request idempotency id: a retry after a dropped reply
              # answers for the already-journaled entry instead of
              # appending a duplicate weight change.
              "token": uuid.uuid4().hex}
    if effective_epoch is not None:
        header["effective_epoch"] = int(effective_epoch)
    return _job_rpc(dispatcher_address, header,
                    rpc_deadline_s=rpc_deadline_s)


def get_mixture_weights(dispatcher_address, job_id="default",
                        rpc_deadline_s=30.0):
    """Fetch the job's journaled weight-change log (``entries`` +
    ``seq``)."""
    from petastorm_tpu.service.fleet import _job_rpc

    return _job_rpc(dispatcher_address,
                    {"type": "get_mixture", "job_id": str(job_id)},
                    rpc_deadline_s=rpc_deadline_s)


def _call_factory(factory, epoch):
    """Invoke a per-pass source factory with the pass index when its
    signature takes one (decided by inspection, NOT by catching
    TypeError — a genuine TypeError inside the factory must surface as
    itself, and the factory must never run twice)."""
    import inspect

    try:
        params = inspect.signature(factory).parameters
    except (TypeError, ValueError):  # builtins, C callables
        params = {}
    takes_arg = any(
        p.kind in (inspect.Parameter.POSITIONAL_ONLY,
                   inspect.Parameter.POSITIONAL_OR_KEYWORD,
                   inspect.Parameter.VAR_POSITIONAL)
        for p in params.values())
    return factory(epoch) if takes_arg else factory()


class _MixtureIterator:
    """Iterator shell carrying the batch-source ``prefetched`` marker.
    ``close()`` always runs the pass's cleanup — even when the generator
    was never started (a bare ``gen.close()`` would skip its
    ``finally``, leaking every per-corpus inner iterator)."""

    def __init__(self, gen, prefetched, cleanup):
        self._gen = gen
        self._cleanup = cleanup
        self.prefetched = prefetched

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self):
        try:
            self._gen.close()
        finally:
            self._cleanup()


class MixedBatchSource:
    """Deterministic multi-corpus batch source with hot-reloadable
    weights.

    :param sources: ordered ``{corpus_name: batch_source}`` — one source
        per corpus (typically :class:`~petastorm_tpu.service.client.
        ServiceBatchSource` instances sharing one dispatcher, each with
        ``corpus=`` naming its registered worker group). Dict order is
        the canonical corpus order. With ``factories=True`` the values
        are zero-arg (or ``pass_index``-arg) callables returning a FRESH
        source per pass — required for multi-pass mixing over service
        sources, whose ``num_epochs`` budget one pass consumes.
    :param weights: initial ``{corpus_name: weight}``.
    :param seed: REQUIRED mixture seed (independent of the dispatcher's
        shuffle seed; fold both from one run seed if you want a single
        knob).
    :param exhaustion: :data:`EXHAUSTION_POLICIES` member.
    :param dispatcher_address: arm hot reloads — each pass start fetches
        the job's journaled weight log and applies entries whose
        ``effective_epoch`` has arrived. ``None`` = static weights.
    :param job_id: the job whose weight log to follow.

    Each ``__call__`` is one mixture *pass* (epoch): every inner source
    is opened once and batches are drawn per the sampler until the
    exhaustion policy ends the pass. ``state_dict(yielded_batches=n)``
    resolves the consumer's true position to per-corpus inner positions
    plus the sampler's ordinal — resume by rebuilding the inner sources
    with their ``resume_state`` slices and passing the snapshot back as
    ``resume_state=``.
    """

    def __init__(self, sources, weights, seed, exhaustion="stop",
                 dispatcher_address=None, job_id="default",
                 resume_state=None, factories=False):
        if not sources:
            raise ValueError("a mixture needs at least one source")
        self._factories = bool(factories)
        if self._factories:
            self._source_factories = dict(sources)
            self._sources = {}
        else:
            self._source_factories = None
            self._sources = dict(sources)
        self._names = list(sources)
        self._weights = validate_weights(dict(weights), self._names)
        if seed is None:
            raise ValueError(
                "MixedBatchSource requires an explicit seed (see "
                "MixtureSampler)")
        self.seed = int(seed)
        if exhaustion not in EXHAUSTION_POLICIES:
            raise ValueError(
                f"exhaustion must be one of {EXHAUSTION_POLICIES}, got "
                f"{exhaustion!r}")
        self.exhaustion = exhaustion
        self._dispatcher_address = (tuple(dispatcher_address)
                                    if dispatcher_address else None)
        self.job_id = str(job_id)
        self._lock = threading.Lock()
        self._pass_index = 0
        self._applied_seq = 0      # highest weight-log seq applied
        self._pending_entries = []
        self._draw_log = []        # corpus name per yielded batch (pass)
        self._yielded = 0          # yields this pass
        #: Exact sampler snapshots keyed by yield count (bounded ring):
        #: a state_dict(yielded_batches=n) taken while the producer is
        #: mid-draw for batch n+1 must restore the sampler AS OF yield n
        #: — ordinal, exhaustion set, and applied weights included —
        #: never a live view racing the next draw.
        self._sampler_ring = []
        self._sampler_ring_depth = 256
        self._pass_live = set(self._names)
        self._sampler = None
        self._resume = None
        if resume_state is not None:
            if resume_state.get("kind") != "mixture_v1":
                raise ValueError(
                    f"resume_state is not a MixedBatchSource snapshot "
                    f"(kind={resume_state.get('kind')!r})")
            if list(resume_state["names"]) != self._names:
                raise ValueError(
                    f"resume_state corpora {resume_state['names']} != "
                    f"{self._names}")
            self._resume = resume_state
            self._pass_index = int(resume_state["pass"])
            self._applied_seq = int(resume_state.get("applied_seq", 0))
            # Carry the weights in force at the snapshot (reloads the
            # original run had applied are NOT pending — their seqs are
            # below applied_seq — so they must ride the snapshot).
            if resume_state.get("weights"):
                self._weights = validate_weights(
                    dict(resume_state["weights"]), self._names)

    # -- hot reload --------------------------------------------------------

    def refresh_weights(self):
        """Fetch the dispatcher's journaled weight log; stage unapplied
        entries. Called automatically at each pass start; harmless to
        call any time (entries only ever APPLY at pass boundaries, so
        the stream stays a pure function of the log)."""
        if self._dispatcher_address is None:
            return
        reply = get_mixture_weights(self._dispatcher_address, self.job_id)
        with self._lock:
            for entry in reply.get("entries", ()):
                if int(entry["seq"]) > self._applied_seq and not any(
                        int(entry["seq"]) == int(e["seq"])
                        for e in self._pending_entries):
                    self._pending_entries.append(dict(entry))
            self._pending_entries.sort(key=lambda e: int(e["seq"]))

    def _apply_due_entries(self, sampler):
        """Apply staged entries whose effective epoch has arrived — the
        deterministic boundary: entry N applies at the START of pass
        ``effective_epoch`` (or the first pass to start after it
        landed), before any draw of that pass.

        A malformed journaled entry (an operator typo naming an unknown
        corpus — the dispatcher cannot validate names, it has no corpus
        list for the job) must never wedge training: unknown corpora are
        dropped with a loud warning, and an entry with nothing usable
        left is skipped — its seq still advances so a later corrected
        entry is reachable."""
        with self._lock:
            due = [e for e in self._pending_entries
                   if int(e.get("effective_epoch", -1)) <= self._pass_index]
            self._pending_entries = [
                e for e in self._pending_entries if e not in due]
        for entry in due:
            seq = int(entry["seq"])
            self._applied_seq = max(self._applied_seq, seq)
            raw = dict(entry["weights"])
            unknown = sorted(set(raw) - set(self._names))
            if unknown:
                logger.warning(
                    "mixture: weight-log entry seq=%d names unknown "
                    "corpora %s (mixture has %s) — dropping them; fix "
                    "with a corrected set_mixture_weights", seq, unknown,
                    self._names)
                raw = {k: v for k, v in raw.items() if k in self._names}
            merged = dict(sampler.weights())
            merged.update(raw)
            try:
                validate_weights(merged, self._names)
            except ValueError as exc:
                logger.warning(
                    "mixture: skipping unusable weight-log entry seq=%d "
                    "(%s) — weights unchanged", seq, exc)
                continue
            sampler.set_weights(merged, why=f"reload:seq={seq}")
            self._weights = dict(merged)

    # -- the batch_source contract ----------------------------------------

    def __call__(self):
        self.refresh_weights()
        epoch = self._pass_index
        sampler = MixtureSampler(self.seed, dict(self._weights),
                                 epoch=epoch, exhaustion=self.exhaustion)
        resume, self._resume = self._resume, None
        if resume is not None and resume.get("sampler") is not None:
            sampler.load_state_dict(resume["sampler"])
        self._sampler = sampler
        if resume is None:
            # Pass-START boundary: apply due weight entries. A mid-pass
            # RESUME must not — the restored sampler already carries
            # everything the uninterrupted run had applied at this
            # pass's start, and applying a newly-staged entry here would
            # change the remaining draws of a pass the uninterrupted run
            # finishes under the old weights (the resumed stream must
            # stay byte-identical to it). Staged entries apply at the
            # next pass boundary, exactly like the uninterrupted run.
            self._apply_due_entries(sampler)
        with self._lock:
            self._sampler_ring = [(0, sampler.state_dict())]
        # Corpora with no live weight (reloaded to 0, reweight-policy
        # drop-outs, already exhausted) can never be drawn this pass:
        # skip opening their sources entirely — each one is a fleet's
        # worth of streams, heartbeats, and reader construction.
        live = set(sampler.live_names())
        skipped = [n for n in self._names if n not in live]
        if skipped:
            logger.info("mixture: not opening zero-weight/exhausted "
                        "corpora %s this pass", sorted(skipped))
        if self._factories:
            built = {}
            for name in self._names:
                if name not in live:
                    continue
                built[name] = _call_factory(
                    self._source_factories[name], epoch)
            self._sources = built
        self._pass_live = live
        iters = {name: iter(self._sources[name]())
                 for name in self._names if name in live}
        prefetched = all(bool(getattr(it, "prefetched", False))
                         for it in iters.values())
        self._draw_log = []
        self._yielded = 0
        _note_pass(1)
        done = [False]

        def cleanup():
            if done[0]:
                return
            done[0] = True
            _note_pass(-1)
            self._pass_index += 1
            for it in iters.values():
                close = getattr(it, "close", None)
                if callable(close):
                    close()

        return _MixtureIterator(self._mix(sampler, iters, cleanup),
                                prefetched, cleanup)

    def _mix(self, sampler, iters, cleanup):
        try:
            while True:
                try:
                    name = sampler.draw()
                except MixtureExhausted:
                    return
                while True:
                    try:
                        batch = next(iters[name])
                        break
                    except StopIteration:
                        try:
                            name = sampler.mark_exhausted(name)
                        except MixtureExhausted:
                            return
                with self._lock:
                    self._draw_log.append(name)
                    self._yielded += 1
                    self._sampler_ring.append(
                        (self._yielded, sampler.state_dict()))
                    while len(self._sampler_ring) > \
                            self._sampler_ring_depth:
                        self._sampler_ring.pop(0)
                yield batch
        finally:
            cleanup()

    # -- checkpointing -----------------------------------------------------

    def state_dict(self, yielded_batches=None):
        """Resumable position at the consumer's true batch count: the
        sampler snapshot plus each inner source's ``state_dict`` taken
        at that corpus's batch count among the first ``n`` yields."""
        sampler = self._sampler
        if sampler is None:
            raise ValueError(
                "state_dict before the first iteration has no position — "
                "start iterating first")
        with self._lock:
            n = (self._yielded if yielded_batches is None
                 else min(int(yielded_batches), self._yielded))
            log = list(self._draw_log[:n])
            # The exact sampler snapshot AS OF yield n (captured
            # atomically with the yield): a live sampler view could be
            # mid-draw for n+1, or carry an exhaustion/reweight event
            # the consumer has not reached.
            sampler_state = None
            for count, snap in self._sampler_ring:
                if count == n:
                    sampler_state = dict(snap)
                    break
            if sampler_state is None:
                raise ValueError(
                    f"no sampler snapshot at yield {n} (the ring keeps "
                    f"{self._sampler_ring_depth}; the consumer's "
                    f"prefetch lag exceeded it)")
        per_corpus = {name: 0 for name in self._names}
        for name in log:
            per_corpus[name] += 1
        inner = {}
        for name, source in self._sources.items():
            if name not in self._pass_live:
                # Never opened this pass (zero weight / exhausted): no
                # position to record — a resume rebuilds it fresh if a
                # reload revives it.
                continue
            state_fn = getattr(source, "state_dict", None)
            if callable(state_fn):
                try:
                    inner[name] = state_fn(
                        yielded_batches=per_corpus[name])
                except TypeError:
                    inner[name] = state_fn()
        return {
            "kind": "mixture_v1",
            "pass": self._pass_index,
            "names": list(self._names),
            "weights": dict(self._weights),
            "applied_seq": self._applied_seq,
            "sampler": sampler_state,
            "per_corpus_batches": per_corpus,
            "inner": inner,
        }

    @property
    def diagnostics(self):
        with self._lock:
            counts = {}
            for name in self._draw_log:
                counts[name] = counts.get(name, 0) + 1
        out = {"mixture": {"weights": dict(self._weights),
                           "pass": self._pass_index,
                           "applied_seq": self._applied_seq,
                           "draws": counts}}
        for name, source in self._sources.items():
            diag = getattr(source, "diagnostics", None)
            if isinstance(diag, dict):
                out.setdefault("per_corpus", {})[name] = diag
        return out
