"""Column codecs: tensor <-> Parquet-cell encodings.

Reference parity: ``petastorm/codecs.py`` (``DataframeColumnCodec``,
``ScalarCodec``, ``NdarrayCodec``, ``CompressedNdarrayCodec``,
``CompressedImageCodec``) — see SURVEY.md §2.1. Byte formats are kept
compatible with the reference (``np.save`` payloads, cv2-encoded png/jpeg)
so datasets written by the reference load unchanged.

Design difference from the reference: codecs here report an *arrow* storage
type (``arrow_dtype``) instead of a Spark SQL type, because the ETL engine is
``pyarrow.dataset``, not Spark. A ``spark_dtype`` shim is provided for API
parity when pyspark is importable.
"""

from __future__ import annotations

import io
from abc import ABC, abstractmethod
from decimal import Decimal

import numpy as np
import pyarrow as pa

try:  # pragma: no cover - exercised only where cv2 is absent
    import cv2

    _HAVE_CV2 = True
except ImportError:  # pragma: no cover
    cv2 = None
    _HAVE_CV2 = False


def numpy_to_arrow_type(numpy_dtype):
    """Map a field's numpy dtype (or Decimal / str / bytes class) to an arrow type."""
    if numpy_dtype is Decimal:
        # We store decimals as strings (lossless, portable); reference datasets
        # written via Spark DecimalType read back as arrow decimal128 and are
        # handled on the decode side.
        return pa.string()
    if numpy_dtype in (str, np.str_, np.unicode_ if hasattr(np, "unicode_") else np.str_):
        return pa.string()
    if numpy_dtype in (bytes, np.bytes_):
        return pa.binary()
    dtype = np.dtype(numpy_dtype)
    if dtype.kind in ("U", "S"):
        return pa.string() if dtype.kind == "U" else pa.binary()
    if dtype.kind == "M":  # datetime64
        unit = np.datetime_data(dtype)[0]
        if unit == "D":
            return pa.date32()
        return pa.timestamp(unit if unit in ("s", "ms", "us", "ns") else "us")
    return pa.from_numpy_dtype(dtype)


class DataframeColumnCodec(ABC):
    """Abstract codec: how one Unischema field is stored in a Parquet cell."""

    @abstractmethod
    def encode(self, unischema_field, value):
        """Encode ``value`` into the storage representation (scalar or bytes)."""

    @abstractmethod
    def decode(self, unischema_field, value):
        """Decode a storage cell back into the field's numpy representation."""

    def decode_column(self, unischema_field, cells):
        """Decode a whole column of storage cells into one ``[N, *shape]``
        array (the TPU-native columnar read path — no per-row objects).

        ``cells``: a sequence (typically a numpy object array) of raw storage
        cells. Default implementation loops :meth:`decode` and stacks;
        subclasses override with vectorized paths. Returns an object array
        when cells are ragged or null."""
        decoded = [self.decode(unischema_field, cell) for cell in cells]
        return _stack_decoded(decoded)

    @abstractmethod
    def arrow_dtype(self):
        """The ``pyarrow.DataType`` of the stored column."""

    def spark_dtype(self):  # pragma: no cover - only with pyspark installed
        """API-parity shim (reference codecs report Spark SQL types)."""
        raise NotImplementedError(
            "spark_dtype requires pyspark; this build's ETL engine is pyarrow"
        )

    def __eq__(self, other):
        return isinstance(other, self.__class__) and self.__dict__ == other.__dict__

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash((self.__class__.__name__, tuple(sorted(self.__dict__.items(), key=str))))


class ScalarCodec(DataframeColumnCodec):
    """Stores a scalar natively in its Parquet column.

    Reference parity: ``petastorm/codecs.py::ScalarCodec(spark_type)``. Here the
    constructor takes an arrow type, a numpy dtype, or ``str``/``bytes``/
    ``Decimal`` — whatever identifies the storage type.
    """

    def __init__(self, arrow_type_or_dtype=None):
        if arrow_type_or_dtype is None:
            self._arrow_type = None  # derived from the field at encode time
        elif isinstance(arrow_type_or_dtype, pa.DataType):
            self._arrow_type = arrow_type_or_dtype
        else:
            self._arrow_type = numpy_to_arrow_type(arrow_type_or_dtype)

    def arrow_dtype(self):
        return self._arrow_type

    def arrow_dtype_for_field(self, unischema_field):
        if self._arrow_type is not None:
            return self._arrow_type
        return numpy_to_arrow_type(unischema_field.numpy_dtype)

    def encode(self, unischema_field, value):
        if unischema_field.shape:
            raise ValueError(
                f"ScalarCodec can only encode scalars; field {unischema_field.name!r} "
                f"has shape {unischema_field.shape}"
            )
        if value is None:
            return None
        dtype = unischema_field.numpy_dtype
        if dtype is Decimal:
            return str(value if isinstance(value, Decimal) else Decimal(str(value)))
        if dtype in (str, np.str_):
            return str(value)
        if dtype in (bytes, np.bytes_):
            return bytes(value)
        if np.dtype(dtype).kind == "M":
            return value
        # np scalar or python scalar -> python native for arrow
        return np.dtype(dtype).type(value).item()

    def decode(self, unischema_field, value):
        if value is None:
            return None
        dtype = unischema_field.numpy_dtype
        if dtype is Decimal:
            if isinstance(value, Decimal):
                return value
            if isinstance(value, bytes):
                value = value.decode("utf-8")
            return Decimal(value)
        if dtype in (str, np.str_):
            return value.decode("utf-8") if isinstance(value, bytes) else str(value)
        if dtype in (bytes, np.bytes_):
            return value
        if np.dtype(dtype).kind == "M":
            # Cast to the field's declared unit; np.datetime64(value) alone
            # would infer a unit from the input and break dtype normalization.
            return np.datetime64(value).astype(np.dtype(dtype))
        return np.dtype(dtype).type(value)

    def decode_column(self, unischema_field, cells):
        """Vectorized decode: numeric/datetime columns are a single astype of
        the arrow-materialized array; strings/Decimals/nullables loop."""
        dtype = unischema_field.numpy_dtype
        if dtype is Decimal or dtype in (str, np.str_, bytes, np.bytes_):
            return super().decode_column(unischema_field, cells)
        arr = np.asarray(cells)
        if arr.dtype == object:  # nulls (or mixed types) present
            return super().decode_column(unischema_field, cells)
        target = np.dtype(dtype)
        if target.kind in "iub" and arr.dtype.kind == "f":
            # Arrow materializes int-with-nulls as float64 NaN; astype would
            # silently turn NaN into garbage ints. Match the row path: None
            # for null cells, via an object array.
            nan_mask = np.isnan(arr)
            if nan_mask.any():
                out = np.empty(len(arr), dtype=object)
                for i, (v, is_nan) in enumerate(zip(arr, nan_mask)):
                    out[i] = None if is_nan else target.type(v)
                return out
        return arr.astype(target, copy=False)


class NdarrayCodec(DataframeColumnCodec):
    """Stores an ndarray as ``np.save`` bytes in a binary column.

    Byte-compatible with the reference's ``petastorm/codecs.py::NdarrayCodec``.
    """

    def arrow_dtype(self):
        return pa.binary()

    def encode(self, unischema_field, value):
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected:
            raise ValueError(
                f"Field {unischema_field.name!r}: expected dtype {expected}, got {value.dtype}"
            )
        _check_shape_compatible(unischema_field, value)
        memfile = io.BytesIO()
        np.save(memfile, value)
        return memfile.getvalue()

    def decode(self, unischema_field, value):
        if value is None:
            return None
        return _fast_npy_load(value)

    def decode_column(self, unischema_field, cells):
        """Vectorized decode: parse each npy header once (cached) and
        ``frombuffer`` straight into a preallocated ``[N, *shape]`` array.
        Falls back to the generic loop for nulls, ragged shapes, or exotic
        payloads."""
        n = len(cells)
        out = None
        for i, cell in enumerate(cells):
            parsed = _fast_npy_parse(cell) if isinstance(cell, bytes) else None
            if parsed is None:
                return super().decode_column(unischema_field, cells)
            dtype, fortran, shape, offset = parsed
            if out is None:
                if dtype.hasobject:
                    return super().decode_column(unischema_field, cells)
                out = np.empty((n,) + shape, dtype=dtype)
                out_shape, out_dtype = shape, dtype
            elif shape != out_shape or dtype != out_dtype:
                return super().decode_column(unischema_field, cells)
            data = np.frombuffer(cell, dtype=dtype, offset=offset,
                                 count=int(np.prod(shape)) if shape else 1)
            out[i] = data.reshape(shape, order="F" if fortran else "C")
        return out if out is not None else np.empty((0,), dtype=object)


# npy headers are identical for every cell of a fixed-shape field, but
# ``np.load`` re-parses the header dict with ast.literal_eval per cell —
# measured as the single hottest line of the whole decode path (hotter than
# PNG decode). Cache parsed headers keyed by their raw bytes.
_NPY_HEADER_CACHE = {}
_NPY_MAGIC = b"\x93NUMPY"


def _fast_npy_parse(value):
    """Parse ``np.save`` bytes → ``(dtype, fortran, shape, data_offset)``,
    with the header-dict parse cached. None when not a plain npy payload."""
    if not isinstance(value, bytes) or not value.startswith(_NPY_MAGIC):
        return None
    major = value[6]
    if major == 1:
        hlen, offset = int.from_bytes(value[8:10], "little"), 10
    elif major in (2, 3):
        hlen, offset = int.from_bytes(value[8:12], "little"), 12
    else:  # unknown future version — let numpy handle it
        return None
    header = value[offset:offset + hlen]
    parsed = _NPY_HEADER_CACHE.get(header)
    if parsed is None:
        import ast

        spec = ast.literal_eval(header.decode("latin1"))
        parsed = (np.dtype(spec["descr"]), bool(spec["fortran_order"]),
                  tuple(spec["shape"]))
        if len(_NPY_HEADER_CACHE) < 4096:
            _NPY_HEADER_CACHE[header] = parsed
    dtype, fortran, shape = parsed
    return dtype, fortran, shape, offset + hlen


def _fast_npy_load(value):
    """Decode ``np.save`` bytes with a cached header parse + frombuffer."""
    parsed = _fast_npy_parse(value)
    if parsed is None:
        return np.load(io.BytesIO(value), allow_pickle=False)
    dtype, fortran, shape, offset = parsed
    if dtype.hasobject:  # would need pickle — defer to numpy (which refuses)
        return np.load(io.BytesIO(value), allow_pickle=False)
    data = np.frombuffer(value, dtype=dtype, offset=offset,
                         count=int(np.prod(shape)) if shape else 1)
    arr = data.reshape(shape, order="F" if fortran else "C")
    # frombuffer views are read-only (backed by the bytes object); consumers
    # (transforms, torch) may mutate — hand out a writable copy (memcpy is
    # ~free next to the header parse we just skipped).
    return arr.copy()


class CompressedNdarrayCodec(DataframeColumnCodec):
    """Stores an ndarray as ``np.savez_compressed`` bytes (zlib-compressed).

    Byte-compatible with the reference's ``CompressedNdarrayCodec`` (array is
    stored under the archive key ``arr``).
    """

    def arrow_dtype(self):
        return pa.binary()

    def encode(self, unischema_field, value):
        expected = np.dtype(unischema_field.numpy_dtype)
        if value.dtype != expected:
            raise ValueError(
                f"Field {unischema_field.name!r}: expected dtype {expected}, got {value.dtype}"
            )
        _check_shape_compatible(unischema_field, value)
        memfile = io.BytesIO()
        np.savez_compressed(memfile, arr=value)
        return memfile.getvalue()

    def decode(self, unischema_field, value):
        if value is None:
            return None
        memfile = io.BytesIO(value)
        with np.load(memfile, allow_pickle=False) as archive:
            keys = archive.files
            return archive["arr" if "arr" in keys else keys[0]]


class CompressedImageCodec(DataframeColumnCodec):
    """Stores an image ndarray as png/jpeg bytes via cv2 (Pillow fallback).

    Byte-compatible with the reference's ``CompressedImageCodec``: channel
    order is whatever the user stored (cv2 convention is BGR, but the codec is
    agnostic); decode uses IMREAD_UNCHANGED so uint16 png and alpha survive.
    """

    def __init__(self, image_codec="png", quality=80):
        if image_codec not in ("png", "jpeg", "jpg"):
            raise ValueError(f"Unsupported image codec: {image_codec!r}")
        self._image_codec = "jpeg" if image_codec == "jpg" else image_codec
        self._quality = quality

    @property
    def image_codec(self):
        return self._image_codec

    def arrow_dtype(self):
        return pa.binary()

    def encode(self, unischema_field, value):
        if not isinstance(value, np.ndarray):
            raise ValueError(
                f"Field {unischema_field.name!r}: CompressedImageCodec expects ndarray"
            )
        if value.dtype != np.dtype(unischema_field.numpy_dtype):
            raise ValueError(
                f"Field {unischema_field.name!r}: expected dtype "
                f"{np.dtype(unischema_field.numpy_dtype)}, got {value.dtype}"
            )
        _check_shape_compatible(unischema_field, value)
        if _HAVE_CV2:
            if self._image_codec == "png":
                ok, contents = cv2.imencode(".png", value)
            else:
                ok, contents = cv2.imencode(
                    ".jpeg", value, [int(cv2.IMWRITE_JPEG_QUALITY), self._quality]
                )
            if not ok:
                raise ValueError(f"cv2.imencode failed for field {unischema_field.name!r}")
            return contents.tobytes()
        return self._pil_encode(value)

    def decode(self, unischema_field, value):
        if value is None:
            return None
        if _HAVE_CV2:
            return cv2.imdecode(
                np.frombuffer(value, dtype=np.uint8), cv2.IMREAD_UNCHANGED
            )
        return self._pil_decode(value)

    def decode_column(self, unischema_field, cells):
        """Vectorized decode: imdecode each cell straight into a preallocated
        ``[N, H, W, C]`` array (no per-row python objects). Falls back to the
        generic loop for nulls or ragged image shapes."""
        if not _HAVE_CV2:
            return super().decode_column(unischema_field, cells)
        n = len(cells)
        out = None
        for i, cell in enumerate(cells):
            if cell is None:
                return super().decode_column(unischema_field, cells)
            img = cv2.imdecode(np.frombuffer(cell, dtype=np.uint8),
                               cv2.IMREAD_UNCHANGED)
            if img is None:  # corrupt/undecodable bytes — match row path
                return super().decode_column(unischema_field, cells)
            if out is None:
                out = np.empty((n,) + img.shape, dtype=img.dtype)
            elif img.shape != out.shape[1:] or img.dtype != out.dtype:
                return super().decode_column(unischema_field, cells)
            out[i] = img
        return out if out is not None else np.empty((0,), dtype=object)

    def _pil_encode(self, value):  # pragma: no cover - cv2 present in this env
        from PIL import Image

        memfile = io.BytesIO()
        img = value
        if img.ndim == 3 and img.shape[2] == 3:
            img = img[:, :, ::-1]  # PIL is RGB; preserve stored-BGR convention
        Image.fromarray(img).save(
            memfile, format="PNG" if self._image_codec == "png" else "JPEG",
            quality=self._quality,
        )
        return memfile.getvalue()

    def _pil_decode(self, value):  # pragma: no cover - cv2 present in this env
        from PIL import Image

        arr = np.asarray(Image.open(io.BytesIO(value)))
        if arr.ndim == 3 and arr.shape[2] == 3:
            arr = arr[:, :, ::-1]
        return arr


def _stack_decoded(decoded):
    """Stack per-cell decoded values into ``[N, ...]``; object array when
    ragged or containing None (nullable fields)."""
    if not decoded:
        return np.empty((0,), dtype=object)
    first = decoded[0]
    if isinstance(first, np.ndarray) and first.dtype != object and \
            all(isinstance(v, np.ndarray) and v.shape == first.shape
                and v.dtype == first.dtype for v in decoded):
        return np.stack(decoded)
    if isinstance(first, (int, float, bool, np.generic)) and \
            all(v is not None for v in decoded):
        return np.asarray(decoded)
    out = np.empty(len(decoded), dtype=object)
    for i, v in enumerate(decoded):
        out[i] = v
    return out


def _check_shape_compatible(unischema_field, value):
    shape = unischema_field.shape
    if shape is None:
        return
    if len(shape) != value.ndim:
        raise ValueError(
            f"Field {unischema_field.name!r}: expected rank {len(shape)}, "
            f"got rank {value.ndim}"
        )
    for expected_dim, actual_dim in zip(shape, value.shape):
        if expected_dim is not None and expected_dim != actual_dim:
            raise ValueError(
                f"Field {unischema_field.name!r}: expected shape {shape}, "
                f"got {value.shape}"
            )
