"""Small pure-JAX CNN image classifier, SPMD-sharded (data + tensor parallel).

The flagship consumer of the data path (``__graft_entry__.py``, MNIST/ImageNet
examples). TPU-first choices:

- compute in **bfloat16** (params kept f32, cast per-step): matmuls/convs land
  on the MXU at full rate; the loss is accumulated in f32;
- **static shapes** only, no Python control flow in the step — one trace, one
  XLA program;
- sharding is expressed as ``PartitionSpec`` s over a ``("data", "model")``
  mesh: batch over ``data``, the hidden dense layer split over ``model``
  (column-parallel first matmul, row-parallel second — XLA inserts the
  all-reduce over ICI, the classic Megatron-style TP pattern done the JAX
  way via sharding annotations rather than explicit collectives).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_params(rng, image_shape, num_classes, hidden=256, conv_features=32,
                dtype=jnp.float32):
    """Initialize the parameter pytree.

    :param image_shape: (H, W, C) of one example.
    :param hidden: hidden width — the tensor-parallel (``"model"``-sharded)
        dimension; keep it a multiple of the mesh's model-axis size.
    """
    h, w, c = image_shape
    k_conv, k_w1, k_w2 = jax.random.split(rng, 3)
    flat = (h // 2) * (w // 2) * conv_features
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)  # noqa: E731
    return {
        "conv": {
            "kernel": (jax.random.normal(k_conv, (3, 3, c, conv_features),
                                         dtype) * scale(9 * c)),
            "bias": jnp.zeros((conv_features,), dtype),
        },
        "dense1": {
            "kernel": (jax.random.normal(k_w1, (flat, hidden), dtype)
                       * scale(flat)),
            "bias": jnp.zeros((hidden,), dtype),
        },
        "dense2": {
            "kernel": (jax.random.normal(k_w2, (hidden, num_classes), dtype)
                       * scale(hidden)),
            "bias": jnp.zeros((num_classes,), dtype),
        },
    }


def param_partition_specs():
    """PartitionSpecs for a ``("data", "model")`` mesh.

    Conv is small → replicated. dense1 is column-parallel (output dim over
    ``model``), dense2 row-parallel (input dim over ``model``) so only one
    all-reduce per forward pass materializes the logits.
    """
    return {
        "conv": {"kernel": P(), "bias": P()},
        "dense1": {"kernel": P(None, "model"), "bias": P("model")},
        "dense2": {"kernel": P("model", None), "bias": P()},
    }


def apply_model(params, images, compute_dtype=jnp.bfloat16):
    """Forward pass: conv3x3 → relu → 2x2 mean-pool → dense → relu → dense.

    ``images``: [B, H, W, C] float. Returns f32 logits [B, num_classes].
    """
    x = images.astype(compute_dtype)
    conv = params["conv"]
    x = jax.lax.conv_general_dilated(
        x, conv["kernel"].astype(compute_dtype),
        window_strides=(1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    x = jax.nn.relu(x + conv["bias"].astype(compute_dtype))
    b, hh, ww, f = x.shape
    x = x.reshape(b, hh // 2, 2, ww // 2, 2, f).mean(axis=(2, 4))  # 2x2 pool
    x = x.reshape(x.shape[0], -1)
    d1 = params["dense1"]
    x = jax.nn.relu(x @ d1["kernel"].astype(compute_dtype)
                    + d1["bias"].astype(compute_dtype))
    d2 = params["dense2"]
    logits = x @ d2["kernel"].astype(compute_dtype) \
        + d2["bias"].astype(compute_dtype)
    return logits.astype(jnp.float32)


def make_train_step(learning_rate=0.01):
    """Return ``step(params, images, labels, mask) -> (params, loss)``.

    Pure SGD, masked cross-entropy (the mask is the loader's ``__pad_mask__``
    column — padded rows contribute zero loss, which is what makes the
    wrap-pad equal-step policy numerically safe).
    """
    def loss_fn(params, images, labels, mask):
        logits = apply_model(params, images)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        nll = jnp.where(mask, nll, 0.0)
        return nll.sum() / jnp.maximum(mask.sum(), 1).astype(jnp.float32)

    def step(params, images, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, images, labels, mask)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype),
            params, grads)
        return new_params, loss

    return step
