"""NGram window training — BASELINE.md config #4 end-to-end.

Timestamped frames (video/lidar stand-in) → ``NGram`` windows through
``make_reader`` → ``make_jax_dataloader`` collates to ``[B, T, ...]`` →
the sequence encoder trains on them (dense or Pallas-flash attention on one
device; pass a mesh for ring/Ulysses sequence parallelism).

Run: ``python -m examples.sequence.train_sequence``.
"""

from __future__ import annotations

import numpy as np

WINDOW = 5


def generate_frames_dataset(dataset_url, frames=1024):
    """Write the timestamped-frame dataset (NdarrayCodec frames)."""
    from petastorm_tpu.benchmark.scenarios import make_ngram_dataset

    return make_ngram_dataset(dataset_url, frames=frames,
                              frame_shape=(8, 8, 1))


def train_sequence(dataset_url, batch_size=16, steps=8, attn_impl="dense"):
    """Train the encoder on NGram windows; returns the final loss."""
    import jax
    import jax.numpy as jnp

    from petastorm_tpu import make_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader
    from petastorm_tpu.models.sequence_model import (init_seq_params,
                                                     make_seq_train_step)
    from petastorm_tpu.ngram import NGram

    ngram = NGram({i: ["ts", "frame", "ego_speed"] for i in range(WINDOW)},
                  delta_threshold=1, timestamp_field="ts")
    reader = make_reader(dataset_url, schema_fields=ngram, num_epochs=None,
                         shuffle_row_groups=True, shard_seed=0)

    feature_dim = 8 * 8 * 1 + 1  # flattened frame + ego_speed per timestep
    params = init_seq_params(jax.random.PRNGKey(0), feature_dim=feature_dim,
                             d_model=32, num_heads=4, num_classes=4)
    step = jax.jit(make_seq_train_step(0.05, num_heads=4,
                                       attn_impl=attn_impl))

    loss = float("nan")
    with make_jax_dataloader(reader, batch_size, max_batches=steps,
                             stage_to_device=False) as loader:
        for batch in loader:
            # [B, T, 8, 8, 1] frames + [B, T] speed -> [B, T, F] features
            frames = jnp.asarray(batch["frame"])
            speed = jnp.asarray(batch["ego_speed"])
            b, t = frames.shape[:2]
            windows = jnp.concatenate(
                [frames.reshape(b, t, -1), speed[..., None]], axis=-1)
            # Synthetic label: the window's mean speed quartile.
            labels = jnp.clip((speed.mean(axis=1) * 4).astype(jnp.int32),
                              0, 3)
            mask = jnp.ones(b, bool)
            params, loss = step(params, windows, labels, mask)
    return float(loss)


def generate_ragged_dataset(dataset_url, rows=256, max_len=24):
    """Variable-length sequences stored PADDED with a ``length`` column —
    the standard ragged-sequence layout (shapes in Parquet must be static;
    the true length rides along as data)."""
    from petastorm_tpu.etl.metadata import materialize_rows
    from petastorm_tpu.schema.codecs import NdarrayCodec, ScalarCodec
    from petastorm_tpu.schema.unischema import Unischema, UnischemaField

    schema = Unischema("RaggedSeq", [
        UnischemaField("id", np.int64, (), ScalarCodec(), False),
        UnischemaField("seq", np.float32, (max_len, 6), NdarrayCodec(),
                       False),
        UnischemaField("length", np.int32, (), ScalarCodec(), False),
        UnischemaField("label", np.int32, (), ScalarCodec(), False),
    ])
    rng = np.random.RandomState(7)

    def rows_gen():
        for i in range(rows):
            n = int(rng.randint(4, max_len + 1))
            seq = np.zeros((max_len, 6), np.float32)
            seq[:n] = rng.randn(n, 6)
            yield {"id": i, "seq": seq, "length": np.int32(n),
                   "label": np.int32(i % 3)}

    materialize_rows(dataset_url, schema, rows_gen(), rows_per_row_group=64)
    return dataset_url


def train_ragged_causal(dataset_url, batch_size=16, steps=8, mesh=None,
                        attn_impl=None):
    """Decoder-style (causal) training on ragged sequences: the ``length``
    column flows into the model so padded positions neither attend nor pool.
    ``attn_impl`` defaults to the Pallas flash kernel single-device and to
    the K/V-ppermute ring when a ``mesh`` is given (sequence parallelism
    over long windows)."""
    if attn_impl is None:
        attn_impl = "ring" if mesh is not None else "flash"
    import jax
    import jax.numpy as jnp

    from petastorm_tpu import make_columnar_reader
    from petastorm_tpu.jax_utils import make_jax_dataloader
    from petastorm_tpu.models.sequence_model import (init_seq_params,
                                                     make_seq_train_step)

    reader = make_columnar_reader(dataset_url, num_epochs=None,
                                  shuffle_row_groups=True,
                                  schema_fields=["seq", "length", "label"])
    params = init_seq_params(jax.random.PRNGKey(1), feature_dim=6,
                             d_model=32, num_heads=4, num_classes=3)
    step = jax.jit(make_seq_train_step(0.05, num_heads=4, mesh=mesh,
                                       attn_impl=attn_impl, causal=True))
    loss = float("nan")
    with make_jax_dataloader(reader, batch_size, max_batches=steps,
                             stage_to_device=False) as loader:
        for batch in loader:
            windows = jnp.asarray(batch["seq"])
            lengths = jnp.asarray(batch["length"])
            labels = jnp.asarray(batch["label"]).astype(jnp.int32)
            mask = jnp.ones(windows.shape[0], bool)
            params, loss = step(params, windows, labels, mask, lengths)
    return float(loss)


def main(dataset_url=None, frames=1024):
    import shutil
    import tempfile

    tmpdir = None
    if dataset_url is None:
        tmpdir = tempfile.mkdtemp(prefix="sequence_example_")
        dataset_url = f"file://{tmpdir}/frames"
        generate_frames_dataset(dataset_url, frames=frames)
    try:
        loss = train_sequence(dataset_url)
        print(f"trained {WINDOW}-frame windows, final loss={loss:.4f}")
        # The ragged demo writes its own dataset — always under a tmpdir,
        # never beside a caller-supplied URL (which may be read-only).
        with tempfile.TemporaryDirectory(
                prefix="sequence_example_ragged_") as ragged_dir:
            ragged_url = f"file://{ragged_dir}/ragged"
            generate_ragged_dataset(ragged_url)
            ragged_loss = train_ragged_causal(ragged_url)
        print(f"trained ragged causal sequences, final loss={ragged_loss:.4f}")
        return loss
    finally:
        if tmpdir:
            shutil.rmtree(tmpdir, ignore_errors=True)


if __name__ == "__main__":
    main()
