"""NGram property tests: delta_threshold gaps, overlap control, boundaries.

Mirrors the reference's ngram end-to-end tests (SURVEY.md §4, §7 hard-part #3).
"""

import numpy as np
import pytest

from petastorm_tpu.ngram import NGram
from petastorm_tpu.schema.codecs import ScalarCodec
from petastorm_tpu.schema.unischema import Unischema, UnischemaField

SCHEMA = Unischema("Seq", [
    UnischemaField("ts", np.int64, (), ScalarCodec(), False),
    UnischemaField("value", np.float64, (), ScalarCodec(), False),
    UnischemaField("aux", str, (), ScalarCodec(), True),
])


def _rows(timestamps):
    return [{"ts": t, "value": float(t) * 2, "aux": f"a{t}"} for t in timestamps]


def test_basic_windows():
    ngram = NGram({0: ["ts", "value"], 1: ["ts"]}, delta_threshold=1,
                  timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    windows = ngram.form_ngram(_rows([1, 2, 3, 4]), SCHEMA)
    assert len(windows) == 3
    assert [w[0]["ts"] for w in windows] == [1, 2, 3]
    assert all("value" in w[0] and "value" not in w[1] for w in windows)


def test_delta_threshold_rejects_gaps():
    ngram = NGram({0: ["ts"], 1: ["ts"]}, delta_threshold=1, timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    # gap between 3 and 10 kills windows spanning it
    windows = ngram.form_ngram(_rows([1, 2, 3, 10, 11]), SCHEMA)
    starts = [w[0]["ts"] for w in windows]
    assert starts == [1, 2, 10]


def test_delta_threshold_none_accepts_all():
    ngram = NGram({0: ["ts"], 1: ["ts"]}, delta_threshold=None,
                  timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    windows = ngram.form_ngram(_rows([1, 100, 5000]), SCHEMA)
    assert len(windows) == 2


def test_rows_sorted_before_windowing():
    ngram = NGram({0: ["ts"], 1: ["ts"]}, delta_threshold=1, timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    windows = ngram.form_ngram(_rows([3, 1, 2]), SCHEMA)
    assert [w[0]["ts"] for w in windows] == [1, 2]


def test_timestamp_overlap_false_strides_by_length():
    ngram = NGram({0: ["ts"], 1: ["ts"]}, delta_threshold=1,
                  timestamp_field="ts", timestamp_overlap=False)
    ngram.resolve_regex_field_names(SCHEMA)
    windows = ngram.form_ngram(_rows([1, 2, 3, 4, 5, 6]), SCHEMA)
    assert [w[0]["ts"] for w in windows] == [1, 3, 5]


def test_negative_and_sparse_offsets():
    ngram = NGram({-1: ["value"], 1: ["value"]}, delta_threshold=2,
                  timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    assert ngram.length == 3
    windows = ngram.form_ngram(_rows([10, 11, 12, 13]), SCHEMA)
    assert len(windows) == 2
    assert set(windows[0].keys()) == {-1, 1}


def test_regex_field_resolution():
    ngram = NGram({0: ["val.*", "ts"]}, delta_threshold=None, timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    assert set(ngram.get_field_names_at_timestep(0)) == {"value", "ts"}
    with pytest.raises(ValueError, match="matched nothing"):
        bad = NGram({0: ["nope.*"]}, delta_threshold=None, timestamp_field="ts")
        bad.resolve_regex_field_names(SCHEMA)


def test_window_shorter_than_data_yields_nothing():
    ngram = NGram({0: ["ts"], 4: ["ts"]}, delta_threshold=None,
                  timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    assert ngram.form_ngram(_rows([1, 2, 3]), SCHEMA) == []


def test_make_namedtuple_shapes():
    ngram = NGram({0: ["ts", "value"], 1: ["value"]}, delta_threshold=1,
                  timestamp_field="ts")
    ngram.resolve_regex_field_names(SCHEMA)
    windows = ngram.form_ngram(_rows([1, 2]), SCHEMA)
    as_tuple = ngram.make_namedtuple(SCHEMA, windows[0])
    assert as_tuple[0].ts == 1 and as_tuple[0].value == 2.0
    assert as_tuple[1]._fields == ("value",)


def test_validation_errors():
    with pytest.raises(ValueError, match="non-empty"):
        NGram({}, 1, "ts")
    with pytest.raises(ValueError, match="Offsets"):
        NGram({"a": ["ts"]}, 1, "ts")
