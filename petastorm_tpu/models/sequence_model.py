"""Sequence encoder with ring attention — the long-context consumer.

The reference's long-sequence feature is NGram window assembly
(SURVEY.md §5): multi-frame sensor/video rows become ``[B, T, ...]`` windows.
This model closes the loop on TPU: windows from
``collate_ngram_rows``/``make_jax_dataloader`` feed a transformer-style
encoder whose attention runs **sequence-parallel** over a mesh axis using
**ring attention** — each device holds a ``T/sp`` slice of the sequence, and
K/V blocks rotate around the ICI ring via ``lax.ppermute`` while an online
(flash-style) softmax accumulates, so no device ever materializes the full
``[T, T]`` score matrix or the full sequence. This is the standard JAX
long-context recipe: ``shard_map`` + collective permute, letting XLA overlap
the ring hop with the local block's compute.

All shapes are static; the ring loop is a ``lax.fori_loop`` (compiler-visible
control flow); matmuls run in bfloat16 on the MXU with f32 softmax
statistics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def attention_reference(q, k, v):
    """Plain (unsharded) scaled-dot-product attention — numerics oracle for
    the ring version. Shapes: [B, T, H, Dh]."""
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    scores = jnp.einsum("blhd,bmhd->bhlm", q, k) * scale
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhlm,bmhd->blhd", probs, v)


def ring_attention_block(q, k, v, axis_name, axis_size, varying_axes=None):
    """Per-shard ring attention body (runs inside shard_map).

    ``q, k, v``: the local sequence slice, [B, L, H, Dh] with L = T/sp.
    K/V blocks rotate ``axis_size`` times around ``axis_name``; an online
    softmax (running max + running sum, f32) makes the result exactly equal
    to attention over the full sequence.
    """
    b, l, h, dh = q.shape
    scale = 1.0 / jnp.sqrt(jnp.array(dh, jnp.float32))
    qf = q.astype(jnp.float32)

    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def body(_, carry):
        k_cur, v_cur, acc, row_max, row_sum = carry
        scores = jnp.einsum("blhd,bmhd->bhlm", qf,
                            k_cur.astype(jnp.float32)) * scale
        blk_max = scores.max(axis=-1)
        new_max = jnp.maximum(row_max, blk_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[..., None])
        acc = acc * correction[..., None] + jnp.einsum(
            "bhlm,bmhd->bhld", probs, v_cur.astype(jnp.float32))
        row_sum = row_sum * correction + probs.sum(axis=-1)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, acc, new_max, row_sum

    # The softmax stats start as constants but the loop body mixes them with
    # the (sequence-varying) K/V blocks; mark them varying over the ring axis
    # so the fori_loop carry types line up under shard_map's vma typing.
    def varying(x):
        axes = tuple(varying_axes or (axis_name,))
        pcast = getattr(jax.lax, "pcast", None)
        if pcast is not None:
            return pcast(x, axes, to="varying")
        return jax.lax.pvary(x, axes)  # pre-pcast jax versions

    init = (k, v,
            varying(jnp.zeros((b, h, l, dh), jnp.float32)),
            varying(jnp.full((b, h, l), -jnp.inf, jnp.float32)),
            varying(jnp.zeros((b, h, l), jnp.float32)))
    _, _, acc, _, row_sum = jax.lax.fori_loop(0, axis_size, body, init)
    out = acc / row_sum[..., None]
    return jnp.einsum("bhld->blhd", out).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", batch_axis=None):
    """Sequence-parallel attention over ``mesh[axis_name]``.

    Inputs are global ``[B, T, H, Dh]`` arrays (sharded or shardable on T);
    output matches :func:`attention_reference` up to float tolerance.
    ``batch_axis``: mesh axis the batch dim is sharded over (data parallel),
    so shard_map doesn't force a reshard at the boundary.
    """
    from jax import shard_map

    spec = P(batch_axis, axis_name, None, None)
    varying_axes = (axis_name,) + ((batch_axis,) if batch_axis else ())
    sharded = shard_map(
        functools.partial(ring_attention_block, axis_name=axis_name,
                          axis_size=mesh.shape[axis_name],
                          varying_axes=varying_axes),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return sharded(q, k, v)


def ulysses_attention_block(q, k, v, axis_name, axis_size):
    """Per-shard Ulysses (all-to-all) attention body (runs inside shard_map).

    Input: the local sequence slice ``[B, L, H, Dh]`` with ``L = T/sp``.
    The DeepSpeed-Ulysses recipe, JAX-style: an all-to-all reshards from
    sequence-sharded/head-replicated to head-sharded/sequence-complete, each
    device runs DENSE attention over the full sequence for its ``H/sp``
    heads, and a reverse all-to-all restores sequence sharding. Two
    all-to-alls per attention vs the ring's ``sp`` permutes — better when
    heads divide evenly and the full-sequence [T, T] block fits (pair with
    the Pallas flash kernel for the local attention when it doesn't).
    """
    b, l, h, dh = q.shape
    if h % axis_size:
        raise ValueError(
            f"ulysses attention needs heads ({h}) divisible by the mesh "
            f"axis ({axis_size}); use ring attention otherwise")

    def to_heads(x):
        # [B, L, H, Dh] -> all_to_all over the head axis: each device trades
        # its sequence slice of all heads for the full sequence of its
        # H/axis_size heads -> [B, L*axis_size = T, H/axis_size, Dh].
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_sequence(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    out = attention_reference(to_heads(q), to_heads(k), to_heads(v))
    return to_sequence(out)


def ulysses_attention(q, k, v, mesh, axis_name="sp", batch_axis=None):
    """All-to-all sequence-parallel attention over ``mesh[axis_name]``.

    Same contract as :func:`ring_attention` (global ``[B, T, H, Dh]`` in,
    matches :func:`attention_reference` numerics); requires ``H`` divisible
    by the axis size. The two collectives ride ICI like the ring's permutes
    — pick by workload: Ulysses moves ``O(T·Dh·H/sp)`` twice, the ring moves
    K/V ``sp`` times but never needs the full sequence on one device.
    """
    from jax import shard_map

    spec = P(batch_axis, axis_name, None, None)
    sharded = shard_map(
        functools.partial(ulysses_attention_block, axis_name=axis_name,
                          axis_size=mesh.shape[axis_name]),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return sharded(q, k, v)


# --- a small encoder around it -------------------------------------------

def init_seq_params(rng, feature_dim, d_model=64, num_heads=4, num_classes=10,
                    max_len=512, dtype=jnp.float32):
    """Parameter pytree: embed → (q,k,v,o) attention → classifier.

    ``num_heads`` is NOT stored in the pytree (a static int inside jit-traced
    params would poison reshapes); pass it to :func:`apply_seq_model` /
    :func:`make_seq_train_step`."""
    del num_heads  # accepted for signature convenience; static, not stored
    keys = jax.random.split(rng, 7)
    s = lambda fan: 1.0 / jnp.sqrt(fan)  # noqa: E731
    return {
        "embed": jax.random.normal(keys[0], (feature_dim, d_model), dtype) * s(feature_dim),
        "pos": jax.random.normal(keys[1], (max_len, d_model), dtype) * 0.02,
        "wq": jax.random.normal(keys[2], (d_model, d_model), dtype) * s(d_model),
        "wk": jax.random.normal(keys[3], (d_model, d_model), dtype) * s(d_model),
        "wv": jax.random.normal(keys[4], (d_model, d_model), dtype) * s(d_model),
        "wo": jax.random.normal(keys[5], (d_model, d_model), dtype) * s(d_model),
        "cls": jax.random.normal(keys[6], (d_model, num_classes), dtype) * s(d_model),
    }


def seq_param_partition_specs():
    """PartitionSpecs over a ("data", "sp") mesh: weights replicated (the
    parallel axis is the sequence, not the model)."""
    return {"embed": P(), "pos": P(), "wq": P(), "wk": P(), "wv": P(),
            "wo": P(), "cls": P()}


def apply_seq_model(params, windows, num_heads=4, mesh=None, attn_axis="sp",
                    compute_dtype=jnp.bfloat16, attn_impl="dense"):
    """``windows``: [B, T, F] float (NGram windows collated to a time axis).

    With ``mesh``: sequence-parallel attention over ``mesh[attn_axis]`` (T
    must divide by the axis size) — ``attn_impl="ring"`` (default; K/V
    ppermute ring, online softmax) or ``"ulysses"`` (all-to-all head
    resharding; needs heads divisible by the axis). Without a mesh:
    single-shard attention — ``attn_impl="dense"`` (XLA einsum softmax;
    ``"ring"`` also maps here, being its exact single-shard equivalent) or
    ``"flash"`` (the Pallas tiled kernel,
    ``petastorm_tpu.ops.flash_attention`` — O(block²) memory, the TPU
    choice for long windows). Returns f32 logits [B, num_classes].
    """
    h = num_heads
    x = windows.astype(compute_dtype) @ params["embed"].astype(compute_dtype)
    b, t, d = x.shape
    x = x + params["pos"][:t].astype(compute_dtype)

    def split(w):
        y = x @ w.astype(compute_dtype)
        return y.reshape(b, t, h, d // h)

    q, k, v = split(params["wq"]), split(params["wk"]), split(params["wv"])
    if mesh is not None:
        if attn_impl == "dense":  # the no-mesh default: means "ring" here
            attn_impl = "ring"
        if attn_impl not in ("ring", "ulysses"):
            raise ValueError(
                f"attn_impl {attn_impl!r} is not a sequence-parallel "
                f"implementation; with a mesh use 'ring' or 'ulysses'")
        batch_axis = "data" if "data" in mesh.axis_names else None
        parallel_attn = (ulysses_attention if attn_impl == "ulysses"
                         else ring_attention)
        attn = parallel_attn(q, k, v, mesh, attn_axis,
                             batch_axis=batch_axis)
    elif attn_impl == "ring":
        # Symmetric remap: "ring" is the mesh-side default (the train-step
        # factory passes it unconditionally); without a mesh it means plain
        # dense attention on the single shard.
        attn = attention_reference(q, k, v)
    elif attn_impl == "flash":
        from petastorm_tpu.ops import flash_attention

        if t < 8:
            # Below the TPU min sublane tile the kernel's (block, 128)
            # scratch would not tile for Mosaic; dense is cheaper anyway.
            attn = attention_reference(q, k, v)
        else:
            block = min(128, t)
            attn = flash_attention(q, k, v, block_q=block, block_k=block)
    elif attn_impl == "dense":
        attn = attention_reference(q, k, v)
    else:
        raise ValueError(
            f"attn_impl {attn_impl!r} is not valid without a mesh "
            f"('ulysses' needs one); use 'dense', 'ring', or 'flash'")
    attn = attn.reshape(b, t, d) @ params["wo"].astype(compute_dtype)
    pooled = attn.mean(axis=1)
    logits = pooled @ params["cls"].astype(compute_dtype)
    return logits.astype(jnp.float32)


def make_seq_train_step(learning_rate=0.05, num_heads=4, mesh=None,
                        attn_axis="sp", attn_impl="ring"):
    """``step(params, windows, labels, mask) -> (params, loss)`` — masked
    cross-entropy + SGD, sequence-parallel attention (ring or ulysses) when
    a mesh is given. The returned step is jittable as-is (all statics are
    closed over)."""
    def loss_fn(params, windows, labels, mask):
        logits = apply_seq_model(params, windows, num_heads=num_heads,
                                 mesh=mesh, attn_axis=attn_axis,
                                 attn_impl=attn_impl)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        nll = jnp.where(mask, nll, 0.0)
        return nll.sum() / jnp.maximum(mask.sum(), 1).astype(jnp.float32)

    def step(params, windows, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(params, windows, labels,
                                                  mask)
        new_params = jax.tree_util.tree_map(
            lambda p, g: (p - learning_rate * g).astype(p.dtype),
            params, grads)
        return new_params, loss

    return step
