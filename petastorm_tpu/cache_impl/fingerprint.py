"""Content fingerprints for decoded-batch cache keys.

A cached batch sequence is only reusable when *everything that shaped it*
matches: the dataset, the row-group pieces read, the selected fields /
schema view, the batch size and last-batch policy, and any transform. The
fingerprint canonicalizes all of that into one hex digest; changing any
ingredient changes the key, so a stale entry is simply never found (miss →
re-decode → refill) rather than ever being served wrong.

Two keying granularities share this function:

- the service worker keys **per piece** (``pieces=[piece_index]``), so an
  epoch's stream is a sequence of per-piece lookups and a re-partitioned
  plan (worker takeover) still hits on the pieces both plans share;
- the JAX loader keys **per reader plan** (``pieces=[(path, row_group),
  ...]``), one entry for the whole epoch's batch sequence.
"""

from __future__ import annotations

import hashlib
import json

#: Bump when the on-wire/cached entry layout changes: old entries must
#: become misses, not deserialization errors.
FINGERPRINT_VERSION = 1


def _canonical(value):
    """JSON-stable canonical form; non-JSON leaves fall back to ``repr``
    (transform specs, predicates, NGram objects — their repr is what the
    seed-parity row-group caches already key on)."""
    if isinstance(value, dict):
        return {str(k): _canonical(v) for k, v in sorted(value.items())}
    if isinstance(value, (list, tuple)):
        return [_canonical(v) for v in value]
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    return repr(value)


def batch_fingerprint(dataset_url, pieces, batch_size, fields=None,
                      transform=None, factory=None, extra=None):
    """Hex digest keying a cached batch sequence.

    :param dataset_url: the dataset the batches were decoded from.
    :param pieces: piece identity — indices into the canonical row-group
        enumeration (service worker) or ``(path, row_group)`` pairs (local
        reader plan).
    :param batch_size: rows per collated batch.
    :param fields: the selected fields / schema view (names, regexes, or an
        NGram — anything with a stable repr).
    :param transform: transform config (a TransformSpec or its repr).
    :param factory: which reader family decoded the batches (``"row"`` /
        ``"batch"`` / ``"columnar"`` or a callable's qualname) — the three
        families emit different collation layouts for codec columns.
    :param extra: any further invalidation inputs (filters, predicate,
        last-batch policy, ...).
    """
    payload = json.dumps({
        "v": FINGERPRINT_VERSION,
        "url": str(dataset_url),
        "pieces": _canonical(list(pieces)),
        "batch_size": int(batch_size),
        "fields": _canonical(fields),
        "transform": _canonical(transform),
        "factory": _canonical(getattr(factory, "__qualname__", factory)),
        "extra": _canonical(extra),
    }, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()
