"""GCS fast-listing tests — all against a fake fsspec filesystem (no network).

Reference analogue: ``petastorm/gcsfs_helpers/gcsfs_fast_list.py`` (SURVEY.md
§2.4): one recursive listing sweep + pseudo-directory synthesis replaces
per-directory ``ls`` round-trips during dataset discovery.
"""

import pytest

from petastorm_tpu.gcsfs_helpers.gcsfs_fast_list import (
    FastListingFilesystem,
    build_dircache,
    fast_list,
    seed_listing_cache,
    warm_gcs_listing,
)


class FakeGCSFileSystem:
    """Flat-key store mimicking gcsfs's listing surface.

    ``find`` assembles its result from fixed-size pages the way gcsfs follows
    ``nextPageToken`` — tests assert multi-page listings come back complete.
    Every API entry point counts its calls so tests can prove "one sweep,
    zero per-directory round-trips".
    """

    PAGE_SIZE = 100

    def __init__(self, keys):
        self._objects = {k: {"name": k, "size": 11, "type": "file"}
                         for k in keys}
        self.dircache = {}
        self.find_calls = 0
        self.pages_served = 0
        self.ls_network_calls = 0

    def find(self, path, detail=False):
        self.find_calls += 1
        names = sorted(k for k in self._objects
                       if k == path or k.startswith(path.rstrip("/") + "/"))
        listing = {}
        for start in range(0, len(names), self.PAGE_SIZE):
            self.pages_served += 1  # one objects.list page per PAGE_SIZE keys
            for name in names[start:start + self.PAGE_SIZE]:
                listing[name] = dict(self._objects[name])
        return listing if detail else sorted(listing)

    def ls(self, path, detail=False):
        path = path.rstrip("/")
        if path in self.dircache:  # fsspec semantics: cache first
            infos = self.dircache[path]
            return list(infos) if detail else [i["name"] for i in infos]
        self.ls_network_calls += 1
        raise AssertionError(f"network ls({path!r}) — dircache incomplete")


DATASET_KEYS = [
    "bucket/ds/_common_metadata",
    "bucket/ds/part-00000.parquet",
    "bucket/ds/part-00001.parquet",
    "bucket/ds/year=2024/month=1/part-00002.parquet",
    "bucket/ds/year=2024/month=2/part-00003.parquet",
    "bucket/ds/year=2025/month=1/part-00004.parquet",
]


def test_fast_list_is_one_find_sweep():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    paths = fast_list("gs://bucket/ds", filesystem=fs)
    assert paths == sorted(DATASET_KEYS)
    assert fs.find_calls == 1


def test_fast_list_detail_and_scheme_stripping():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    listing = fast_list("gcs://bucket/ds", filesystem=fs, detail=True)
    assert set(listing) == set(DATASET_KEYS)
    assert listing["bucket/ds/_common_metadata"]["type"] == "file"


def test_fast_list_paginates_completely():
    # 2.5 pages worth of objects — result must span every page.
    keys = [f"bucket/big/part-{i:05d}.parquet" for i in range(250)]
    fs = FakeGCSFileSystem(keys)
    paths = fast_list("gs://bucket/big", filesystem=fs)
    assert len(paths) == 250
    assert fs.find_calls == 1
    assert fs.pages_served == 3  # 100 + 100 + 50


def test_build_dircache_synthesizes_intermediate_directories():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    cache = build_dircache("bucket/ds", fs.find("bucket/ds", detail=True))
    # Every intermediate level exists, including dirs holding only dirs.
    assert set(cache) == {
        "bucket/ds", "bucket/ds/year=2024", "bucket/ds/year=2024/month=1",
        "bucket/ds/year=2024/month=2", "bucket/ds/year=2025",
        "bucket/ds/year=2025/month=1",
    }
    root_names = {i["name"]: i["type"] for i in cache["bucket/ds"]}
    assert root_names["bucket/ds/year=2024"] == "directory"
    assert root_names["bucket/ds/part-00000.parquet"] == "file"
    # A directory containing only directories still lists its children.
    y2025 = cache["bucket/ds/year=2025"]
    assert [i["name"] for i in y2025] == ["bucket/ds/year=2025/month=1"]


def test_build_dircache_skips_root_marker_and_rejects_foreign_paths():
    cache = build_dircache("bucket/ds", {
        "bucket/ds": {"name": "bucket/ds", "size": 0, "type": "file"},
        "bucket/ds/a.parquet": {"name": "bucket/ds/a.parquet", "size": 1,
                                "type": "file"},
    })
    assert [i["name"] for i in cache["bucket/ds"]] == ["bucket/ds/a.parquet"]
    with pytest.raises(ValueError, match="not under the root"):
        build_dircache("bucket/ds", {"bucket/other/x": {"size": 1}})


def test_build_dircache_skips_nested_directory_markers():
    # GCS console creates zero-byte 'dir/' placeholder objects; they must not
    # become phantom files in the cache.
    cache = build_dircache("bucket/ds", {
        "bucket/ds/sub/": {"name": "bucket/ds/sub/", "size": 0,
                           "type": "file"},
        "bucket/ds/sub/a.parquet": {"name": "bucket/ds/sub/a.parquet",
                                    "size": 1, "type": "file"},
    })
    names = [i["name"] for i in cache["bucket/ds/sub"]]
    assert names == ["bucket/ds/sub/a.parquet"]


def test_fast_listing_filesystem_ls_of_file_path():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    wrapped = FastListingFilesystem(fs, "gs://bucket/ds")
    # fsspec contract: ls of a file returns that file's own entry.
    assert wrapped.ls("bucket/ds/part-00000.parquet") == \
        ["bucket/ds/part-00000.parquet"]
    assert wrapped.ls("bucket/ds/part-00000.parquet",
                      detail=True)[0]["size"] == 11


def test_seed_listing_cache_makes_every_ls_hit_memory():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    files = warm_gcs_listing(fs, "gs://bucket/ds")
    assert files == len(DATASET_KEYS)
    assert fs.find_calls == 1
    # Walk the whole tree through ls() — the fake raises on any network ls.
    to_visit = ["bucket/ds"]
    seen_files = []
    while to_visit:
        for info in fs.ls(to_visit.pop(), detail=True):
            if info["type"] == "directory":
                to_visit.append(info["name"])
            else:
                seen_files.append(info["name"])
    assert sorted(seen_files) == sorted(DATASET_KEYS)
    assert fs.ls_network_calls == 0


def test_seed_listing_cache_direct():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    listing = fast_list("gs://bucket/ds", filesystem=fs, detail=True)
    seed_listing_cache(fs, "gs://bucket/ds", listing)
    assert fs.ls("bucket/ds/year=2024") == [
        "bucket/ds/year=2024/month=1", "bucket/ds/year=2024/month=2"]


def test_fast_listing_filesystem_metadata_surface():
    fs = FakeGCSFileSystem(DATASET_KEYS)
    wrapped = FastListingFilesystem(fs, "gs://bucket/ds")
    assert fs.find_calls == 1

    assert wrapped.isdir("bucket/ds/year=2024")
    assert not wrapped.isdir("bucket/ds/part-00000.parquet")
    assert wrapped.isfile("bucket/ds/part-00000.parquet")
    assert wrapped.exists("bucket/ds/year=2025/month=1/part-00004.parquet")
    assert not wrapped.exists("bucket/ds/nope")
    assert wrapped.info("bucket/ds/part-00000.parquet")["size"] == 11
    assert wrapped.info("bucket/ds/year=2024")["type"] == "directory"
    with pytest.raises(FileNotFoundError):
        wrapped.ls("bucket/ds/absent")

    files = wrapped.find("bucket/ds/year=2024")
    assert files == ["bucket/ds/year=2024/month=1/part-00002.parquet",
                     "bucket/ds/year=2024/month=2/part-00003.parquet"]

    walked = list(wrapped.walk())
    dirpaths = [d for d, _, _ in walked]
    assert dirpaths[0] == "bucket/ds"
    assert set(dirpaths) == {
        "bucket/ds", "bucket/ds/year=2024", "bucket/ds/year=2025",
        "bucket/ds/year=2024/month=1", "bucket/ds/year=2024/month=2",
        "bucket/ds/year=2025/month=1",
    }
    all_files = [f for _, _, fnames in walked for f in fnames]
    assert len(all_files) == len(DATASET_KEYS)
    # After construction, zero further API calls were made.
    assert fs.find_calls == 1
    assert fs.ls_network_calls == 0


def test_fast_listing_filesystem_passes_content_ops_through():
    class FakeWithOpen(FakeGCSFileSystem):
        def open(self, path, mode="rb"):
            return ("opened", path, mode)

    fs = FakeWithOpen(DATASET_KEYS)
    wrapped = FastListingFilesystem(fs, "gs://bucket/ds")
    assert wrapped.open("bucket/ds/part-00000.parquet") == \
        ("opened", "bucket/ds/part-00000.parquet", "rb")
