"""Graph-rewrite catalog + pure trigger economics.

The autotuner's action space beyond knob nudges
(``docs/guides/pipeline.md#graph-rewrites``): structural changes to the
pipeline topology, applied through the same pure-plan → probe →
revert-on-regression → journal machinery as every knob — with a longer
``rewrite_hysteresis``, next-iteration application (a topology never
changes mid-stream), and trigger predicates gating each rewrite on the
measured economics that make it worth probing at all. A rewrite whose
trigger does not fire is simply skipped (the planner falls through to the
class's knob levers), so knob-only workloads never pay a wasted probe.

Everything here is a pure function of the window profile — canned-profile
golden tests pin every trigger and threshold (``tests/test_rewrites.py``).
"""

from __future__ import annotations

#: The rewrite catalog: every kind the planner may apply, its graph knob,
#: and the knob value that means "rewrite in force" (the other value is
#: the baseline topology). ``docs/guides/pipeline.md`` documents each row
#: (test_docs asserts the catalog table covers every kind declared here).
REWRITE_KINDS = {
    "fuse_worker_stages": {
        "knob": "stage_fusion",
        "applied_value": "fused",
        "description": (
            "Collapse the worker-side collate→transform(→pack)→serialize "
            "chain into the decode pool task (one fused task per piece): "
            "per-output hand-off cost disappears and serialization "
            "parallelizes across pool workers. Byte-identical output."),
    },
    "hoist_filter": {
        "knob": "filter_placement",
        "applied_value": "worker",
        "description": (
            "Move the declared row filter (and column projection) from "
            "trainer-side batch masking to the workers' two-phase "
            "predicate read BELOW decode: dropped rows never decode, "
            "never serialize, never cross the wire."),
    },
    "cache_placement": {
        "knob": "cache_placement",
        "applied_value": "post-decode",
        "description": (
            "Choose the worker batch cache's insertion point relative to "
            "the batch transform: post-transform (warm serves are "
            "zero-work) vs post-decode (entries hold smaller/shareable "
            "pre-transform bytes; warm serves re-apply the transform)."),
    },
    "row_vs_columnar": {
        "knob": "reader_family",
        "applied_value": "columnar",
        "description": (
            "Serve the stream through the columnar reader family: codec "
            "decode runs as vectorized per-column kernels over whole "
            "Arrow batches instead of per-row Python materialization "
            "(no to_pylist on the hot path). Decoded bytes are "
            "identical; exotic codecs/readers fall back to the row "
            "path per piece, still byte-identical."),
    },
}

#: Trigger thresholds (override via ``autotune={'rewrite_thresholds':
#: {...}}``). Semantics per trigger below.
DEFAULT_THRESHOLDS = {
    # fuse: the stream-thread work fusion would move into the pool task
    # (collation + serialization hand-off, plus the batch transform when
    # it runs worker-side) must be at least this fraction of the measured
    # decode cost (the tf.data fused-map economics: fusing only pays when
    # the single serving thread's serial work is a visible share of the
    # parallelizable work).
    "fuse_overhead_frac": 0.15,
    # hoist: the client-side filter must be dropping at least this
    # fraction of decoded rows (below it, the saved decode does not cover
    # the risk of a probe round).
    "hoist_min_drop_frac": 0.25,
    # cache → post-decode: only when the transform is CHEAP to re-apply —
    # its window cost at most this fraction of worker decode cost — and
    # the cache shows eviction pressure (entry bytes are the constraint).
    "cache_cheap_transform_frac": 0.25,
    # cache → post-transform: only when warm serving dominates (hit rate
    # at least cache_min_hit_rate) and re-applying the transform per
    # serve costs at least this fraction of the window wall.
    "cache_hot_transform_frac": 0.20,
    "cache_min_hit_rate": 0.5,
    # row→columnar: worker decode must dominate the window wall — the
    # vectorized kernels only move the needle when per-row decode IS the
    # bottleneck (a transport- or consumer-bound stream gains nothing and
    # pays a cache re-fill, since the two families key entries apart).
    "columnar_min_decode_frac": 0.30,
}


def _get(profile, key):
    value = profile.get(key)
    return float(value) if value else 0.0


def rewrite_triggered(kind, want, profile, thresholds=None):
    """Does the window's measured profile justify probing this rewrite?

    Returns ``(triggered, reason)`` — ``reason`` is the journal string
    explaining the economics (empty when not triggered). Pure: reads only
    the profile dict and thresholds.
    """
    t = dict(DEFAULT_THRESHOLDS)
    if thresholds:
        t.update(thresholds)
    if kind == "fuse_worker_stages":
        handoff = _get(profile, "handoff_s")
        movable = handoff
        if profile.get("knobs", {}).get("transform_placement", "remote") \
                == "remote":
            # A worker-side transform runs on the same single serving
            # thread the hand-off work does — fusion moves it into the
            # pool task too (parallel across pool workers).
            movable += _get(profile, "transform_s")
        decode = _get(profile, "worker_decode_s")
        if handoff > 0 and movable >= t["fuse_overhead_frac"] * max(
                decode, 1e-9):
            return True, (f"serving-thread work {movable:.3f}s (handoff "
                          f"{handoff:.3f}s) >= "
                          f"{t['fuse_overhead_frac']:.0%} of decode "
                          f"{decode:.3f}s")
        return False, ""
    if kind == "hoist_filter":
        rows_in = _get(profile, "filter_rows_in")
        kept = _get(profile, "filter_rows_kept")
        if rows_in > 0:
            drop_frac = 1.0 - kept / rows_in
            if drop_frac >= t["hoist_min_drop_frac"]:
                return True, (f"client filter drops {drop_frac:.0%} of "
                              f"decoded rows")
        return False, ""
    if kind == "cache_placement":
        hits = _get(profile, "cache_hits")
        misses = _get(profile, "cache_misses")
        lookups = hits + misses
        transform_s = _get(profile, "transform_s")
        if want == "post-decode":
            evictions = _get(profile, "cache_evictions")
            decode_s = _get(profile, "worker_decode_s")
            if lookups > 0 and evictions > 0 \
                    and transform_s <= t["cache_cheap_transform_frac"] \
                    * max(decode_s, 1e-9):
                return True, (f"eviction pressure ({evictions:.0f} in "
                              f"window) with cheap transform "
                              f"({transform_s:.3f}s vs decode "
                              f"{decode_s:.3f}s): pre-transform entries "
                              f"admit more")
            return False, ""
        # want == "post-transform": warm serving pays the transform per
        # serve — move the cache above it once that cost is visible.
        wall = _get(profile, "wall_s")
        if lookups > 0 and wall > 0:
            hit_rate = hits / lookups
            if hit_rate >= t["cache_min_hit_rate"] \
                    and transform_s >= t["cache_hot_transform_frac"] * wall:
                return True, (f"warm serves (hit rate {hit_rate:.0%}) "
                              f"re-pay the transform "
                              f"({transform_s:.3f}s of {wall:.3f}s wall)")
        return False, ""
    if kind == "row_vs_columnar":
        decode_s = _get(profile, "worker_decode_s")
        wall = _get(profile, "wall_s")
        if decode_s > 0 and wall > 0 \
                and decode_s >= t["columnar_min_decode_frac"] * wall:
            return True, (f"worker decode {decode_s:.3f}s is "
                          f"{decode_s / wall:.0%} of the {wall:.3f}s "
                          f"window: vectorized columnar kernels replace "
                          f"per-row decode")
        return False, ""
    raise ValueError(f"unknown rewrite kind {kind!r}")


def rewrite_kind_for_knob(knob_name):
    """The catalog kind a knob belongs to, or ``None``."""
    for kind, info in REWRITE_KINDS.items():
        if info["knob"] == knob_name:
            return kind
    return None
