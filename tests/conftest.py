"""Test-session configuration.

JAX runs on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (SURVEY.md §4 "implication for the rebuild").
Env vars must be set before jax is first imported anywhere in the test run.
"""

import os

# Force CPU even when the session has a real TPU attached (JAX_PLATFORMS=axon):
# the suite needs 8 virtual devices to exercise sharding; the single real chip
# is for bench.py only. The axon sitecustomize overrides the JAX_PLATFORMS env
# var via jax.config, so we must override back through jax.config too.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import sys  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import threading  # noqa: E402
import time  # noqa: E402
from types import SimpleNamespace  # noqa: E402

import pytest  # noqa: E402


def _open_socket_fds():
    """Snapshot of this process's open socket fds as (fd, inode) pairs —
    Linux-only (/proc); empty elsewhere, which disables the socket check."""
    out = set()
    try:
        fds = os.listdir("/proc/self/fd")
    except OSError:
        return out
    for fd in fds:
        try:
            target = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue  # fd closed between listdir and readlink
        if target.startswith("socket:"):
            out.add((fd, target))
    return out


def _open_memfd_fds():
    """Snapshot of this process's open shm-transport memfd fds as
    (fd, name) pairs. Every arena :mod:`petastorm_tpu.service.shm_ring`
    creates — ring data regions, frame pools — carries the ``ptshm``
    memfd name prefix precisely so this scan can spot one surviving a
    test: an orphaned arena pins its full size in /dev/shm for the rest
    of the session. Linux-only (/proc); empty elsewhere."""
    out = set()
    try:
        fds = os.listdir("/proc/self/fd")
    except OSError:
        return out
    for fd in fds:
        try:
            target = os.readlink(f"/proc/self/fd/{fd}")
        except OSError:
            continue  # fd closed between listdir and readlink
        if target.startswith("/memfd:ptshm"):
            out.add((fd, target))
    return out


#: Process-lifetime thread pools libraries create on first use and keep
#: forever (not per-test leaks): orbax-checkpoint's async machinery.
_LIBRARY_SINGLETON_THREAD_PREFIXES = ("metadata_store", "base_pytree_ch",
                                      "orbax", "grpc")

#: Reader-pool worker threads are DAEMON threads (the non-daemon check
#: misses them), but an orphaned one means a Reader — e.g. the one owned
#: by a service stream's streaming piece engine — was never stopped: it
#: keeps decoding into a bounded queue nobody drains and pins its pool
#: for the rest of the session.
_READER_POOL_THREAD_PREFIX = "petastorm-tpu-worker"

#: The pipeline autotuner's controller thread is a daemon too; one
#: surviving a test means an autotuned loader was never stopped — it
#: keeps re-planning (and resizing pools!) against a dead pipeline for
#: the rest of the session. Graph-rewrite rounds (stage fusion, filter
#: hoisting, cache placement — pipeline/rewrites.py) run on this same
#: controller thread, so a leaked rewrite controller is caught by this
#: prefix too — rewrites spawn no threads of their own (the fused pool
#: task reuses the reader pool's "petastorm-tpu-worker" threads, guarded
#: above).
_AUTOTUNE_THREAD_PREFIX = "pipeline-autotune"

#: The fleet autoscaler's controller thread: one surviving a test means a
#: dispatcher armed with autoscale= was never stopped — it keeps applying
#: (and journaling!) admit/drain decisions against a dead fleet.
_FLEET_AUTOSCALE_THREAD_PREFIX = "fleet-autoscale"

#: The chaos-schedule fuzzer's per-seed run threads: one surviving a test
#: means a fuzz run hung past its join budget and was abandoned with a
#: live service topology inside it.
_FUZZ_THREAD_PREFIX = "failpoint-fuzz"

#: The fleet cache tier's peer threads — ``cache-peer-push-<wid>``
#: (placement pusher) and ``cache-peer-handoff-<wid>`` (drain handoff
#: shipper, worker.py). Both are daemons; one surviving a test means a
#: fleet-cache worker was never stopped (or its tier never cleanup()d) —
#: the pusher keeps dialing ring peers that no longer exist.
_CACHE_PEER_THREAD_PREFIX = "cache-peer"


def _orphan_cache_tmp_files():
    """``.tmp`` staging files inside every LIVE cache dir. The disk tier
    writes entries as ``mkstemp(... suffix=".tmp")`` + ``os.replace``; a
    write interrupted between the two — a failpoint (``handoff-torn``,
    ``cache-peer-gone``) firing mid-handoff-adoption, a killed worker —
    orphans the staging file, which ``os.replace`` will never claim and
    eviction (keyed on the entry suffix) will never delete."""
    from petastorm_tpu.cache_impl import live_cache_dirs

    out = set()
    for cache_dir in live_cache_dirs():
        try:
            names = os.listdir(cache_dir)
        except OSError:
            continue  # dir vanished — live_cache_dirs leak check owns it
        out.update(os.path.join(cache_dir, n) for n in names
                   if n.endswith(".tmp"))
    return out


@pytest.fixture(autouse=True)
def _resource_leak_guard(request):
    """Fail any tier-1 test that leaks a non-daemon thread, a socket, or a
    cache-created directory past its teardown.

    The service stack (dispatcher/worker/client, heartbeats, chaos) is all
    threads + sockets; a test that forgets to stop a node would silently
    tax every later test in the session. Caches (the decoded-batch cache's
    tiers, ``LocalDiskCache``) register every directory they *create* with
    ``cache_impl`` and deregister on ``cleanup()`` — an entry surviving the
    test means some owner (a worker, a reader, the cache itself) was never
    cleaned up, the exact leak class that accumulates spill dirs across
    worker restarts. A short grace loop absorbs asynchronous teardown
    (daemon handler threads closing sockets, GC-collected connections);
    whatever survives it is a leak. Opt out with
    ``@pytest.mark.allow_resource_leaks`` (and a reason)."""
    from petastorm_tpu import failpoints
    from petastorm_tpu.cache_impl import live_cache_dirs
    from petastorm_tpu.service.fleet import open_job_registrations
    from petastorm_tpu.service.mixture import open_mixture_passes
    from petastorm_tpu.service.shm_ring import live_shm_counts

    if request.node.get_closest_marker("allow_resource_leaks"):
        yield
        return
    before_threads = set(threading.enumerate())
    before_sockets = _open_socket_fds()
    before_memfds = _open_memfd_fds()
    before_shm = live_shm_counts()
    before_cache_dirs = live_cache_dirs()
    before_cache_tmp = _orphan_cache_tmp_files()
    before_jobs = open_job_registrations()
    before_mixture_passes = open_mixture_passes()
    yield
    leaked_schedule = failpoints.ACTIVE
    if leaked_schedule is not None:
        # Disarm FIRST so one leak cannot inject faults into every later
        # test, then fail: an armed schedule outliving its test is the
        # quarantine/chaos analogue of an unstopped node.
        failpoints.disarm()
    # A leaked schedule is already a failure — the grace loop below only
    # absorbs ASYNCHRONOUS teardown, which cannot un-leak it: take one
    # pass collecting the other leak classes and fail immediately.
    deadline = time.monotonic() + (0.0 if leaked_schedule is not None
                                   else 2.0)
    while True:
        leaked_threads = [
            t for t in threading.enumerate()
            if t not in before_threads and t.is_alive() and not t.daemon
            and not t.name.startswith(_LIBRARY_SINGLETON_THREAD_PREFIXES)]
        leaked_pool_threads = [
            t for t in threading.enumerate()
            if t not in before_threads and t.is_alive()
            and t.name.startswith((_READER_POOL_THREAD_PREFIX,
                                   _AUTOTUNE_THREAD_PREFIX,
                                   _FLEET_AUTOSCALE_THREAD_PREFIX,
                                   _FUZZ_THREAD_PREFIX,
                                   _CACHE_PEER_THREAD_PREFIX))]
        leaked_sockets = _open_socket_fds() - before_sockets
        leaked_memfds = _open_memfd_fds() - before_memfds
        # Live-arena registry deltas: a leaked RingProducer/RingConsumer
        # or FramePool (or its doorbell eventfds — invisible to the
        # memfd scan) means a stream transport was never closed.
        after_shm = live_shm_counts()
        leaked_shm = {kind: after_shm[kind] - before_shm.get(kind, 0)
                      for kind in after_shm
                      if after_shm[kind] > before_shm.get(kind, 0)}
        leaked_cache_dirs = live_cache_dirs() - before_cache_dirs
        leaked_cache_tmp = _orphan_cache_tmp_files() - before_cache_tmp
        leaked_jobs = open_job_registrations() - before_jobs
        # An abandoned MixedBatchSource pass holds N per-corpus inner
        # iterators (stream threads, heartbeats, sockets) — the mixture
        # analogue of an unstopped Reader.
        leaked_mixture = open_mixture_passes() - before_mixture_passes
        if not leaked_threads and not leaked_pool_threads \
                and not leaked_sockets and not leaked_memfds \
                and not leaked_shm and not leaked_cache_dirs \
                and not leaked_cache_tmp \
                and not leaked_jobs and leaked_mixture <= 0 \
                and leaked_schedule is None:
            return
        if time.monotonic() >= deadline:
            break
        time.sleep(0.05)
    pytest.fail(
        f"test leaked resources past teardown: "
        f"non-daemon threads {[t.name for t in leaked_threads]}, "
        f"reader-pool/autotune/fleet-autoscale/failpoint-fuzz/cache-peer "
        f"threads {[t.name for t in leaked_pool_threads]} "
        f"(an unstopped Reader — e.g. a streaming piece engine whose "
        f"owner never stopped/joined it — an autotuned loader whose "
        f"controller was never stopped, a Dispatcher(autoscale=) never "
        f"stopped, a hung fuzz run, or a fleet-cache worker whose peer "
        f"pusher/handoff thread was never stopped), "
        f"sockets {sorted(leaked_sockets)}, "
        f"shm arenas: memfds {sorted(leaked_memfds)}, live ring/pool/"
        f"eventfd registry deltas {leaked_shm} (a RingProducer/"
        f"RingConsumer or FramePool never close()d — an orphaned arena "
        f"pins its full size in /dev/shm), "
        f"cache dirs {sorted(leaked_cache_dirs)}, "
        f"orphaned cache .tmp staging files {sorted(leaked_cache_tmp)} "
        f"(a disk-tier write — e.g. a handoff adoption spilling to disk "
        f"— interrupted between mkstemp and os.replace), "
        f"open job registrations {sorted(leaked_jobs)} (a register_job "
        f"without end_job — use fleet.JobHandle), "
        f"open mixture passes {max(leaked_mixture, 0)} (a "
        f"MixedBatchSource iterator abandoned without close() — its "
        f"per-corpus inner sources stay live), "
        f"armed failpoint schedule "
        f"{'yes (now disarmed)' if leaked_schedule is not None else 'no'} "
        f"(use failpoints.armed(...) so the scope always disarms) — "
        f"stop/close every service node, loader, engine, and connection "
        f"the test started, and cleanup() every cache "
        f"(mark allow_resource_leaks only with a documented reason)",
        pytrace=False)


#: ROADMAP's tier-1 timeout and the fraction of it the `-m 'not slow'`
#: suite may consume before the gate fails: steal/chaos tests must not
#: silently bloat the fast suite until the 870s timeout starts flaking.
_TIER1_TIMEOUT_S = 870.0
_TIER1_BUDGET_FRACTION = 0.8


def pytest_configure(config):
    config._tier1_budget_start = time.monotonic()


def pytest_sessionfinish(session, exitstatus):
    """Fail an otherwise-green `-m 'not slow'` run that exceeds 80% of the
    ROADMAP's 870s tier-1 timeout — a runtime regression is a gate
    failure BEFORE it becomes a timeout flake."""
    markexpr = getattr(session.config.option, "markexpr", "") or ""
    if "not slow" not in markexpr:
        return
    start = getattr(session.config, "_tier1_budget_start", None)
    if start is None:
        return
    elapsed = time.monotonic() - start
    budget = _TIER1_TIMEOUT_S * _TIER1_BUDGET_FRACTION
    if elapsed > budget and exitstatus == 0:
        reporter = session.config.pluginmanager.get_plugin(
            "terminalreporter")
        message = (
            f"tier-1 runtime budget exceeded: the -m 'not slow' suite took "
            f"{elapsed:.0f}s, over {_TIER1_BUDGET_FRACTION:.0%} of the "
            f"{_TIER1_TIMEOUT_S:.0f}s ROADMAP timeout ({budget:.0f}s). "
            f"Move slow additions behind @pytest.mark.slow or shrink them.")
        if reporter is not None:
            reporter.write_sep("!", message)
        session.exitstatus = 1


@pytest.fixture(scope="session")
def petastorm_dataset(tmp_path_factory):
    """Session-scoped synthetic petastorm-format dataset (30 rows, 3 row
    groups) — the analogue of the reference's ``create_test_dataset`` fixture."""
    from petastorm_tpu.test_util.dataset_factory import TestSchema, create_test_dataset

    path = tmp_path_factory.mktemp("data") / "petastorm_ds"
    url = f"file://{path}"
    rows = create_test_dataset(url, rows_count=30, rows_per_row_group=10)
    return SimpleNamespace(url=url, path=str(path), rows=rows, schema=TestSchema)


@pytest.fixture(scope="session")
def scalar_dataset(tmp_path_factory):
    """Session-scoped plain-Parquet dataset for make_batch_reader tests."""
    from petastorm_tpu.test_util.dataset_factory import ScalarSchema, create_test_scalar_dataset

    path = tmp_path_factory.mktemp("data") / "scalar_ds"
    url = f"file://{path}"
    rows = create_test_scalar_dataset(url, rows_count=30, rows_per_row_group=10)
    return SimpleNamespace(url=url, path=str(path), rows=rows, schema=ScalarSchema)
