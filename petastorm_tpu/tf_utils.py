"""TensorFlow adapter: Reader → ``tf.data.Dataset`` / eager tensors.

Reference parity: ``petastorm/tf_utils.py`` (``make_petastorm_dataset``,
``tf_tensors``, ``_schema_to_tf_dtypes`` and the dtype-promotion map) —
SURVEY.md §2.5, call stack §3.4. Differences from the reference:

- TF2-first: ``from_generator`` with an ``output_signature`` (the reference's
  TF1 ``tf.py_func`` + ``RandomShuffleQueue`` path is expressed with
  ``tf.data`` shuffling instead);
- TF's missing dtypes promote exactly as in the reference: uint16 → int32,
  uint32 → int64, Decimal → string, datetime64 → int64 (epoch ns);
- NGram readers yield ``{offset: namedtuple}`` structures, as upstream.

TF import is deferred so the package never pulls TF unless this module is
used (the reference guards its imports for the same reason).
"""

from __future__ import annotations

import datetime
import re
from decimal import Decimal

import numpy as np

_NUMPY_TO_TF_PROMOTIONS = {
    # numpy dtype name -> tf dtype name; identity unless TF lacks the dtype
    "uint16": "int32",
    "uint32": "int64",
    "uint64": "int64",
}


def _field_tf_dtype(field):
    """tf.DType for a UnischemaField, honoring the promotion map."""
    import tensorflow as tf

    if field.numpy_dtype is Decimal:
        return tf.string
    if field.numpy_dtype in (str, np.str_, bytes, np.bytes_):
        return tf.string
    np_dtype = np.dtype(field.numpy_dtype)
    if np_dtype.kind == "M":
        return tf.int64  # epoch nanoseconds
    name = _NUMPY_TO_TF_PROMOTIONS.get(np_dtype.name, np_dtype.name)
    return tf.dtypes.as_dtype(name)


def _schema_to_tf_dtypes(schema):
    """Ordered ``{field_name: tf.DType}`` for a Unischema (reference helper)."""
    return {name: _field_tf_dtype(field)
            for name, field in schema.fields.items()}


def _sanitize_field_tf_name(name):
    """TF graph names reject some identifier characters the schema allows."""
    return re.sub(r"[^A-Za-z0-9_.\-/]", "_", name)


def _coerce_value(field, value, tf_dtype):
    """Row value → numpy value matching the promoted TF dtype."""
    if value is None:
        raise ValueError(
            f"Field {field.name!r} is None; TF tensors cannot carry nulls — "
            f"filter nullable fields with a predicate/TransformSpec or select "
            f"non-nullable schema_fields")
    if field.numpy_dtype is Decimal:
        return str(value)
    np_dtype = np.dtype(field.numpy_dtype) \
        if field.numpy_dtype not in (str, np.str_, bytes, np.bytes_) else None
    if np_dtype is not None and np_dtype.kind == "M":
        value = np.asarray(value, dtype="datetime64[ns]")
        return value.astype(np.int64)
    if tf_dtype.name in ("int32", "int64") and np_dtype is not None \
            and np_dtype.kind == "u":
        return np.asarray(value).astype(tf_dtype.name)
    return value

def _row_signature(schema, batched):
    """(names, TensorSpec tuple) for the flattened generator output."""
    import tensorflow as tf

    names, specs = [], []
    for name, field in schema.fields.items():
        shape = tuple(field.shape or ())
        if batched:
            shape = (None,) + shape
        specs.append(tf.TensorSpec(shape=shape, dtype=_field_tf_dtype(field),
                                   name=_sanitize_field_tf_name(name)))
        names.append(name)
    return names, tuple(specs)


def make_petastorm_dataset(reader):
    """Wrap a Reader as a ``tf.data.Dataset``.

    - ``make_reader``: dataset of schema namedtuples (one row per element);
      with an NGram, elements are ``{offset: namedtuple}`` dicts.
    - ``make_batch_reader``: dataset of namedtuples of column batches
      (record-batch-sized — apply ``.unbatch().batch(B)`` for training).

    Reference parity: ``petastorm/tf_utils.py::make_petastorm_dataset``.
    """
    import tensorflow as tf

    if reader.ngram is not None:
        return _make_ngram_dataset(tf, reader)

    schema = reader.schema
    names, specs = _row_signature(schema, batched=reader.batched_output)
    fields = [schema.fields[n] for n in names]
    dtypes = [_field_tf_dtype(f) for f in fields]

    def generator():
        for row in reader:
            yield tuple(_coerce_value(f, getattr(row, n), d)
                        for n, f, d in zip(names, fields, dtypes))

    dataset = tf.data.Dataset.from_generator(generator,
                                             output_signature=specs)
    nt = schema._get_namedtuple()
    return dataset.map(lambda *cols: nt(*cols))


def _make_ngram_dataset(tf, reader):
    """NGram reader → dataset of {offset: namedtuple} (reference structure)."""
    ngram = reader.ngram
    offsets = sorted(ngram.fields)
    schema = reader.schema
    per_offset = []
    for offset in offsets:
        field_names = sorted(ngram.get_field_names_at_timestep(offset))
        fields = [schema.fields[n] for n in field_names]
        per_offset.append((offset, field_names, fields,
                           [_field_tf_dtype(f) for f in fields]))
    specs = tuple(
        tf.TensorSpec(shape=tuple(f.shape or ()), dtype=d,
                      name=_sanitize_field_tf_name(f"{n}_{off}"))
        for off, names_, fields_, dtypes_ in per_offset
        for n, f, d in zip(names_, fields_, dtypes_))

    def generator():
        for window in reader:
            flat = []
            for offset, names_, fields_, dtypes_ in per_offset:
                step_row = window[offset]
                flat.extend(_coerce_value(f, getattr(step_row, n), d)
                            for n, f, d in zip(names_, fields_, dtypes_))
            yield tuple(flat)

    dataset = tf.data.Dataset.from_generator(generator,
                                             output_signature=specs)

    from collections import namedtuple

    step_types = {
        offset: namedtuple(f"NGramStep_{offset}",
                           [_sanitize_field_tf_name(n) for n in names_])
        for offset, names_, _, _ in per_offset}

    def reassemble(*cols):
        out = {}
        i = 0
        for offset, names_, fields_, _ in per_offset:
            k = len(names_)
            out[offset] = step_types[offset](*cols[i:i + k])
            i += k
        return out

    return dataset.map(reassemble)


def tf_tensors(reader, shuffling_queue_capacity=0, min_after_dequeue=0):
    """One-row-at-a-time eager tensors (reference's legacy surface, TF2-ified).

    Returns an iterator yielding schema namedtuples of eager tensors; with
    ``shuffling_queue_capacity`` > 0, rows pass through ``tf.data``'s shuffle
    buffer (the TF2 equivalent of the reference's ``RandomShuffleQueue``;
    ``min_after_dequeue`` is accepted for API parity and folded into the
    buffer size).
    """
    dataset = make_petastorm_dataset(reader)
    if shuffling_queue_capacity > 0:
        dataset = dataset.shuffle(
            max(shuffling_queue_capacity, min_after_dequeue + 1))
    return iter(dataset)
