"""Disaggregated data service tests — all over 127.0.0.1.

Layers under test (docs/guides/service.md):

- the framed-socket codec (``reader_impl/framed_socket.py``) — pure wire
  format, exercised over a socketpair;
- the dispatcher's split planning (static per-client sharding, fcfs queue,
  epoch tracking, failure re-assignment) — driven through the real protocol;
- the loopback end-to-end path (ISSUE acceptance): dispatcher + 2 workers +
  1 client streaming through ``JaxDataLoader`` yields the same multiset of
  samples as a local ``make_reader`` of the same dataset;
- worker-failure handling: a fast in-process kill smoke test (tier-1) and a
  real-subprocess kill mid-epoch (``slow``) — both assert no sample loss
  under static sharding.
"""

import multiprocessing
import socket
import threading
import time

import numpy as np
import pytest

from petastorm_tpu.reader_impl.framed_socket import (
    ConnectionClosedError,
    FramedConnection,
    recv_framed,
    send_framed,
)
from petastorm_tpu.service import (
    BatchWorker,
    Dispatcher,
    ServiceBatchSource,
    ServiceError,
)

pytestmark = pytest.mark.service


# ---------------------------------------------------------------------------
# framed-socket codec
# ---------------------------------------------------------------------------

def _socketpair():
    a, b = socket.socketpair()
    return a, b


def test_framed_roundtrip_pickle_payload():
    a, b = _socketpair()
    batch = {"id": np.arange(5), "x": np.random.rand(5, 3).astype(np.float32),
             "s": np.array(["a", "bb", "ccc", "d", "e"], dtype=object)}
    send_framed(a, {"type": "batch", "rows": 5}, batch)
    header, payload = recv_framed(b)
    assert header == {"type": "batch", "rows": 5}
    np.testing.assert_array_equal(payload["id"], batch["id"])
    np.testing.assert_array_equal(payload["x"], batch["x"])
    assert list(payload["s"]) == ["a", "bb", "ccc", "d", "e"]
    a.close(), b.close()


def test_framed_roundtrip_arrow_table_payload():
    import pyarrow as pa

    a, b = _socketpair()
    table = pa.table({"c": [1, 2, 3], "d": ["x", "y", "z"]})
    send_framed(a, {"type": "batch"}, table)
    _, payload = recv_framed(b)
    assert isinstance(payload, pa.Table)
    assert payload.equals(table)
    a.close(), b.close()


def test_framed_none_payload_and_multiple_messages():
    a, b = _socketpair()
    send_framed(a, {"type": "ping"})
    send_framed(a, {"type": "ping", "n": 2})
    assert recv_framed(b) == ({"type": "ping"}, None)
    assert recv_framed(b) == ({"type": "ping", "n": 2}, None)
    a.close(), b.close()


def test_framed_peer_close_raises_connection_closed():
    a, b = _socketpair()
    a.close()
    with pytest.raises(ConnectionClosedError):
        recv_framed(b)
    b.close()


def test_buffer_pool_recycles_transient_buffers():
    from petastorm_tpu.reader_impl.framed_socket import BufferPool

    pool = BufferPool()
    buf = pool.acquire(100)
    assert len(buf) == 128  # size-classed to the next power of two
    pool.release(buf)
    assert pool.acquire(100) is buf  # recycled, not reallocated
    assert (pool.hits, pool.misses) == (1, 1)
    # Odd-sized buffers (exact-size allocations above the pooled cap) and
    # releases beyond max_buffers are dropped, not hoarded.
    small = BufferPool(max_buffers=1)
    first, second = small.acquire(64), small.acquire(64)
    small.release(first)
    small.release(second)
    assert small.acquire(64) is first
    assert small.acquire(64) is not second


def test_framed_reader_reuses_buffers_and_stays_correct():
    """Buffered receive: transient buffers recycle across messages while
    the decoded arrays (built zero-copy from out-of-band frames) stay
    intact — data frames must never land in the pool or the shared
    transit buffer's recycled region."""
    from petastorm_tpu.reader_impl.framed_socket import (BufferPool,
                                                         FramedReader)

    pool = BufferPool()
    a, b = _socketpair()
    reader = FramedReader(b, pool=pool)
    rng = np.random.RandomState(5)
    batches = [{"id": np.arange(i, i + 8),
                "x": rng.rand(8, 4).astype(np.float32)} for i in range(3)]
    received = []
    for batch in batches:
        send_framed(a, {"type": "batch"}, batch)
        header, payload = reader.recv()
        assert header == {"type": "batch"}
        received.append(payload)
    # Later messages recycled the earlier pickle heads...
    assert pool.hits > 0
    # ...and did not corrupt earlier payloads (the zero-copy invariant).
    for batch, payload in zip(batches, received):
        np.testing.assert_array_equal(payload["id"], batch["id"])
        np.testing.assert_array_equal(payload["x"], batch["x"])
    # Out-of-band reconstruction is zero-copy: the arrays view the received
    # frame buffers instead of owning a fresh copy.
    assert received[0]["x"].base is not None
    a.close(), b.close()


def test_framed_reader_interleaves_with_large_frames():
    """Messages mixing tiny and large frames (bulk frames bypass the
    transit buffer) decode correctly across several messages, including
    one large enough to exceed the reader's refill chunk. The sender runs
    on a thread: a 2x-CHUNK message overflows the socketpair buffer, so a
    same-thread send would deadlock against the recv."""
    from petastorm_tpu.reader_impl.framed_socket import FramedReader

    a, b = _socketpair()
    reader = FramedReader(b)
    rng = np.random.RandomState(9)
    big = rng.rand(FramedReader.CHUNK // 4).astype(np.float64)  # 2x CHUNK
    batches = [{"small": np.arange(3) + rep, "big": big} for rep in range(2)]

    def _send_all():
        for rep, batch in enumerate(batches):
            send_framed(a, {"rep": rep}, batch)

    sender = threading.Thread(target=_send_all, daemon=True)
    sender.start()
    for rep, batch in enumerate(batches):
        header, payload = reader.recv()
        assert header == {"rep": rep}
        np.testing.assert_array_equal(payload["small"], batch["small"])
        np.testing.assert_array_equal(payload["big"], big)
    sender.join(timeout=10)
    assert not sender.is_alive()
    a.close(), b.close()


def test_send_framed_handles_more_frames_than_iov_max():
    """A very wide schema serializes to more sendmsg iovec entries than
    IOV_MAX (1024) — the send path must slice, not fail with EMSGSIZE."""
    from petastorm_tpu.reader_impl.framed_socket import FramedReader

    a, b = _socketpair()
    wide = {f"c{i}": np.arange(4) + i for i in range(700)}  # >1400 parts
    result = {}

    def _recv():
        result["msg"] = FramedReader(b).recv()

    t = threading.Thread(target=_recv, daemon=True)
    t.start()
    send_framed(a, {"type": "batch"}, wide)
    t.join(timeout=10)
    assert not t.is_alive()
    _, payload = result["msg"]
    assert len(payload) == 700
    np.testing.assert_array_equal(payload["c699"], np.arange(4) + 699)
    a.close(), b.close()


# ---------------------------------------------------------------------------
# dispatcher control plane (driven through the real protocol)
# ---------------------------------------------------------------------------

def _register(dispatcher, worker_id, num_pieces, port=1):
    with FramedConnection.connect(dispatcher.address) as conn:
        reply, _ = conn.request({
            "type": "register_worker", "worker_id": worker_id,
            "host": "127.0.0.1", "port": port, "num_pieces": num_pieces})
    return reply


def _request(dispatcher, header):
    with FramedConnection.connect(dispatcher.address) as conn:
        reply, _ = conn.request(header)
    return reply


def test_dispatcher_static_assignment_is_disjoint_and_complete():
    with Dispatcher(port=0, mode="static", num_epochs=1).start() as disp:
        assert _register(disp, "w0", 10)["type"] == "ok"
        assert _register(disp, "w1", 10)["type"] == "ok"
        reply = _request(disp, {"type": "get_assignment", "client_id": "c",
                                "client_index": 0, "num_clients": 1,
                                "epoch": 0})
        assert reply["type"] == "assignment"
        pieces = sorted(p for ps in reply["assignments"].values() for p in ps)
        assert pieces == list(range(10))  # complete, no overlap
        assert len(reply["assignments"]) == 2  # both workers used


def test_dispatcher_static_shards_per_client():
    with Dispatcher(port=0, mode="static", num_epochs=1).start() as disp:
        _register(disp, "w0", 9)
        shards = []
        for index in range(3):
            reply = _request(disp, {
                "type": "get_assignment", "client_id": f"c{index}",
                "client_index": index, "num_clients": 3, "epoch": 0})
            shards.append(sorted(
                p for ps in reply["assignments"].values() for p in ps))
        assert shards == [[0, 3, 6], [1, 4, 7], [2, 5, 8]]


def test_dispatcher_reassigns_dead_workers_pieces_to_survivors():
    with Dispatcher(port=0, mode="static", num_epochs=1).start() as disp:
        _register(disp, "w0", 6)
        _register(disp, "w1", 6)
        reply = _request(disp, {"type": "report_failure", "client_id": "c",
                                "worker_id": "w1", "pieces": [1, 3, 5]})
        assert reply["type"] == "assignment"
        assert reply["assignments"] == {"w0": [1, 3, 5]}
        # A dead worker stops being listed and assigned.
        listed = _request(disp, {"type": "list_workers"})
        assert sorted(listed["workers"]) == ["w0"]
        # Killing the last worker leaves the service unable to progress.
        reply = _request(disp, {"type": "report_failure", "client_id": "c",
                                "worker_id": "w0", "pieces": [0]})
        assert reply["type"] == "error"


def test_dispatcher_rejects_mismatched_piece_counts():
    with Dispatcher(port=0, mode="static", num_epochs=1).start() as disp:
        _register(disp, "w0", 6)
        reply = _register(disp, "w1", 7)
        assert reply["type"] == "error"
        assert "6" in reply["error"] and "7" in reply["error"]


def test_dispatcher_fcfs_queue_and_epoch_refill():
    with Dispatcher(port=0, mode="fcfs", num_epochs=2).start() as disp:
        _register(disp, "w0", 3)
        seen = []
        while True:
            reply = _request(disp, {"type": "next_split", "client_id": "c"})
            if reply["type"] == "end_of_stream":
                assert reply["epochs_completed"] == 2
                break
            seen.append((reply["epoch"], reply["piece"]))
        # Two full epochs, each covering every piece exactly once.
        assert [p for e, p in seen if e == 0] == [0, 1, 2]
        assert [p for e, p in seen if e == 1] == [0, 1, 2]


def test_dispatcher_mode_mismatch_and_unknown_requests_error():
    with Dispatcher(port=0, mode="fcfs", num_epochs=1).start() as disp:
        _register(disp, "w0", 3)
        assert _request(disp, {"type": "get_assignment", "client_id": "c",
                               "client_index": 0, "num_clients": 1,
                               "epoch": 0})["type"] == "error"
        assert _request(disp, {"type": "bogus"})["type"] == "error"
        status = _request(disp, {"type": "status"})
        assert status["mode"] == "fcfs"
        assert status["num_pieces"] == 3


# ---------------------------------------------------------------------------
# loopback end-to-end (the ISSUE acceptance path)
# ---------------------------------------------------------------------------

def _local_ids(url, **kwargs):
    from petastorm_tpu import make_reader

    with make_reader(url, num_epochs=1, shuffle_row_groups=False,
                     workers_count=2, **kwargs) as reader:
        return sorted(int(row.id) for row in reader)


def _service_fleet(url, mode="static", num_epochs=1, n_workers=2,
                   batch_size=7, reader_factory="row"):
    dispatcher = Dispatcher(port=0, mode=mode, num_epochs=num_epochs).start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=batch_size, reader_factory=reader_factory,
                    worker_id=f"w{i}",
                    reader_kwargs={"workers_count": 2}).start()
        for i in range(n_workers)]
    return dispatcher, workers


def test_loopback_static_matches_local_reader(petastorm_dataset):
    """Dispatcher + 2 workers + 1 client over 127.0.0.1 yields the same
    multiset of samples as a local make_reader (order-independent)."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        loader = JaxDataLoader(None, 7, batch_source=source,
                               stage_to_device=False)
        got = []
        with loader:
            for batch in loader:
                got.extend(int(i) for i in batch["id"])
        assert sorted(got) == _local_ids(petastorm_dataset.url)
        assert loader.diagnostics["rows"] == len(got)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_loopback_fcfs_matches_local_reader(petastorm_dataset):
    dispatcher, workers = _service_fleet(petastorm_dataset.url, mode="fcfs")
    try:
        source = ServiceBatchSource(dispatcher.address)
        got = [int(i) for batch in source() for i in batch["id"]]
        assert sorted(got) == _local_ids(petastorm_dataset.url)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_loopback_two_static_clients_split_the_dataset(petastorm_dataset):
    """Two clients with disjoint static shards cover the dataset exactly."""
    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        ids = []
        for index in range(2):
            source = ServiceBatchSource(dispatcher.address,
                                        client_index=index, num_clients=2)
            ids.append(sorted(
                int(i) for batch in source() for i in batch["id"]))
        assert not set(ids[0]) & set(ids[1])
        assert sorted(ids[0] + ids[1]) == _local_ids(petastorm_dataset.url)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_loopback_multi_epoch_static(petastorm_dataset):
    dispatcher, workers = _service_fleet(petastorm_dataset.url, num_epochs=2)
    try:
        source = ServiceBatchSource(dispatcher.address)
        got = [int(i) for batch in source() for i in batch["id"]]
        assert sorted(got) == sorted(_local_ids(petastorm_dataset.url) * 2)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_remote_diagnostics_surface_reader_snapshots(petastorm_dataset):
    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        for _ in source():
            pass
        diag = source.remote_diagnostics()
        assert sorted(diag) == ["w0", "w1"]
        for snapshot in diag.values():
            assert snapshot["num_pieces"] == 3
            # Streams finished: their final Reader.diagnostics are retained.
            assert snapshot["completed_streams"]
            finished = next(iter(snapshot["completed_streams"].values()))
            assert "rowgroups_total" in finished
        status = source.dispatcher_status()
        assert status["type"] == "status"
        assert sorted(status["workers"]) == ["w0", "w1"]
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

def test_loader_state_dict_delegates_to_service_source(petastorm_dataset):
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        loader = JaxDataLoader(None, 7, batch_source=source,
                               stage_to_device=False)
        with loader:
            for _ in loader:
                pass
        state = loader.state_dict()
        assert state["mode"] == "static"
        assert state["epoch"] == 1  # the epoch now in progress
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_loader_state_dict_still_raises_without_source_support():
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    loader = JaxDataLoader(None, 4, batch_source=lambda: iter(()),
                           stage_to_device=False)
    with pytest.raises(ValueError, match="batch_source"):
        loader.state_dict()


@pytest.mark.parametrize("transport", ["tcp", "shm"])
def test_resume_skips_completed_pieces(petastorm_dataset, transport):
    """A snapshot naming completed pieces resumes without re-reading them
    — on either delivery tier (watermark resume is transport-invariant;
    docs/guides/service.md#transport-tiers)."""
    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        # Dataset has 3 row groups of 10 rows; claim piece 0 completed.
        state = {"version": 1, "mode": "static", "client_index": 0,
                 "num_clients": 1, "epoch": 0, "completed_pieces": [0]}
        source = ServiceBatchSource(dispatcher.address, resume_state=state,
                                    transport=transport)
        got = [int(i) for batch in source() for i in batch["id"]]
        expected = [i for i in _local_ids(petastorm_dataset.url) if i >= 10]
        assert sorted(got) == expected
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_resume_state_validation():
    state = {"version": 1, "mode": "static", "client_index": 1,
             "num_clients": 2, "epoch": 0, "completed_pieces": []}
    with pytest.raises(ValueError, match="client_index"):
        ServiceBatchSource(("127.0.0.1", 1), client_index=0, num_clients=2,
                           resume_state=state)
    with pytest.raises(ValueError, match="version"):
        ServiceBatchSource(("127.0.0.1", 1),
                           resume_state={"version": 9, "mode": "static"})


def test_fcfs_state_dict_raises(petastorm_dataset):
    dispatcher, workers = _service_fleet(petastorm_dataset.url, mode="fcfs")
    try:
        source = ServiceBatchSource(dispatcher.address)
        for _ in source():
            break
        with pytest.raises(ValueError, match="fcfs"):
            source.state_dict()
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_fcfs_rejects_resume_state(petastorm_dataset):
    """A static-mode snapshot fed to an fcfs dispatcher must error, not
    silently re-stream the whole dataset (duplicating trained data)."""
    dispatcher, workers = _service_fleet(petastorm_dataset.url, mode="fcfs")
    try:
        state = {"version": 1, "mode": "static", "client_index": 0,
                 "num_clients": 1, "epoch": 1, "completed_pieces": [0]}
        source = ServiceBatchSource(dispatcher.address, resume_state=state)
        with pytest.raises(ValueError, match="fcfs"):
            source()
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


# ---------------------------------------------------------------------------
# worker failure (fast in-process smoke — tier-1)
# ---------------------------------------------------------------------------

def test_worker_kill_mid_epoch_loses_no_samples(tmp_path):
    """Kill one of two workers after the first batches flow; the client
    reconnects, reports the failure, and the dispatcher's re-assignment
    finishes the epoch with every sample delivered (duplicates allowed —
    at-least-once)."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/ds"
    rows = create_test_scalar_dataset(url, rows_count=60,
                                      rows_per_row_group=5)  # 12 row groups
    dispatcher, workers = _service_fleet(url, batch_size=4,
                                         reader_factory="batch")
    try:
        source = ServiceBatchSource(dispatcher.address, max_retries=2,
                                    backoff_base=0.02, backoff_max=0.1)
        got, killed = [], False
        for batch in source():
            got.extend(int(i) for i in batch["id"])
            if not killed and len(got) >= 8:
                workers[1].kill()
                killed = True
        assert killed, "dataset too small to kill mid-epoch"
        assert set(int(r["id"]) for r in rows) <= set(got)  # no sample loss
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_credit_window_respected(petastorm_dataset):
    """Flow-control smoke (tier-1): a worker never has more than ``credits``
    un-acked batches in flight — it blocks out of credits and resumes per
    replenishment message."""
    worker = BatchWorker(petastorm_dataset.url, batch_size=4,
                         reader_kwargs={"workers_count": 2}).start()
    sock = None
    try:
        sock = socket.create_connection(worker.address, timeout=5)
        send_framed(sock, {"type": "stream", "pieces": [0, 1, 2],
                           "epoch": 0, "credits": 2})
        for _ in range(2):
            header, _ = recv_framed(sock)
            assert header["type"] == "batch"
        # Window exhausted: the worker must NOT send a third batch.
        sock.settimeout(0.5)
        with pytest.raises(socket.timeout):
            recv_framed(sock)
        # One credit buys exactly one more batch.
        send_framed(sock, {"type": "credit", "n": 1})
        sock.settimeout(5)
        header, _ = recv_framed(sock)
        assert header["type"] == "batch"
        sock.settimeout(0.5)
        with pytest.raises(socket.timeout):
            recv_framed(sock)
    finally:
        if sock is not None:
            sock.close()
        worker.stop()


def test_stream_without_credits_is_unbounded(petastorm_dataset):
    """A pre-credit client (no ``credits`` in the stream request) still gets
    the full unbounded push — protocol backward compatibility."""
    worker = BatchWorker(petastorm_dataset.url, batch_size=10,
                         reader_kwargs={"workers_count": 2}).start()
    try:
        with FramedConnection.connect(worker.address, timeout=5) as conn:
            conn.send({"type": "stream", "pieces": [0, 1, 2], "epoch": 0})
            kinds = []
            while True:
                header, _ = conn.recv()
                kinds.append(header["type"])
                if header["type"] == "end":
                    break
        assert kinds == ["batch"] * 3 + ["end"]  # all batches, no blocking
    finally:
        worker.stop()


def test_stream_end_mid_epoch_never_skips_or_double_counts(petastorm_dataset):
    """Regression for the old drain's cycle-rebuild on stream removal: as
    streams end at different times mid-epoch, completion bookkeeping must
    record every piece exactly once per epoch, at non-decreasing production
    counts — nothing skipped, nothing double-counted."""
    dispatcher, workers = _service_fleet(petastorm_dataset.url,
                                         num_epochs=2)
    try:
        source = ServiceBatchSource(dispatcher.address)
        got = [int(i) for batch in source() for i in batch["id"]]
        assert sorted(got) == sorted(_local_ids(petastorm_dataset.url) * 2)
        events = source._events
        for epoch in (0, 1):
            pieces = sorted(p for _, event_epoch, ps in events
                            for p in ps if event_epoch == epoch)
            assert pieces == [0, 1, 2]  # exactly once each
        counts = [count for count, _, _ in events]
        assert counts == sorted(counts)  # production counts never regress
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_source_and_loader_surface_flow_diagnostics(petastorm_dataset):
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        loader = JaxDataLoader(None, 7, batch_source=source,
                               stage_to_device=False)
        with loader:
            for _ in loader:
                pass
        diag = source.diagnostics
        assert diag["credits_window"] == 8
        assert diag["ready_queue_depth"] == 0  # drained and torn down
        per_worker = diag["per_worker"]
        assert sorted(per_worker) == ["w0", "w1"]
        for counters in per_worker.values():
            assert counters["batches"] > 0
            assert counters["stall_s"] >= 0
            assert counters["credits_outstanding"] == 0  # all consumed
        assert (sum(c["batches"] for c in per_worker.values())
                == loader.diagnostics["batches"])
        # The loader snapshots the source's counters into its own stage
        # breakdown — one dict root-causes the whole delivery path.
        assert loader.diagnostics["source"]["per_worker"] == per_worker
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_loader_reiteration_closes_stale_direct_source(petastorm_dataset):
    """Re-iterating the loader mid-epoch on the direct (prefetched-source)
    path must tear down the first drain's reader threads before the fresh
    iteration resets the source's bookkeeping — and the abandoned first
    iterator must not break the live one."""
    from petastorm_tpu.jax_utils.loader import JaxDataLoader

    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        loader = JaxDataLoader(None, 7, batch_source=source,
                               stage_to_device=False)
        it1 = iter(loader)
        next(it1)  # first drain live, readers running
        got = [int(i) for batch in loader for i in batch["id"]]
        assert sorted(got) == _local_ids(petastorm_dataset.url)
        # The superseded iterator winds down cleanly (its source generator
        # was closed by the re-iteration): it may flush batches it had
        # already prefetched, then ends without raising.
        list(it1)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_dispatcher_worker_diagnostics_passthrough(petastorm_dataset):
    """One ``worker_diagnostics`` request against the dispatcher aggregates
    every live worker's diagnostics (reader counters + flow-control state)."""
    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        for _ in source():
            pass
        with FramedConnection.connect(dispatcher.address) as conn:
            reply, payload = conn.request({"type": "worker_diagnostics"})
        assert reply["type"] == "diagnostics"
        assert sorted(payload) == ["w0", "w1"]
        for snapshot in payload.values():
            assert snapshot["completed_streams"]
            finished = next(iter(snapshot["completed_streams"].values()))
            assert finished["credits_window"] == 8
            assert finished["batches_sent"] > 0
            assert "rowgroups_total" in finished  # reader counters merged
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


@pytest.mark.slow
def test_skewed_worker_does_not_head_of_line_block(tmp_path):
    """One of two workers delayed per batch: the client must keep yielding
    the fast worker's batches instead of serializing them behind the slow
    stream (the failure mode of the old blocking round-robin drain)."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/ds"
    create_test_scalar_dataset(url, rows_count=60,
                               rows_per_row_group=5)  # 12 row groups
    delay_s = 0.3
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=1).start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=5, reader_factory="batch", worker_id=f"w{i}",
                    batch_delay_s=(delay_s if i == 0 else 0.0),
                    reader_kwargs={"workers_count": 2}).start()
        for i in range(2)]
    try:
        source = ServiceBatchSource(dispatcher.address)
        # Piece plan: sorted worker ids, round-robin → w0 (slow) serves the
        # even pieces, w1 (fast) the odd ones; row ids of piece p are
        # [5p, 5p+5), so a batch's origin is identifiable from its ids.
        fast_rows = {i for p in range(1, 12, 2) for i in range(5 * p, 5 * p + 5)}
        t0 = time.perf_counter()
        yielded = []  # (elapsed_s, is_fast)
        for batch in source():
            ids = [int(i) for i in batch["id"]]
            yielded.append((time.perf_counter() - t0,
                            all(i in fast_rows for i in ids)))
        fast_done_at = max(t for t, is_fast in yielded if is_fast)
        # The fast worker's 6 batches arrive while the slow worker is still
        # sleeping off its first deliveries — well before the ~1.8s the
        # slow stream needs. The old drain interleaved them 1:1, pushing
        # the last fast batch past ~5 slow periods (~1.5s).
        assert fast_done_at < 3 * delay_s, (
            f"fast worker's batches head-of-line blocked: last arrived at "
            f"{fast_done_at:.2f}s (yields: {yielded})")
        # Interleaving, not starvation: most of the first half of the
        # delivery order is fast-worker batches.
        first_half = [is_fast for _, is_fast in yielded[:6]]
        assert sum(first_half) >= 4
        # The slow worker's stall is visible per worker, attributed to w0.
        per_worker = source.diagnostics["per_worker"]
        assert per_worker["w0"]["stall_s"] > per_worker["w1"]["stall_s"]
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


@pytest.mark.slow
def test_recovery_does_not_block_survivor_delivery(tmp_path):
    """Retry/takeover of a dead worker runs off the consumer thread: while
    the client sits out the reconnect backoff (>= 0.9s with these knobs —
    jitter only lengthens it), the survivor's batches must keep flowing."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/ds"
    rows = create_test_scalar_dataset(url, rows_count=120,
                                      rows_per_row_group=5)  # 24 pieces
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=1).start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=5, reader_factory="batch", worker_id=f"w{i}",
                    batch_delay_s=0.05,  # both paced: batches keep coming
                    reader_kwargs={"workers_count": 2}).start()
        for i in range(2)]
    try:
        source = ServiceBatchSource(dispatcher.address, max_retries=2,
                                    backoff_base=0.4, backoff_max=0.5)
        got, killed_at, post_kill = [], None, []
        for batch in source():
            now = time.perf_counter()
            got.extend(int(i) for i in batch["id"])
            if killed_at is None and len(got) >= 10:
                workers[0].kill()
                killed_at = time.perf_counter()
            elif killed_at is not None:
                post_kill.append(now - killed_at)
        assert killed_at is not None
        # Recovery's backoff alone sleeps >= 0.9s; a blocking drain would
        # yield nothing in that window. The survivor delivers throughout.
        early = [t for t in post_kill if t < 0.7]
        assert len(early) >= 2, (
            f"no survivor delivery during recovery: {post_kill[:6]}")
        assert set(int(r["id"]) for r in rows) <= set(got)  # no loss
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


@pytest.mark.slow
def test_worker_kill_under_skew_loses_no_samples(tmp_path):
    """Takeover still at-least-once under the multiplexed drain with skew in
    the fleet: kill the slow worker mid-epoch; the survivors re-serve its
    pieces and no sample is lost."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/ds"
    rows = create_test_scalar_dataset(url, rows_count=60,
                                      rows_per_row_group=5)
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=1).start()
    workers = [
        BatchWorker(url, dispatcher_address=dispatcher.address,
                    batch_size=4, reader_factory="batch", worker_id=f"w{i}",
                    batch_delay_s=(0.1 if i == 0 else 0.0),
                    reader_kwargs={"workers_count": 2}).start()
        for i in range(2)]
    try:
        source = ServiceBatchSource(dispatcher.address, max_retries=1,
                                    backoff_base=0.02, backoff_max=0.1)
        got, killed = [], False
        for batch in source():
            got.extend(int(i) for i in batch["id"])
            if not killed and len(got) >= 8:
                workers[0].kill()  # the slow one
                killed = True
        assert killed
        assert set(int(r["id"]) for r in rows) <= set(got)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_error_streams_surface_as_service_error(petastorm_dataset):
    """A deterministic worker-side failure (bad piece plan) is an error
    reply, not a reconnect loop."""
    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        with FramedConnection.connect(workers[0].address) as conn:
            conn.send({"type": "stream", "pieces": [99], "epoch": 0})
            header, _ = conn.recv()
        assert header["type"] == "error"
        assert "99" in header["error"]
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


# ---------------------------------------------------------------------------
# worker failure (real subprocess kill — slow)
# ---------------------------------------------------------------------------

def _run_worker_process(dataset_url, dispatcher_address, worker_id):
    """Child-process entry: serve until killed."""
    worker = BatchWorker(dataset_url, dispatcher_address=dispatcher_address,
                         batch_size=4, reader_factory="batch",
                         worker_id=worker_id,
                         reader_kwargs={"workers_count": 2})
    worker.start()
    threading.Event().wait()  # until SIGKILL


@pytest.mark.slow
def test_subprocess_worker_sigkill_mid_epoch_loses_no_samples(tmp_path):
    """Fault injection with a real process death (SIGKILL, no FIN handshake
    from the worker's streams beyond what the kernel sends): the epoch still
    completes with no sample loss under static sharding."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/ds"
    rows = create_test_scalar_dataset(url, rows_count=120,
                                      rows_per_row_group=5)  # 24 row groups
    dispatcher = Dispatcher(port=0, mode="static", num_epochs=1).start()
    local = BatchWorker(url, dispatcher_address=dispatcher.address,
                        batch_size=4, reader_factory="batch",
                        worker_id="local",
                        reader_kwargs={"workers_count": 2}).start()
    ctx = multiprocessing.get_context("spawn")
    child = ctx.Process(target=_run_worker_process,
                        args=(url, dispatcher.address, "child"), daemon=True)
    child.start()
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with FramedConnection.connect(dispatcher.address) as conn:
                reply, _ = conn.request({"type": "list_workers"})
            if sorted(reply["workers"]) == ["child", "local"]:
                break
            time.sleep(0.1)
        else:
            pytest.fail("child worker never registered")

        source = ServiceBatchSource(dispatcher.address, max_retries=2,
                                    backoff_base=0.02, backoff_max=0.2)
        got, killed = [], False
        for batch in source():
            got.extend(int(i) for i in batch["id"])
            if not killed and len(got) >= 12:
                child.kill()
                killed = True
        assert killed
        assert set(int(r["id"]) for r in rows) <= set(got)
    finally:
        child.kill()
        child.join(timeout=10)
        local.stop()
        dispatcher.stop()


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def test_service_cli_parse_address():
    from petastorm_tpu.service.cli import parse_address

    assert parse_address("10.0.0.1:7077") == ("10.0.0.1", 7077)
    assert parse_address("7077") == ("127.0.0.1", 7077)


def test_service_cli_runs_dispatcher_and_worker(petastorm_dataset, capsys):
    import json

    from petastorm_tpu.service.cli import main

    ready = {}
    stop = threading.Event()  # tears both nodes down at test end (no

    def run_dispatcher():     # leaked listeners past teardown)
        main(["dispatcher", "--port", "0", "--mode", "static"],
             run_seconds=30, stop_event=stop)

    disp_thread = threading.Thread(target=run_dispatcher, daemon=True)
    disp_thread.start()
    worker_thread = None
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and "port" not in ready:
            out = capsys.readouterr().out
            for line in out.splitlines():
                if line.startswith("{"):
                    ready.update(json.loads(line))
            time.sleep(0.05)
        assert ready.get("role") == "dispatcher"

        worker_thread = threading.Thread(
            target=lambda: main(
                ["worker", "--dispatcher", f"127.0.0.1:{ready['port']}",
                 "--dataset-url", petastorm_dataset.url, "--batch-size", "7",
                 "--workers-count", "2"],
                run_seconds=30, stop_event=stop),
            daemon=True)
        worker_thread.start()

        source = ServiceBatchSource(("127.0.0.1", ready["port"]),
                                    max_retries=8,
                                    backoff_base=0.1, backoff_max=0.5)

        # The worker registers asynchronously; retry until the fleet serves.
        deadline = time.monotonic() + 8
        got = []
        while time.monotonic() < deadline:
            try:
                got = [int(i) for batch in source() for i in batch["id"]]
                if got:
                    break
            except ServiceError:
                time.sleep(0.2)
        assert sorted(got) == _local_ids(petastorm_dataset.url)
    finally:
        stop.set()
        disp_thread.join(timeout=10)
        if worker_thread is not None:
            worker_thread.join(timeout=10)


def test_state_dict_respects_consumer_yield_position(petastorm_dataset):
    """Completion is computed relative to what the consumer actually
    yielded: batches still in a prefetch queue keep their pieces
    un-completed, so a resume re-reads them (at-least-once, never loss)."""
    dispatcher, workers = _service_fleet(petastorm_dataset.url)
    try:
        source = ServiceBatchSource(dispatcher.address)
        total_batches = sum(1 for _ in source())
        all_pieces = {0, 1, 2}
        # Nothing yielded yet → nothing completed, epoch still 0.
        s0 = source.state_dict(yielded_batches=0)
        assert (s0["epoch"], s0["completed_pieces"]) == (0, [])
        # One batch short of everything → at most a strict subset completed.
        s_mid = source.state_dict(yielded_batches=total_batches - 1)
        assert s_mid["epoch"] == 0
        assert set(s_mid["completed_pieces"]) < all_pieces
        # Everything yielded → the epoch is done; next epoch, clean slate.
        s_end = source.state_dict(yielded_batches=total_batches)
        assert (s_end["epoch"], s_end["completed_pieces"]) == (1, [])
        # Default (no consumer info) equals the fully-yielded snapshot —
        # exact for direct iteration, where produced == consumed.
        assert source.state_dict() == s_end
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()


def test_worker_rejects_split_planning_reader_kwargs(petastorm_dataset):
    """Sharding/selector kwargs would silently disagree with the
    dispatcher's piece universe — rejected at construction."""
    for bad in ({"cur_shard": 0, "shard_count": 2},
                {"rowgroup_selector": object()},
                {"piece_indices": [0]}):
        with pytest.raises(ValueError, match="split protocol"):
            BatchWorker(petastorm_dataset.url, reader_kwargs=bad)


def test_fcfs_worker_kill_loses_no_samples(tmp_path):
    """fcfs failure path: retry the worker with backoff, then flag it and
    serve the split from a surviving worker — no sample loss."""
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_scalar_dataset,
    )

    url = f"file://{tmp_path}/ds"
    rows = create_test_scalar_dataset(url, rows_count=60,
                                      rows_per_row_group=5)
    dispatcher, workers = _service_fleet(url, mode="fcfs", batch_size=4,
                                         reader_factory="batch")
    try:
        source = ServiceBatchSource(dispatcher.address, max_retries=1,
                                    backoff_base=0.02, backoff_max=0.05)
        got, killed = [], False
        for batch in source():
            got.extend(int(i) for i in batch["id"])
            if not killed and len(got) >= 8:
                workers[0].kill()
                killed = True
        assert killed
        assert set(int(r["id"]) for r in rows) <= set(got)
    finally:
        for w in workers:
            w.stop()
        dispatcher.stop()
