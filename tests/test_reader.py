"""Reader behavioral suite, parametrized across pools × reader kinds.

Mirrors the reference's ``test_end_to_end.py`` / ``test_reader.py`` shape
(SURVEY.md §4): every row seen exactly once per epoch, epochs, predicates,
sharding partitions the dataset, shuffling changes order, transform specs.
"""

import numpy as np
import pytest

from petastorm_tpu.errors import NoDataAvailableError
from petastorm_tpu.ngram import NGram
from petastorm_tpu.predicates import in_lambda, in_pseudorandom_split, in_set
from petastorm_tpu.reader import make_batch_reader, make_reader
from petastorm_tpu.schema.transform import TransformSpec
from petastorm_tpu.test_util.shuffling_analysis import (
    compute_correlation_distance_metric,
)

# 'process' is exercised in the dedicated tests below (startup is ~2s/pool);
# the full matrix runs on thread + dummy.
POOLS = ["thread", "dummy"]


def _collect_ids(reader):
    return [row.id for row in reader]


def _collect_batch_ids(reader):
    ids = []
    for batch in reader:
        ids.extend(batch.id.tolist())
    return ids


@pytest.mark.parametrize("pool", POOLS)
def test_all_rows_exactly_once(petastorm_dataset, pool):
    with make_reader(petastorm_dataset.url, reader_pool_type=pool,
                     workers_count=3) as reader:
        ids = _collect_ids(reader)
    assert sorted(ids) == list(range(30))


@pytest.mark.parametrize("pool", POOLS)
def test_full_row_contents_roundtrip(petastorm_dataset, pool):
    with make_reader(petastorm_dataset.url, reader_pool_type=pool,
                     workers_count=2, shuffle_row_groups=False) as reader:
        rows = {row.id: row for row in reader}
    for source in petastorm_dataset.rows:
        row = rows[source["id"]]
        assert np.array_equal(row.image_png, source["image_png"])
        assert np.array_equal(row.matrix, source["matrix"])
        assert row.decimal == source["decimal"]
        assert row.string_value == source["string_value"]
        if source["matrix_nullable"] is None:
            assert row.matrix_nullable is None
        else:
            assert np.array_equal(row.matrix_nullable, source["matrix_nullable"])


def test_num_epochs(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="thread",
                     num_epochs=3) as reader:
        ids = _collect_ids(reader)
    assert len(ids) == 90
    assert sorted(set(ids)) == list(range(30))
    assert all(ids.count(i) == 3 for i in range(30))


def test_infinite_epochs_stop(petastorm_dataset):
    reader = make_reader(petastorm_dataset.url, reader_pool_type="thread",
                         num_epochs=None)
    taken = [next(reader).id for _ in range(100)]
    assert len(taken) == 100
    reader.stop()
    reader.join()


def test_sharding_partitions_dataset(petastorm_dataset):
    seen = []
    for shard in range(3):
        with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         cur_shard=shard, shard_count=3,
                         shuffle_row_groups=False) as reader:
            seen.append(set(_collect_ids(reader)))
    assert set.union(*seen) == set(range(30))
    for a in range(3):
        for b in range(a + 1, 3):
            assert not (seen[a] & seen[b])


def test_sharding_validations(petastorm_dataset):
    with pytest.raises(ValueError, match="together"):
        make_reader(petastorm_dataset.url, cur_shard=0)
    with pytest.raises(ValueError, match="out of range"):
        make_reader(petastorm_dataset.url, cur_shard=5, shard_count=3)


def test_shuffling_changes_order(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     shuffle_row_groups=False) as reader:
        ordered = _collect_ids(reader)
    assert ordered == sorted(ordered)
    metric_ordered = compute_correlation_distance_metric(ordered)
    assert metric_ordered == 0.0
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     shuffle_row_groups=True,
                     shuffle_row_drop_partitions=2) as reader:
        shuffled = _collect_ids(reader)
    assert sorted(shuffled) == sorted(ordered)
    assert compute_correlation_distance_metric(shuffled) > 0.05


def test_shuffle_row_drop_partitions_sees_all_rows(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="thread",
                     shuffle_row_drop_partitions=3) as reader:
        ids = _collect_ids(reader)
    assert sorted(ids) == list(range(30))


def test_schema_fields_view(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     schema_fields=["id", "string_value"]) as reader:
        row = next(reader)
    assert row._fields == ("id", "string_value")


def test_schema_fields_regex(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     schema_fields=["id.*"]) as reader:
        row = next(reader)
    assert set(row._fields) == {"id", "id2"}


def test_predicate_filters_rows(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="thread",
                     predicate=in_set({3, 7, 11}, "id")) as reader:
        ids = _collect_ids(reader)
    assert sorted(ids) == [3, 7, 11]


def test_predicate_on_field_outside_view(petastorm_dataset):
    """Predicate fields need not be part of the returned schema view."""
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     schema_fields=["string_value"],
                     predicate=in_lambda(["id"], lambda v: v["id"] < 5)) as reader:
        rows = list(reader)
    assert len(rows) == 5
    assert all(r._fields == ("string_value",) for r in rows)


def test_pseudorandom_split_deterministic_partition(petastorm_dataset):
    subsets = []
    for index in range(2):
        with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         predicate=in_pseudorandom_split([0.5, 0.5], index, "id")
                         ) as reader:
            subsets.append(set(_collect_ids(reader)))
    assert subsets[0] | subsets[1] == set(range(30))
    assert not (subsets[0] & subsets[1])
    # deterministic: rerun gives the identical split
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     predicate=in_pseudorandom_split([0.5, 0.5], 0, "id")
                     ) as reader:
        assert set(_collect_ids(reader)) == subsets[0]


def test_predicate_removing_everything_still_terminates(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="thread",
                     predicate=in_set(set(), "id")) as reader:
        assert list(reader) == []


def test_transform_spec_row_path(petastorm_dataset):
    def double_matrix(row):
        row["matrix"] = row["matrix"] * 2
        return row

    spec = TransformSpec(double_matrix)
    with make_reader(petastorm_dataset.url, reader_pool_type="thread",
                     shuffle_row_groups=False, transform_spec=spec) as reader:
        rows = {r.id: r for r in reader}
    for source in petastorm_dataset.rows[:5]:
        assert np.allclose(rows[source["id"]].matrix, source["matrix"] * 2)


def test_transform_spec_removes_and_adds_fields(petastorm_dataset):
    def add_norm(row):
        row["norm"] = np.float64(np.linalg.norm(row["matrix"]))
        del row["matrix"]
        return row

    spec = TransformSpec(add_norm,
                         edit_fields=[("norm", np.float64, (), False)],
                         removed_fields=["matrix"])
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     schema_fields=["id", "matrix"],
                     transform_spec=spec) as reader:
        row = next(reader)
    assert set(row._fields) == {"id", "norm"}
    assert isinstance(row.norm, float)


def test_reset_after_exhaustion(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="thread") as reader:
        first = _collect_ids(reader)
        with pytest.raises(StopIteration):
            next(reader)
        reader.reset()
        second = _collect_ids(reader)
    assert sorted(first) == sorted(second) == list(range(30))


def test_reset_mid_epoch_raises(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="thread") as reader:
        next(reader)
        with pytest.raises(NotImplementedError):
            reader.reset()


def test_make_reader_on_plain_parquet_raises_pointed_error(scalar_dataset):
    with pytest.raises(RuntimeError, match="make_batch_reader"):
        make_reader(scalar_dataset.url)


def test_ngram_reader(petastorm_dataset):
    fields = {
        0: ["id", "sensor_name"],
        1: ["id"],
    }
    ngram = NGram(fields, delta_threshold=1, timestamp_field="timestamp_s")
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     schema_fields=ngram, shuffle_row_groups=False) as reader:
        windows = list(reader)
    # 3 row groups x 10 rows: 9 windows per group (consecutive timestamps)
    assert len(windows) == 27
    for window in windows:
        assert set(window.keys()) == {0, 1}
        assert window[1].id == window[0].id + 1
        assert hasattr(window[0], "sensor_name")
        assert not hasattr(window[1], "sensor_name")


# ---- make_batch_reader ---------------------------------------------------

@pytest.mark.parametrize("pool", POOLS)
def test_batch_reader_all_rows(scalar_dataset, pool):
    with make_batch_reader(scalar_dataset.url, reader_pool_type=pool) as reader:
        assert reader.batched_output
        ids = _collect_batch_ids(reader)
    assert sorted(ids) == list(range(30))


def test_batch_reader_columns_and_dtypes(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type="dummy",
                           shuffle_row_groups=False) as reader:
        batch = next(reader)
    assert batch.id.dtype == np.int64
    assert batch.float_col.dtype == np.float64
    assert batch.int_col.dtype == np.int32
    assert list(batch.string_col[:2]) == ["value_0", "value_1"]


def test_batch_reader_predicate(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type="thread",
                           predicate=in_lambda(["id"], lambda v: v["id"] % 2 == 0)
                           ) as reader:
        ids = _collect_batch_ids(reader)
    assert sorted(ids) == list(range(0, 30, 2))


def test_batch_reader_transform_spec_pandas(scalar_dataset):
    def scale(frame):
        frame["float_col"] = frame["float_col"] * 10
        return frame

    with make_batch_reader(scalar_dataset.url, reader_pool_type="thread",
                           shuffle_row_groups=False,
                           transform_spec=TransformSpec(scale)) as reader:
        batch = next(reader)
    np.testing.assert_allclose(batch.float_col, batch.id * 15.0)


def test_batch_reader_schema_fields(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type="dummy",
                           schema_fields=["id", "string_col"]) as reader:
        batch = next(reader)
    assert set(batch._fields) == {"id", "string_col"}


def test_batch_reader_on_petastorm_dataset_reads_storage(petastorm_dataset):
    """Reference parity: batch reader treats a petastorm store as plain
    Parquet (codec columns come back as raw encoded bytes)."""
    with make_batch_reader(petastorm_dataset.url, reader_pool_type="dummy",
                           schema_fields=["id", "image_png"]) as reader:
        batch = next(reader)
    assert isinstance(batch.image_png[0], bytes)
    assert batch.image_png[0][:8] == b"\x89PNG\r\n\x1a\n"


def test_batch_reader_filters_pushdown(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type="dummy",
                           filters=[("id", ">=", 20)]) as reader:
        ids = _collect_batch_ids(reader)
    # statistics-level pruning: only the last row group (ids 20..29) survives
    assert sorted(ids) == list(range(20, 30))


def test_filters_on_make_reader(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                     filters=[("id", "<", 10)]) as reader:
        ids = _collect_ids(reader)
    assert sorted(ids) == list(range(10))


def test_no_data_after_filtering_raises(scalar_dataset):
    with pytest.raises(NoDataAvailableError):
        make_batch_reader(scalar_dataset.url, filters=[("id", ">", 10_000)])


# ---- process pool end-to-end (one test per reader kind; startup is slow) --

def test_process_pool_make_reader(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="process",
                     workers_count=2) as reader:
        ids = _collect_ids(reader)
    assert sorted(ids) == list(range(30))


def test_process_pool_batch_reader_arrow_ipc(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, reader_pool_type="process",
                           workers_count=2) as reader:
        ids = _collect_batch_ids(reader)
    assert sorted(ids) == list(range(30))


# ---------------------------------------------------------------------------
# explicit split plans (piece_indices — the data service's planning hook)
# ---------------------------------------------------------------------------

def test_piece_indices_selects_row_groups(petastorm_dataset):
    # 30 rows in 3 row groups of 10: piece k holds ids [10k, 10k+10).
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     shuffle_row_groups=False, piece_indices=[0, 2]) as reader:
        ids = sorted(_collect_ids(reader))
    assert ids == list(range(10)) + list(range(20, 30))


def test_piece_indices_validates_range(petastorm_dataset):
    with pytest.raises(ValueError, match="out of range"):
        make_reader(petastorm_dataset.url, piece_indices=[0, 7])


def test_piece_indices_partition_is_disjoint_and_complete(petastorm_dataset):
    """Readers over a partition of piece indices jointly see every row
    exactly once — the invariant the service's dispatcher relies on."""
    ids = []
    for plan in ([0], [1], [2]):
        with make_reader(petastorm_dataset.url, num_epochs=1,
                         shuffle_row_groups=False,
                         piece_indices=plan) as reader:
            ids.extend(_collect_ids(reader))
    assert sorted(ids) == list(range(30))


def test_piece_indices_batch_reader(scalar_dataset):
    with make_batch_reader(scalar_dataset.url, num_epochs=1,
                           shuffle_row_groups=False,
                           piece_indices=[1]) as reader:
        ids = sorted(_collect_batch_ids(reader))
    assert ids == list(range(10, 20))


def test_piece_indices_are_part_of_resume_fingerprint(petastorm_dataset):
    with make_reader(petastorm_dataset.url, num_epochs=1,
                     shuffle_row_groups=False, piece_indices=[0]) as reader:
        list(reader)
        state = reader.state_dict()
    # Same plan resumes; a different plan must be rejected.
    make_reader(petastorm_dataset.url, num_epochs=1, shuffle_row_groups=False,
                piece_indices=[0], resume_state=state).stop()
    with pytest.raises(ValueError, match="resume_state mismatch"):
        make_reader(petastorm_dataset.url, num_epochs=1,
                    shuffle_row_groups=False, piece_indices=[0, 1],
                    resume_state=state)
