"""Sharding helpers: pod-aware reader shards + global jax.Array assembly.

TPU-first replacement for the reference's implicit Horovod-rank sharding
(SURVEY.md §5 "distributed communication backend"): the reference expects the
user to pass ``cur_shard=hvd.rank(), shard_count=hvd.size()``; here the
defaults come from ``jax.process_index()/process_count()`` so a pod "just
works", and batches can be assembled into globally-sharded ``jax.Array`` s for
pjit. The data plane still never crosses hosts — each host reads its own row
groups from the (DCN-attached) store; ICI collectives belong to the training
step, exactly as the scaling recipe prescribes.
"""

from __future__ import annotations

import random
import warnings


def default_shard_options(cur_shard=None, shard_count=None):
    """Fill (cur_shard, shard_count) from the JAX runtime when unset.

    Single-process (or JAX absent): (None, None) — no sharding, matching the
    reference's default behavior.
    """
    if cur_shard is not None or shard_count is not None:
        return cur_shard, shard_count
    try:
        import jax

        if jax.process_count() > 1:
            return jax.process_index(), jax.process_count()
    except Exception:  # pragma: no cover - jax missing/uninitialized
        pass
    return None, None


def split_pieces_for_shards(pieces, shard_count, shard_seed=None):
    """Partition a row-group piece list into ``shard_count`` shards.

    Single source of truth for the shard arithmetic: the optional
    ``shard_seed`` pre-shuffle followed by round-robin ``pieces[s::count]`` —
    exactly what ``Reader`` does (reference parity:
    ``petastorm/reader.py`` shard logic), so metadata-only step-count
    computations agree with what each host's reader will actually deliver.
    """
    if shard_count is None:
        return [list(pieces)]
    if shard_seed is not None:
        pieces = list(pieces)
        random.Random(shard_seed).shuffle(pieces)
    return [pieces[s::shard_count] for s in range(shard_count)]


def _batches_for_rows(rows, batch_size, last_batch):
    """Number of batches ``batch_iterator`` emits for a ``rows``-row stream."""
    if rows <= 0:
        return 0
    if last_batch == "drop":
        return rows // batch_size
    # "pad" and "keep" both emit the final partial batch.
    return -(-rows // batch_size)


def global_step_count(dataset_url, batch_size, shard_count,
                      last_batch="drop", num_epochs=1, shard_seed=None,
                      filters=None, storage_options=None, filesystem=None,
                      hdfs_driver="libhdfs"):
    """Global per-host step count for SPMD lockstep — pure metadata arithmetic.

    pjit programs are SPMD-synchronous: every host must dispatch the same
    number of steps or the pod deadlocks (SURVEY.md §7 hard-part #2). The
    reference's round-robin row-group sharding gives *unequal* row counts per
    shard, so the safe global step count is the **minimum** over shards of the
    number of batches that shard can produce. This helper computes it from
    Parquet metadata alone (no data read): per-shard row counts via the same
    enumeration + shard arithmetic the Reader uses, then the batcher's
    ``last_batch`` policy.

    Pass the result as ``max_batches`` to every host's
    :func:`~petastorm_tpu.jax_utils.make_jax_dataloader` (done automatically
    when a ``sharding`` is given and the reader carries shard metadata — see
    :func:`derive_equal_step_max_batches`).

    Exact when no row-level ``predicate`` is used (``filters`` prune whole row
    groups, so metadata counts stay exact). With a predicate the surviving row
    count is data-dependent — coordinate steps out of band instead.

    :param num_epochs: must be a finite int (``None``/infinite streams have no
        step count).
    :return: int — the global minimum number of full batches across shards
        (0 when any shard is empty: the only safe lockstep count).
    """
    if num_epochs is None:
        raise ValueError(
            "global_step_count requires a finite num_epochs (an infinite "
            "stream has no step count)")
    if shard_count is None or shard_count < 1:
        raise ValueError("shard_count must be a positive integer")
    from petastorm_tpu.fs_utils import FilesystemResolver
    from petastorm_tpu.reader.reader import enumerate_row_group_pieces

    resolver = FilesystemResolver(dataset_url, hdfs_driver=hdfs_driver,
                                  storage_options=storage_options,
                                  filesystem=filesystem)
    from petastorm_tpu.etl.metadata import piece_row_counts

    fs = resolver.filesystem()
    pieces = enumerate_row_group_pieces(fs, resolver.get_dataset_path(),
                                        filters)
    counts = piece_row_counts(fs, pieces)
    shards = split_pieces_for_shards(pieces, shard_count, shard_seed)
    return min(
        _batches_for_rows(
            sum(counts[(p.path, p.row_group)] for p in shard) * num_epochs,
            batch_size, last_batch)
        for shard in shards)


def derive_equal_step_max_batches(reader, batch_size, last_batch="drop"):
    """Derive a pod-safe ``max_batches`` from a constructed Reader, or None.

    Readers record the row counts of *every* shard at planning time
    (``Reader.shard_row_counts``) — each host can therefore compute the global
    minimum locally, with zero cross-host communication (consistent because
    all hosts enumerate the same store with the same shard_seed). Returns
    None when the count cannot be known from metadata: row-level predicate,
    NGram windows (windows per row group are data-dependent), infinite
    epochs, or a reader type that doesn't expose shard metadata.
    """
    # Cheap disqualifiers first: shard_row_counts is a lazy property that may
    # open parquet footers (one read per file on an object store) — don't pay
    # that when derivation is rejected anyway.
    num_epochs = getattr(reader, "num_epochs", 1)
    if num_epochs is None:
        warnings.warn(
            "Cannot derive an equal SPMD step count for an infinite stream "
            "(num_epochs=None). Pass max_batches explicitly (agreed across "
            "hosts) or steps may deadlock the pod",
            UserWarning, stacklevel=3)
        return None
    if getattr(reader, "ngram", None) is not None:
        warnings.warn(
            "Cannot derive an equal SPMD step count for an NGram reader: "
            "windows per row group are data-dependent. Pass max_batches "
            "explicitly (agreed across hosts) or steps may deadlock the pod",
            UserWarning, stacklevel=3)
        return None
    if getattr(reader, "_resume_state", None) is not None:
        warnings.warn(
            "Cannot derive an equal SPMD step count for a resumed reader: "
            "remaining rows are checkpoint-dependent. Pass max_batches "
            "explicitly (observe via count_deliverable_batches, agree via "
            "agree_max_batches)",
            UserWarning, stacklevel=3)
        return None
    if getattr(reader, "_predicate", None) is not None:
        warnings.warn(
            "Cannot derive an equal SPMD step count: a row-level predicate "
            "makes per-shard row counts data-dependent. Pass max_batches "
            "explicitly (observe via count_deliverable_batches, agree via "
            "agree_max_batches) or steps may deadlock the pod",
            UserWarning, stacklevel=3)
        return None
    transform_spec = getattr(reader, "_transform_spec", None)
    if transform_spec is not None and getattr(transform_spec, "func",
                                              None) is not None:
        # A TransformSpec func may drop/duplicate rows (it rewrites the whole
        # frame/batch), so metadata row counts no longer predict delivered
        # rows — same data-dependence hazard as a predicate. Schema-only
        # specs (func=None, edit/removed fields) cannot change row counts
        # and keep automatic derivation.
        warnings.warn(
            "Cannot derive an equal SPMD step count: a TransformSpec can "
            "change per-shard row counts. Pass max_batches explicitly "
            "(observe via count_deliverable_batches, agree via "
            "agree_max_batches) or steps may deadlock the pod",
            UserWarning, stacklevel=3)
        return None
    counts = getattr(reader, "shard_row_counts", None)
    if counts is None:
        return None
    return min(_batches_for_rows(c * num_epochs, batch_size, last_batch)
               for c in counts)


def agree_max_batches(local_count, reduce="min"):
    """Agree a pod-safe ``max_batches`` from per-host OBSERVED batch counts.

    Closes the loop for every case :func:`derive_equal_step_max_batches`
    declines (row-level predicate, NGram windows, TransformSpec funcs,
    resumed readers): each host observes how many batches it can actually
    deliver — e.g. one ``stage_to_device=False`` counting pass over its
    reader, or an application-side row count — and this helper agrees the
    global value with ONE tiny collective (``jax.experimental.
    multihost_utils.process_allgather`` of a single int64; control plane
    only, no data moves).

    :param local_count: this host's locally-observed deliverable batch count.
    :param reduce: ``"min"`` (default — the only *safe* lockstep count with
        ragged shards: every host can deliver at least the minimum) or
        ``"host0"`` (adopt host 0's count — only when the caller guarantees
        every host can deliver it, e.g. a deliberately truncated run).
    :return: the agreed global count (``local_count`` unchanged when
        running single-process).
    """
    if reduce not in ("min", "host0"):
        raise ValueError(f"reduce {reduce!r} is not 'min' or 'host0'")
    local_count = int(local_count)
    try:
        import jax

        if jax.process_count() == 1:
            return local_count
    except Exception:  # pragma: no cover - jax missing/uninitialized
        return local_count
    import numpy as np

    from jax.experimental import multihost_utils

    counts = np.asarray(multihost_utils.process_allgather(
        np.asarray([local_count], np.int64)))
    return int(counts.min()) if reduce == "min" else int(counts.flat[0])


def count_deliverable_batches(reader, batch_size, last_batch="drop"):
    """Count the batches ``reader`` can deliver by DRAINING it once (a
    host-side counting pass — no device, no decode retention).

    The observation half of :func:`agree_max_batches` for data-dependent
    pipelines (predicates, NGram): run this on a *separately constructed*
    reader with the same arguments, agree the result across hosts, then pass
    it as ``max_batches`` to the real loader. The counting pass pays one
    decode sweep — worth it once per training run when the alternative is a
    pod deadlock.
    """
    from petastorm_tpu.jax_utils.batcher import batch_iterator

    if getattr(reader, "num_epochs", 1) is None:
        raise ValueError(
            "count_deliverable_batches would never terminate on an infinite "
            "reader (num_epochs=None): construct the counting reader with "
            "num_epochs=1 and scale the agreed count by your epoch budget")
    n = 0
    with reader:
        for _ in batch_iterator(reader, batch_size, last_batch=last_batch):
            n += 1
    return n


def batch_sharding(mesh, axis="data"):
    """NamedSharding that splits the batch (leading) axis over ``mesh[axis]``.

    The standard data-parallel input sharding: every other array dim is
    replicated; model/tensor axes of the mesh replicate the input so the
    training step's pjit can re-shard activations as it likes.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(axis))


def local_data_to_global_array(sharding, array, observe_shard_put=None):
    """Host-local numpy batch → globally-sharded ``jax.Array``.

    Sharding-aware DIRECT delivery on the fast path: when every device of
    ``sharding`` is addressable from this process (the single-controller
    case — one host driving its own chips), each device's slice is
    ``device_put`` straight onto its target device and the global array is
    assembled with ``jax.make_array_from_single_device_arrays`` — per-shard
    H2D transfers with no intermediate host-side global buffer, so each
    device receives exactly its rows. Multi-process shardings (a pod) fall
    back to ``jax.make_array_from_process_local_data``: each host
    contributes its shard of the global batch; XLA never moves data over
    DCN — the global array is metadata stitching over per-host HBM buffers.

    :param observe_shard_put: optional callable receiving each per-shard
        ``device_put``'s dispatch seconds (the loader feeds its
        ``shard_put`` stage histogram through this).
    """
    import jax
    import numpy as np

    arr = np.asarray(array)
    if not getattr(sharding, "is_fully_addressable", False):
        return jax.make_array_from_process_local_data(sharding, arr)
    import time

    # Fully addressable ⇒ the process-local batch IS the global batch.
    index_map = sharding.addressable_devices_indices_map(arr.shape)
    shards = []
    for device, index in index_map.items():
        t0 = time.perf_counter()
        shards.append(jax.device_put(arr[index], device))
        if observe_shard_put is not None:
            observe_shard_put(time.perf_counter() - t0)
    return jax.make_array_from_single_device_arrays(arr.shape, sharding,
                                                    shards)
