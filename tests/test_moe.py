"""Expert-parallel MoE tests over the virtual CPU mesh: the shard_map +
all_to_all dispatch/combine must match the dense single-device oracle exactly
(including capacity drops), forward and backward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from petastorm_tpu.models.moe import (
    apply_moe_model,
    init_moe_params,
    make_moe_train_step,
    moe_param_partition_specs,
    reference_forward,
)


def _mesh(n, names=("ep",)):
    devs = np.array(jax.devices()[:n])
    if len(names) == 2:
        devs = devs.reshape(2, n // 2)
    return Mesh(devs, names)


def _params(num_experts=8, seed=0):
    return init_moe_params(jax.random.PRNGKey(seed), feature_dim=6,
                           d_model=16, d_hidden=32,
                           num_experts=num_experts, num_classes=3)


def _features(n, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(n, 6)
                       .astype(np.float32))


def test_moe_matches_dense_oracle():
    mesh = _mesh(8)
    params = _params(8)
    x = _features(32)
    got, aux = apply_moe_model(params, x, mesh, capacity_factor=8.0)
    want, aux_want = reference_forward(params, x, num_shards=8,
                                       capacity_factor=8.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_want), rtol=1e-5)


def test_moe_matches_oracle_with_capacity_drops():
    """Tiny capacity forces drops; the sharded path and the oracle must
    agree on WHICH tokens drop (per-shard queues) and on the passthrough."""
    mesh = _mesh(4)
    params = _params(4, seed=1)
    x = _features(32, seed=1)
    got, _ = apply_moe_model(params, x, mesh, capacity_factor=0.5)
    want, _ = reference_forward(params, x, num_shards=4,
                                capacity_factor=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_moe_dropped_tokens_pass_through_residual():
    """With capacity 1 and many tokens, most tokens drop: their logits must
    equal embed→head with zero expert contribution."""
    mesh = _mesh(4)
    params = _params(4, seed=2)
    x = _features(16, seed=2)
    logits, _ = apply_moe_model(params, x, mesh, capacity_factor=0.26)
    emb = x @ params["embed"]
    passthrough = np.asarray((emb @ params["head"]).astype(jnp.float32))
    got = np.asarray(logits)
    # at least one token must hit the passthrough exactly (it was dropped)
    dropped = np.isclose(got, passthrough, rtol=1e-6).all(axis=1)
    assert dropped.any()


def test_moe_gradients_match_oracle():
    """Backward through both all_to_alls (their transposes are the reverse
    exchanges) must equal the dense oracle's gradients."""
    mesh = _mesh(8)
    params = _params(8, seed=3)
    x = _features(32, seed=3)
    labels = jnp.asarray(np.arange(32) % 3, jnp.int32)

    def loss_sharded(p):
        logits, aux = apply_moe_model(p, x, mesh, capacity_factor=8.0)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        return nll.mean() + 0.01 * aux

    def loss_dense(p):
        logits, aux = reference_forward(p, x, num_shards=8,
                                        capacity_factor=8.0)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
        return nll.mean() + 0.01 * aux

    g_sharded = jax.grad(loss_sharded)(params)
    g_dense = jax.grad(loss_dense)(params)
    for key in params:
        np.testing.assert_allclose(
            np.asarray(g_sharded[key]), np.asarray(g_dense[key]),
            rtol=1e-4, atol=1e-5, err_msg=key)


def test_moe_train_step_dp_ep_mesh_jit():
    """dp × ep: tokens shard over both axes, experts over ep only; a jitted
    step with the published partition specs runs and learns."""
    mesh = _mesh(8, names=("data", "ep"))
    params = _params(8, seed=4)
    specs = moe_param_partition_specs()
    params = jax.device_put(
        params, {k: NamedSharding(mesh, specs[k]) for k in params})
    step = make_moe_train_step(mesh=mesh, batch_axis="data",
                               capacity_factor=4.0)
    x_shard = NamedSharding(mesh, P(("data", "ep"), None))
    lab_shard = NamedSharding(mesh, P(("data", "ep")))
    jstep = jax.jit(step)
    x = jax.device_put(_features(32, seed=4), x_shard)
    labels = jax.device_put(jnp.asarray(np.arange(32) % 3, jnp.int32),
                            lab_shard)
    mask = jax.device_put(jnp.ones((32,), bool), lab_shard)
    losses = []
    for _ in range(8):
        params, loss = jstep(params, x, labels, mask)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_pad_mask_zeroes_gradient():
    """Fully-masked batch: cross-entropy contributes nothing; only the aux
    term (which ignores the mask — routing still happens) may move params."""
    mesh = _mesh(4)
    params = _params(4, seed=5)
    step = make_moe_train_step(mesh=mesh, aux_weight=0.0)
    x = _features(8, seed=5)
    labels = jnp.zeros((8,), jnp.int32)
    new_params, loss = step(params, x, labels, jnp.zeros((8,), bool))
    assert float(loss) == 0.0
    for key in params:
        np.testing.assert_array_equal(np.asarray(new_params[key]),
                                      np.asarray(params[key]))


def test_moe_rejects_bad_shapes():
    mesh = _mesh(8)
    params = _params(num_experts=6)  # 6 experts on an 8-wide ep axis
    with pytest.raises(ValueError, match="experts do not split"):
        apply_moe_model(params, _features(32), mesh)
    params = _params(num_experts=8)
    with pytest.raises(ValueError, match="tokens do not shard"):
        apply_moe_model(params, _features(30), mesh)


# ---------------------------------------------------------------------------
# top-2 routing (GShard)
# ---------------------------------------------------------------------------

def test_moe_top2_matches_dense_oracle():
    mesh = _mesh(8)
    params = _params(8, seed=21)
    feats = _features(32, seed=21)
    got, aux = jax.jit(lambda p, f: apply_moe_model(
        p, f, mesh, top_k=2))(params, feats)
    want, aux_ref = reference_forward(params, feats, num_shards=8, top_k=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(aux_ref), rtol=1e-6)


def test_moe_top2_gradients_match_oracle():
    mesh = _mesh(8)
    params = _params(8, seed=22)
    feats = _features(32, seed=22)

    def loss_sharded(p):
        logits, aux = apply_moe_model(p, feats, mesh, top_k=2)
        return (logits ** 2).sum() + 0.1 * aux

    def loss_ref(p):
        logits, aux = reference_forward(p, feats, num_shards=8, top_k=2)
        return (logits ** 2).sum() + 0.1 * aux

    got = jax.grad(loss_sharded)(params)
    want = jax.grad(loss_ref)(params)
    for k in want:
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_moe_top2_second_choice_contributes():
    """With top-2, a token's output mixes TWO experts: against a top-1 run
    on identical params/features the outputs must differ. (Gate
    renormalization itself is covered by the dense-oracle parity tests —
    the oracle runs the same _route_topk math.)"""
    mesh = _mesh(8)
    params = _params(8, seed=23)
    feats = _features(32, seed=23)
    out1, _ = apply_moe_model(params, feats, mesh, top_k=1,
                              capacity_factor=8.0)
    out2, _ = apply_moe_model(params, feats, mesh, top_k=2,
                              capacity_factor=8.0)
    assert not np.allclose(np.asarray(out1), np.asarray(out2))


def test_moe_aux_loss_balances_expert_load():
    """The point of the aux loss (VERDICT r4 weak #5): start from a
    deliberately COLLAPSED router (one expert's logit biased +2, so most
    first choices pile onto it and the balance metric starts far above 1)
    and train with the aux loss on — the balance metric must drop
    substantially toward 1; with aux_weight=0 it must not improve
    meaningfully from the routing's own gradients."""
    mesh = _mesh(8)
    rng = np.random.RandomState(24)
    feats = jnp.asarray(rng.randn(64, 6).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 3, 64), jnp.int32)
    mask = jnp.ones(64, bool)

    def collapsed_params():
        p = dict(_params(8, seed=24))
        router = np.asarray(p["router"]).copy()
        router[:, 0] = np.abs(router[:, 0]) + 2.0  # collapse onto expert 0
        p["router"] = jnp.asarray(router)
        return p

    def balance(p):
        _, aux = apply_moe_model(p, feats, mesh, top_k=2)
        return float(aux)

    start = balance(collapsed_params())
    assert start > 2.0, f"fixture not collapsed: aux={start}"

    def train(aux_weight, steps=30):
        p = collapsed_params()
        step = jax.jit(make_moe_train_step(0.3, aux_weight=aux_weight,
                                           mesh=mesh, top_k=2))
        for _ in range(steps):
            p, _ = step(p, feats, labels, mask)
        return balance(p)

    balanced = train(aux_weight=0.5)
    unbalanced = train(aux_weight=0.0)
    assert balanced < start * 0.6, (start, balanced)
    assert balanced < unbalanced - 0.2, (balanced, unbalanced)
