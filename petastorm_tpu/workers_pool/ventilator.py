"""Work ventilation: drip-feeding items to a pool with bounded in-flight count.

Reference parity: ``petastorm/workers_pool/ventilator.py`` (``Ventilator``,
``ConcurrentVentilator``) — SURVEY.md §2.2. The ventilator is the memory
backpressure mechanism: without it, every row group of every epoch would be
enqueued at once.
"""

from __future__ import annotations

import random
import threading
from abc import ABC, abstractmethod

from petastorm_tpu.telemetry.metrics import (
    VENTILATOR_EPOCHS,
    VENTILATOR_ITEMS,
)


class Ventilator(ABC):
    """Base ventilator: feeds work items to a pool via ``ventilate_fn``."""

    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    @abstractmethod
    def start(self):
        """Begin ventilation (typically on a background thread)."""

    @abstractmethod
    def processed_item(self):
        """Notify that one ventilated item finished (advances the window)."""

    @abstractmethod
    def completed(self):
        """True when no further items will ever be ventilated."""

    @abstractmethod
    def stop(self):
        """Stop ventilation and release the background thread."""


class ConcurrentVentilator(Ventilator):
    """Ventilates ``items_to_ventilate`` for ``iterations`` epochs on a
    background thread, keeping at most ``max_ventilation_queue_size`` items
    in flight.

    ``iterations=None`` ventilates forever (infinite epochs).
    ``randomize_item_order`` reshuffles the item order every epoch.
    Items are dicts passed as kwargs to ``ventilate_fn`` (reference semantics).

    ``per_item_iterations`` (resume support): a list parallel to
    ``items_to_ventilate`` giving how many more epochs each item should be
    ventilated for; epoch ``e`` (0-based) ventilates the items with
    ``per_item_iterations[i] > e``. Requires finite ``iterations`` equal to
    ``max(per_item_iterations)``.
    """

    def __init__(self, ventilate_fn, items_to_ventilate, iterations=1,
                 randomize_item_order=False, random_seed=None,
                 max_ventilation_queue_size=None, ventilation_interval=0.01,
                 per_item_iterations=None):
        super().__init__(ventilate_fn)
        if iterations is not None and iterations <= 0:
            raise ValueError(f"iterations must be positive or None, got {iterations}")
        self._items_to_ventilate = list(items_to_ventilate)
        if per_item_iterations is not None:
            if iterations is None:
                raise ValueError(
                    "per_item_iterations requires finite iterations")
            if len(per_item_iterations) != len(self._items_to_ventilate):
                raise ValueError(
                    "per_item_iterations must parallel items_to_ventilate")
            if max(per_item_iterations, default=0) != iterations:
                raise ValueError(
                    "iterations must equal max(per_item_iterations)")
        self._per_item_iterations = per_item_iterations
        self._iterations = iterations
        self._randomize_item_order = randomize_item_order
        self._random = random.Random(random_seed)
        self._max_ventilation_queue_size = (
            max_ventilation_queue_size
            if max_ventilation_queue_size is not None
            else len(self._items_to_ventilate) or 1
        )
        self._ventilation_interval = ventilation_interval

        self._in_flight = 0
        self._items_ventilated = 0
        self._epochs_completed = 0
        self._lock = threading.Lock()
        self._space_available = threading.Condition(self._lock)
        self._stop_requested = False
        self._completed = False
        self._error = None
        self._thread = None

    @property
    def diagnostics(self):
        """Live ventilation counters (reference ``Reader.diagnostics`` parity:
        items ventilated / in flight — SURVEY.md §5)."""
        with self._lock:
            return {
                "items_ventilated": self._items_ventilated,
                "items_in_flight": self._in_flight,
                "epochs_completed": self._epochs_completed,
                "ventilation_completed": self._completed,
            }

    @property
    def error(self):
        """Exception that killed the ventilation thread, if any. Pools check
        this so a ventilation failure surfaces to the consumer instead of
        hanging the reader until timeout."""
        return self._error

    def start(self):
        if self._thread is not None:
            raise RuntimeError("Ventilator already started")
        if not self._items_to_ventilate:
            self._completed = True
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="petastorm-tpu-ventilator")
        self._thread.start()

    def _run(self):
        try:
            self._run_inner()
        except Exception as exc:  # noqa: BLE001 - surfaced via self.error
            self._error = exc
            self._completed = True

    def _run_inner(self):
        iterations_left = self._iterations
        epoch = 0
        while iterations_left is None or iterations_left > 0:
            if self._per_item_iterations is not None:
                items = [item for item, n in zip(self._items_to_ventilate,
                                                 self._per_item_iterations)
                         if n > epoch]
            else:
                items = list(self._items_to_ventilate)
            if self._randomize_item_order:
                self._random.shuffle(items)
            for item in items:
                with self._space_available:
                    while (self._in_flight >= self._max_ventilation_queue_size
                           and not self._stop_requested):
                        self._space_available.wait(self._ventilation_interval)
                    if self._stop_requested:
                        self._completed = True
                        return
                    self._in_flight += 1
                    self._items_ventilated += 1
                VENTILATOR_ITEMS.inc()
                self._ventilate_fn(**item)
            with self._lock:
                self._epochs_completed += 1
            VENTILATOR_EPOCHS.inc()
            epoch += 1
            if iterations_left is not None:
                iterations_left -= 1
            if self._stop_requested:
                break
        self._completed = True

    def processed_item(self):
        with self._space_available:
            if self._in_flight > 0:
                self._in_flight -= 1
            self._space_available.notify()

    def completed(self):
        # Completed only when the thread finished ventilating every item of
        # every epoch; in-flight items may still be in the pool's queues.
        return self._completed

    def stop(self):
        with self._space_available:
            self._stop_requested = True
            self._space_available.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def reset(self):
        """Restart ventilation from epoch 0 (only when previous run finished).

        Supports ``Reader.reset()``: re-ventilates the same items for the
        original number of iterations.
        """
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("Cannot reset a ventilator that is still running")
        self._thread = None
        self._stop_requested = False
        self._completed = False
        self._error = None
        with self._lock:
            self._in_flight = 0
        self.start()


class DynamicVentilator(Ventilator):
    """Externally-fed ventilator: items arrive one at a time via
    :meth:`submit` instead of from a pre-planned list.

    The seam behind ``Reader(dynamic_ventilation=True)`` — the service's
    streaming piece engine feeds row-group pieces into ONE long-lived pool
    as its mutable piece queue is consumed (and edited mid-stream by
    work-stealing rebalances), instead of constructing a reader per piece.
    The caller owns admission control (how many pieces it keeps in flight);
    this class only tracks the counts and the finished flag. There is no
    background thread: :meth:`submit` calls ``ventilate_fn`` inline, so a
    thread pool enqueues and returns immediately while a dummy pool decodes
    synchronously inside the call.
    """

    def __init__(self, ventilate_fn):
        super().__init__(ventilate_fn)
        self._lock = threading.Lock()
        self._in_flight = 0
        self._items_ventilated = 0
        self._finished = False
        #: Pools probe ``ventilator.error`` to surface ventilation-thread
        #: failures; a thread-less ventilator never has one.
        self.error = None

    @property
    def diagnostics(self):
        with self._lock:
            return {
                "items_ventilated": self._items_ventilated,
                "items_in_flight": self._in_flight,
                "ventilation_completed": self._finished,
            }

    def start(self):
        """Nothing to start — submission drives everything."""

    def submit(self, item):
        """Feed one work item (a kwargs dict) to the pool."""
        with self._lock:
            if self._finished:
                raise RuntimeError(
                    "DynamicVentilator.submit after finish(): the stream "
                    "already declared its piece queue closed")
            self._in_flight += 1
            self._items_ventilated += 1
        VENTILATOR_ITEMS.inc()
        self._ventilate_fn(**item)

    def processed_item(self):
        with self._lock:
            if self._in_flight > 0:
                self._in_flight -= 1

    def finish(self):
        """No further submissions: once in-flight items drain, consumers
        see end-of-data (``EmptyResultError``) instead of blocking."""
        with self._lock:
            self._finished = True

    def completed(self):
        return self._finished

    def stop(self):
        self.finish()
