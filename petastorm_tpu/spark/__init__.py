"""Dataset converter package (Spark-converter API shape, pyarrow-backed).

Reference parity: ``petastorm/spark/`` — the package name is kept for import
compatibility (``from petastorm_tpu.spark import make_spark_converter``),
but the engine is pyarrow: pandas DataFrames and Arrow tables convert
natively, Spark DataFrames via ``toPandas()`` when pyspark is importable.
"""

from petastorm_tpu.spark.dataset_converter import (
    DatasetConverter,
    SparkDatasetConverter,
    make_spark_converter,
    set_parent_cache_dir_url,
)

__all__ = ["make_spark_converter", "DatasetConverter", "SparkDatasetConverter",
           "set_parent_cache_dir_url"]
