"""DLRM-style tabular recommender, SPMD-sharded (data + model parallel).

The model consumer for BASELINE.md config #3 (Criteo-1TB-class tabular data —
the dataset shape ``benchmark.scenarios.make_tabular_dataset`` writes and
``make_batch_reader`` streams). The reference ships no model code (SURVEY.md
§0); this exists to exercise the wide-schema path end-to-end: Parquet →
``make_batch_reader`` → ``make_jax_dataloader`` → sharded pjit train step.

TPU-first choices:

- **Embedding tables are the memory problem** (Criteo-scale tables dwarf
  HBM), so they shard **table-wise over the ``"model"`` mesh axis**: the
  stacked ``[num_tables, vocab, dim]`` tensor splits on its leading axis.
  Lookups are a pure ``take`` along the vocab axis of each local table —
  with batch data-parallel and tables model-parallel, XLA turns the
  gather + feature-interaction contraction into an all-to-all-shaped
  exchange over ICI (the hand-written NCCL all-to-all of GPU DLRM
  implementations, recovered from sharding annotations alone).
- Dense/top MLPs compute in **bfloat16** on the MXU (params f32, cast
  per-step, f32 loss accumulation) — same convention as
  ``models/image_classifier.py``.
- **Static shapes**, hashed categorical ids (``ids % vocab``) so any int64
  column feeds the same trace; pad-mask aware loss for the loader's
  ``last_batch="pad"`` lockstep policy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_dlrm_params(rng, num_dense, num_sparse, vocab_size=1024,
                     embed_dim=16, bottom_hidden=64, top_hidden=64,
                     dtype=jnp.float32):
    """Initialize the DLRM parameter pytree.

    :param num_dense: count of dense float features (Criteo: 13).
    :param num_sparse: count of categorical features = embedding tables
        (Criteo: 26) — the ``"model"``-sharded dimension; keep it a multiple
        of the mesh's model-axis size.
    :param vocab_size: rows per table (ids are hashed into this range).
    :param embed_dim: embedding width; also the bottom MLP's output width so
        dense features join the feature interaction as one more "table".
    """
    k_emb, k_b1, k_b2, k_t1, k_t2 = jax.random.split(rng, 5)
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)  # noqa: E731
    num_features = num_sparse + 1  # +1: bottom-MLP output joins interaction
    interact = (num_features * (num_features - 1)) // 2 + embed_dim
    return {
        "embeddings": jax.random.normal(
            k_emb, (num_sparse, vocab_size, embed_dim), dtype) * 0.05,
        "bottom1": {
            "kernel": jax.random.normal(k_b1, (num_dense, bottom_hidden),
                                        dtype) * scale(num_dense),
            "bias": jnp.zeros((bottom_hidden,), dtype),
        },
        "bottom2": {
            "kernel": jax.random.normal(k_b2, (bottom_hidden, embed_dim),
                                        dtype) * scale(bottom_hidden),
            "bias": jnp.zeros((embed_dim,), dtype),
        },
        "top1": {
            "kernel": jax.random.normal(k_t1, (interact, top_hidden),
                                        dtype) * scale(interact),
            "bias": jnp.zeros((top_hidden,), dtype),
        },
        "top2": {
            "kernel": jax.random.normal(k_t2, (top_hidden, 1),
                                        dtype) * scale(top_hidden),
            "bias": jnp.zeros((1,), dtype),
        },
    }


def dlrm_partition_specs():
    """PartitionSpecs for a ``("data", "model")`` mesh.

    Only the embedding stack is model-sharded (table-wise on the leading
    axis); the MLPs are small and replicate. Activations follow from the
    batch's ``P("data")`` sharding.
    """
    return {
        "embeddings": P("model", None, None),
        "bottom1": {"kernel": P(None, None), "bias": P(None)},
        "bottom2": {"kernel": P(None, None), "bias": P(None)},
        "top1": {"kernel": P(None, None), "bias": P(None)},
        "top2": {"kernel": P(None, None), "bias": P(None)},
    }


def apply_dlrm(params, dense, sparse_ids, compute_dtype=jnp.bfloat16):
    """Forward pass → logits ``[B]``.

    :param dense: float ``[B, num_dense]``.
    :param sparse_ids: int ``[B, num_sparse]`` raw ids (hashed internally).
    """
    dense = dense.astype(compute_dtype)
    emb = params["embeddings"].astype(compute_dtype)
    num_sparse, vocab, embed_dim = emb.shape

    # Bottom MLP over dense features → one pseudo-embedding.
    x = dense @ params["bottom1"]["kernel"].astype(compute_dtype)
    x = jax.nn.relu(x + params["bottom1"]["bias"].astype(compute_dtype))
    x = x @ params["bottom2"]["kernel"].astype(compute_dtype)
    dense_vec = jax.nn.relu(
        x + params["bottom2"]["bias"].astype(compute_dtype))  # [B, D]

    # Table-wise lookups: one take per table along its vocab axis. vmap over
    # the (model-sharded) table axis keeps the gather local to each shard.
    ids = (sparse_ids % vocab).astype(jnp.int32).T  # [num_sparse, B]
    looked_up = jax.vmap(lambda table, i: jnp.take(table, i, axis=0))(
        emb, ids)  # [num_sparse, B, D]
    features = jnp.concatenate(
        [dense_vec[None], looked_up], axis=0)  # [F, B, D]

    # Pairwise dot-product interaction (the DLRM signature op): one batched
    # matmul on the MXU, upper triangle taken with a static mask.
    feats_b = jnp.transpose(features, (1, 0, 2))  # [B, F, D]
    inter = feats_b @ jnp.transpose(feats_b, (0, 2, 1))  # [B, F, F]
    f = feats_b.shape[1]
    iu, ju = jnp.triu_indices(f, k=1)
    pairwise = inter[:, iu, ju]  # [B, F*(F-1)/2]

    top_in = jnp.concatenate([dense_vec, pairwise], axis=1)
    x = top_in @ params["top1"]["kernel"].astype(compute_dtype)
    x = jax.nn.relu(x + params["top1"]["bias"].astype(compute_dtype))
    x = x @ params["top2"]["kernel"].astype(compute_dtype)
    logits = x + params["top2"]["bias"].astype(compute_dtype)
    return logits[:, 0].astype(jnp.float32)


def make_dlrm_train_step(learning_rate=0.01):
    """SGD step on masked binary cross-entropy; jit/pjit-ready.

    Signature: ``step(params, dense, sparse_ids, labels, mask) ->
    (params, loss)`` — ``mask`` is the loader's ``__pad_mask__`` (all-True
    when unpadded) so padded rows contribute zero gradient.
    """

    def loss_fn(params, dense, sparse_ids, labels, mask):
        logits = apply_dlrm(params, dense, sparse_ids)
        losses = jnp.maximum(logits, 0) - logits * labels + \
            jnp.log1p(jnp.exp(-jnp.abs(logits)))  # stable BCE-with-logits
        mask = mask.astype(jnp.float32)
        return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def step(params, dense, sparse_ids, labels, mask):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, dense, sparse_ids, labels.astype(jnp.float32), mask)
        params = jax.tree_util.tree_map(
            lambda p, g: p - learning_rate * g.astype(p.dtype), params, grads)
        return params, loss

    return step
