"""Live-diagnostics tests: pool counters, real results_qsize, live
``Reader.diagnostics`` snapshots, loader per-stage timings.

Reference analogue: ``Reader.diagnostics`` runtime counters (SURVEY.md §5 —
items ventilated/processed, queue sizes) that the reference exposes for
input-pipeline stall debugging.
"""

import time

import numpy as np

from petastorm_tpu import make_reader
from petastorm_tpu.jax_utils import make_jax_dataloader
from petastorm_tpu.workers_pool import EmptyResultError
from petastorm_tpu.workers_pool.dummy_pool import DummyPool
from petastorm_tpu.workers_pool.process_pool import ProcessPool
from petastorm_tpu.workers_pool.thread_pool import ThreadPool
from petastorm_tpu.workers_pool.ventilator import ConcurrentVentilator
from petastorm_tpu.workers_pool.worker_base import WorkerBase


class EchoWorker(WorkerBase):
    def process(self, value):
        self.publish_func(value)


def _drain(pool):
    results = []
    while True:
        try:
            results.append(pool.get_results(timeout=20))
        except EmptyResultError:
            return results


def test_thread_pool_diagnostics_live_counters():
    pool = ThreadPool(2)
    pool.start(EchoWorker)
    assert pool.diagnostics["items_ventilated"] == 0
    for v in range(5):
        pool.ventilate(v)
    assert pool.diagnostics["items_ventilated"] == 5
    results = [pool.get_results(timeout=20) for _ in range(5)]
    assert sorted(results) == list(range(5))
    # DONE bookkeeping messages may still be in the results queue; counters
    # settle once they are drained by the next get_results call.
    try:
        pool.get_results(timeout=1)
    except Exception:
        pass
    diag = pool.diagnostics
    assert diag["items_processed"] == 5
    assert diag["items_in_flight"] == 0
    assert diag["workers_count"] == 2
    pool.stop()
    pool.join()


def test_dummy_pool_diagnostics_and_qsize():
    pool = DummyPool()
    pool.start(EchoWorker)
    for v in range(3):
        pool.ventilate(v)
    # DummyPool is synchronous: everything already processed, results queued.
    diag = pool.diagnostics
    assert diag["items_ventilated"] == 3
    assert diag["items_processed"] == 3
    assert diag["results_queue_size"] == 3
    assert pool.results_qsize() == 3
    pool.get_results(timeout=5)
    assert pool.results_qsize() == 2
    pool.stop()
    pool.join()


def test_process_pool_results_qsize_is_a_real_depth():
    pool = ProcessPool(1)
    pool.start(EchoWorker)
    for v in range(4):
        pool.ventilate(v)
    # Wait for the worker to push all four results, then observe the depth
    # WITHOUT consuming anything.
    deadline = time.monotonic() + 20
    while pool.results_qsize() < 4 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert pool.results_qsize() == 4
    diag = pool.diagnostics
    assert diag["items_ventilated"] == 4
    assert diag["results_queue_size"] == 4
    # Buffered frames are served in order and completion still settles.
    results = [pool.get_results(timeout=20) for _ in range(4)]
    assert sorted(results) == list(range(4))
    # All RESULT payloads consumed: only DONE bookkeeping frames may remain,
    # and those never count toward the results depth.
    assert pool.results_qsize() == 0
    assert pool.diagnostics["items_in_flight"] >= 0
    pool.stop()
    pool.join()


def test_ventilator_diagnostics():
    seen = []
    vent = ConcurrentVentilator(lambda **kw: seen.append(kw),
                                [{"value": i} for i in range(4)],
                                iterations=2)
    vent.start()
    deadline = time.monotonic() + 10
    while not vent.completed() and time.monotonic() < deadline:
        vent.processed_item()
        time.sleep(0.001)
    diag = vent.diagnostics
    assert diag["items_ventilated"] == 8
    assert diag["epochs_completed"] == 2
    assert diag["ventilation_completed"] is True
    vent.stop()


def test_reader_diagnostics_live_mid_iteration(petastorm_dataset):
    with make_reader(petastorm_dataset.url, reader_pool_type="thread",
                     workers_count=2, num_epochs=1) as reader:
        before = reader.diagnostics
        assert before["rowgroups_total"] > 0
        rows = 0
        for _ in reader:
            rows += 1
            if rows == 5:
                mid = reader.diagnostics
                # Live counters visible mid-iteration — non-trivial values.
                assert mid["items_ventilated"] > 0
                assert mid["items_processed"] >= 0
                assert "results_queue_size" in mid
        after = reader.diagnostics
        assert after["items_processed"] == after["items_ventilated"]
        assert after["ventilation_completed"] is True
        assert rows > 5


def test_loader_stage_breakdown(petastorm_dataset):
    reader = make_reader(petastorm_dataset.url, reader_pool_type="dummy",
                         num_epochs=1)
    with make_jax_dataloader(reader, batch_size=4,
                             stage_to_device=False) as loader:
        batches = sum(1 for _ in loader)
        assert batches > 0
        diag = loader.diagnostics
        assert diag["producer_decode_s"] > 0
        assert diag["producer_queue_wait_s"] >= 0
        assert diag["device_dispatch_s"] >= 0
        # Stage times and stall are internally consistent with wall time.
        assert diag["wall_s"] > 0
        assert diag["stall_s"] <= diag["wall_s"] + 0.001
