"""ETL: dataset materialization, metadata, row-group indexing.

Reference parity: ``petastorm/etl/`` — SURVEY.md §2.3. The engine here is
``pyarrow.dataset`` (no JVM): materialization runs in-process or across a
local process pool, and a TPU pod's hosts each read metadata independently
(zero data-plane cross-host traffic, SURVEY.md §5).
"""

from petastorm_tpu.etl.metadata import (  # noqa: F401
    materialize_dataset,
    get_schema,
    get_schema_from_dataset_url,
    infer_or_load_unischema,
    load_row_groups,
)
