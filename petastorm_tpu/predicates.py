"""Row-level predicates, evaluated worker-side before full decode.

Reference parity: ``petastorm/predicates.py`` (``PredicateBase``, ``in_set``,
``in_intersection``, ``in_lambda``, ``in_negate``, ``in_reduce``,
``in_pseudorandom_split``) — SURVEY.md §2.1. Predicates declare the minimal column subset they need
(:meth:`PredicateBase.get_fields`); the reader worker does a two-phase read
(predicate columns → boolean mask → remaining columns for surviving rows), so
a selective predicate skips most of the expensive decode work.
"""

from __future__ import annotations

import hashlib
import re
import uuid
from abc import ABC, abstractmethod


class PredicateBase(ABC):
    """A row filter: which fields it needs + per-row inclusion decision.

    Subclasses define deterministic ``__repr__`` s: the repr is part of the
    disk-cache key (``LocalDiskCache`` persists across runs, so an
    address-bearing default repr would both defeat cache hits and risk
    aliasing different predicates).
    """

    @abstractmethod
    def get_fields(self):
        """Set of field names :meth:`do_include` reads."""

    @abstractmethod
    def do_include(self, values):
        """``values`` maps each field from :meth:`get_fields` to the row's
        value; return True to keep the row."""

    def do_include_vectorized(self, columns, num_rows):
        """Optional columnar evaluation: ``columns`` maps each field to a
        whole numpy column; return a bool mask of ``num_rows``, or ``None``
        to signal "evaluate row by row" (the default). Batch/columnar
        workers try this first — on wide tabular scans the per-row Python
        loop is the predicate cost, not the comparison itself."""
        return None


def _func_fingerprint(func):
    """Stable fingerprint of a callable: qualname + bytecode + consts +
    captured state (closure cells, defaults) digest.

    Closure cells matter: ``lambda v: v['id'] > t`` compiled with ``t=5`` and
    ``t=10`` shares bytecode — only the cell value distinguishes them, and the
    disk-cache key must too."""
    code = getattr(func, "__code__", None)
    if code is None:  # builtins (e.g. all/any) have no __code__
        return getattr(func, "__qualname__", repr(func))
    cells = tuple(_stable_repr(cell.cell_contents)
                  for cell in (func.__closure__ or ()))
    defaults = _stable_repr(getattr(func, "__defaults__", None))
    # Referenced globals by VALUE, not just name: ``lambda v: v > THRESHOLD``
    # must change key when THRESHOLD changes.
    func_globals = getattr(func, "__globals__", {})
    globals_used = tuple(
        (name, _stable_repr(func_globals[name]))
        for name in code.co_names if name in func_globals)
    digest = hashlib.sha256(
        code.co_code + _consts_fingerprint(code.co_consts).encode("utf-8")
        + repr(code.co_names).encode("utf-8")  # attribute/builtin names
        + repr(globals_used).encode("utf-8")
        + repr(cells).encode("utf-8") + defaults.encode("utf-8")
    ).hexdigest()[:16]
    return f"{getattr(func, '__qualname__', '<fn>')}:{digest}"


def _consts_fingerprint(consts):
    """Address-free fingerprint of ``co_consts``. Nested lambdas and
    comprehensions place code objects in co_consts whose repr embeds a memory
    address — recurse into their own code/consts instead, or the persistent
    disk cache misses on every new process."""
    parts = []
    for const in consts:
        if hasattr(const, "co_code"):  # a nested code object
            parts.append(f"code({const.co_name},"
                         f"{const.co_code!r},"
                         f"{_consts_fingerprint(const.co_consts)},"
                         f"{const.co_names!r})")
        elif isinstance(const, frozenset):
            # repr order follows randomized string hashing — sort, or the
            # fingerprint changes per process (`x in {...}` lambdas).
            parts.append("frozenset(" + ",".join(sorted(
                repr(item) for item in const)) + ")")
        else:
            parts.append(repr(const))
    return "(" + ",".join(parts) + ")"


_DEFAULT_OBJECT_REPR = re.compile(r"<.+ at 0x[0-9a-fA-F]+>")

_PROCESS_SALT = uuid.uuid4().hex[:12]


def _stable_repr(obj):
    """repr(), except address-bearing default reprs become content digests.

    ``<Foo object at 0x7f...>`` changes every process — useless (and
    alias-prone, if stripped) in a persistent cache key. Pickle the object
    instead: contents-based, cross-run stable. Unpicklable objects fall back
    to the class name alone (cache misses, never aliases wrong data because
    the rest of the key still distinguishes dataset/row-group/fields)."""
    r = repr(obj)
    if not _DEFAULT_OBJECT_REPR.search(r):
        return r
    import pickle

    try:
        digest = hashlib.sha256(
            pickle.dumps(obj, protocol=4)).hexdigest()[:16]
        return f"<{type(obj).__qualname__} pickle:{digest}>"
    except Exception:
        # Unpicklable: id() distinguishes objects within this process; the
        # per-process salt guarantees a cross-run cache MISS (ids can recur
        # across runs — a miss is safe, an alias serves wrong rows).
        return (f"<{type(obj).__qualname__} "
                f"unpicklable:{id(obj)}:{_PROCESS_SALT}>")


#: The declarative comparison vocabulary of :class:`ColumnPredicate` —
#: every op has a scalar form (``do_include``), a numpy columnar form
#: (``do_include_vectorized``), and a pyarrow-compute form (``pa_mask``),
#: all three bit-equivalent on scalar columns.
COLUMN_PREDICATE_OPS = ("eq", "ne", "lt", "le", "gt", "ge", "in", "not-in",
                        "mod-eq")


class ColumnPredicate(PredicateBase):
    """A declarative single-column row filter that can cross the wire.

    Unlike the ``in_lambda``-family predicates (arbitrary Python — only
    usable in the process that constructed them), a ``ColumnPredicate`` is
    pure data: ``(field, op, value[, modulus])``. That is what lets the
    service client ship it on a **stream request** so the filter runs
    worker-side *below decode* (the filter-hoisting graph rewrite —
    ``docs/guides/pipeline.md#graph-rewrites``) and what lets cache
    fingerprints sign it canonically (:meth:`to_wire` is the key
    ingredient, stable across processes — no reprs of live objects).

    Ops (see :data:`COLUMN_PREDICATE_OPS`): the six comparisons, ``in`` /
    ``not-in`` (membership in ``value``, a list), and ``mod-eq`` — keep
    rows where ``field % modulus == value`` (the selectivity-dial used by
    predicate-heavy benchmarks and tests).

    All three evaluation forms are provided: per-row ``do_include``,
    columnar ``do_include_vectorized`` (numpy), and ``pa_mask`` (pyarrow
    compute on the raw Arrow table — what the two-phase predicate read
    uses to mask a row group without materializing dropped rows). They
    operate on **stored scalar values**: the reader only takes the
    column-level fast path for scalar-codec fields, where stored and
    decoded values compare identically.
    """

    def __init__(self, field, op, value, modulus=None):
        if op not in COLUMN_PREDICATE_OPS:
            raise ValueError(
                f"op must be one of {COLUMN_PREDICATE_OPS}, got {op!r}")
        if op == "mod-eq":
            if modulus is None or int(modulus) <= 0:
                raise ValueError("op='mod-eq' needs a positive modulus")
            modulus = int(modulus)
        elif modulus is not None:
            raise ValueError(f"modulus only applies to op='mod-eq', "
                             f"not {op!r}")
        if op in ("in", "not-in"):
            value = list(value)
        self._field = str(field)
        self._op = op
        self._value = value
        self._modulus = modulus

    # -- the PredicateBase contract ---------------------------------------

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        v = values[self._field]
        op, want = self._op, self._value
        if op == "eq":
            return v == want
        if op == "ne":
            return v != want
        if op == "lt":
            return v < want
        if op == "le":
            return v <= want
        if op == "gt":
            return v > want
        if op == "ge":
            return v >= want
        if op == "in":
            return v in want
        if op == "not-in":
            return v not in want
        return v % self._modulus == want  # mod-eq

    def do_include_vectorized(self, columns, num_rows):
        import numpy as np

        column = np.asarray(columns[self._field])
        op, want = self._op, self._value
        if op == "eq":
            return column == want
        if op == "ne":
            return column != want
        if op == "lt":
            return column < want
        if op == "le":
            return column <= want
        if op == "gt":
            return column > want
        if op == "ge":
            return column >= want
        if op in ("in", "not-in"):
            mask = np.isin(column, np.asarray(want))
            return ~mask if op == "not-in" else mask
        return column % self._modulus == want  # mod-eq

    # -- the column-level (pyarrow compute) form ---------------------------

    def pa_mask(self, table):
        """Boolean keep-mask over ``table`` (which holds this predicate's
        column), computed with pyarrow compute kernels — no Python-object
        materialization of any row. The two-phase predicate read uses this
        to filter BOTH column reads down to survivors while they are still
        Arrow (dropped rows never decode, never materialize)."""
        import numpy as np
        import pyarrow.compute as pc

        column = table.column(self._field)
        op, want = self._op, self._value
        if op == "eq":
            mask = pc.equal(column, want)
        elif op == "ne":
            mask = pc.not_equal(column, want)
        elif op == "lt":
            mask = pc.less(column, want)
        elif op == "le":
            mask = pc.less_equal(column, want)
        elif op == "gt":
            mask = pc.greater(column, want)
        elif op == "ge":
            mask = pc.greater_equal(column, want)
        elif op in ("in", "not-in"):
            import pyarrow as pa

            mask = pc.is_in(column, value_set=pa.array(want))
            if op == "not-in":
                mask = pc.invert(mask)
        else:  # mod-eq: modulo has no stable pc kernel name across
            # pyarrow versions — evaluate in numpy, same result.
            values = np.asarray(column.to_numpy(zero_copy_only=False))
            return np.asarray(values % self._modulus == want)
        # Null storage values compare to null; a filter mask must be
        # definite — nulls drop, matching the row path's False.
        return np.asarray(mask.combine_chunks().to_numpy(
            zero_copy_only=False) if hasattr(mask, "combine_chunks")
            else mask.to_numpy(zero_copy_only=False)) == True  # noqa: E712

    # -- wire form (stream requests, cache-key ingredient) -----------------

    def to_wire(self):
        """JSON-safe canonical dict — the stream-request field and the
        cache-fingerprint ingredient (stable across processes)."""
        out = {"field": self._field, "op": self._op, "value": self._value}
        if self._modulus is not None:
            out["modulus"] = self._modulus
        return out

    @classmethod
    def from_wire(cls, wire):
        """Reconstruct from :meth:`to_wire` output (validates shape)."""
        if not isinstance(wire, dict) or "field" not in wire \
                or "op" not in wire:
            raise ValueError(
                f"ColumnPredicate wire form must be a dict with "
                f"field/op/value, got {wire!r}")
        return cls(wire["field"], wire["op"], wire.get("value"),
                   modulus=wire.get("modulus"))

    def __repr__(self):
        return (f"ColumnPredicate({self._field!r}, {self._op!r}, "
                f"{self._value!r}, modulus={self._modulus!r})")


class in_set(PredicateBase):
    """Keep rows whose ``predicate_field`` value is in ``inclusion_values``."""

    def __init__(self, inclusion_values, predicate_field):
        self._inclusion_values = set(inclusion_values)
        self._predicate_field = predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def do_include(self, values):
        return values[self._predicate_field] in self._inclusion_values

    def do_include_vectorized(self, columns, num_rows):
        import numpy as np

        column = np.asarray(columns[self._predicate_field])
        if column.dtype == object:
            # Object cells may be unhashable (lists): np.isin would silently
            # compare elementwise to all-False where the row path raises a
            # loud TypeError — decline and keep the row-path semantics.
            return None
        values = list(self._inclusion_values)
        try:
            values_arr = np.asarray(values)
        except (TypeError, ValueError):
            return None
        if values_arr.dtype == object:
            return None
        # np.isin compares in the promoted dtype; when that promotion turns
        # ints into float64, magnitudes past 2**53 collapse
        # (9007199254740993 -> ...992.0) and the mask matches rows the exact
        # Python comparison of the row path rejects. Both directions are
        # lossy (int column vs float values, float column vs int values) —
        # decline whenever any int on either side exceeds the exact range.
        limit = 2 ** 53
        try:
            # result_type raises DTypePromotionError (a TypeError) for
            # non-promotable pairs (e.g. datetime64 vs float) — decline to
            # the exact row path, same as np.isin failures.
            promoted = np.result_type(column.dtype, values_arr.dtype)
            if promoted.kind == "f":
                if any(isinstance(v, (int, np.integer))
                       and not isinstance(v, bool) and abs(int(v)) > limit
                       for v in values):
                    return None
                if (column.dtype.kind in "iu" and column.size
                        and int(np.abs(column).max()) > limit):
                    return None
            return np.isin(column, values_arr)
        except (TypeError, ValueError):  # exotic value types: row path
            return None

    def __repr__(self):
        return (f"in_set({sorted(map(repr, self._inclusion_values))}, "
                f"{self._predicate_field!r})")


class in_intersection(PredicateBase):
    """Keep rows whose ITERABLE ``predicate_field`` value shares at least
    one element with ``inclusion_values`` — the collection-valued
    counterpart of :class:`in_set` (a tag/category array column: keep the
    row if ANY tag is in the inclusion set). Upstream
    ``petastorm/predicates.py`` lists an ``in_intersection`` combinator;
    SURVEY.md §2.1 marks its exact semantics uncertain, so this implements
    the natural reading: non-empty set intersection. A scalar field value
    degrades to :class:`in_set` membership."""

    def __init__(self, inclusion_values, predicate_field):
        self._inclusion_values = set(inclusion_values)
        self._predicate_field = predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def do_include(self, values):
        import numpy as np

        value = values[self._predicate_field]
        items = np.asarray(value).ravel().tolist()
        return not self._inclusion_values.isdisjoint(items)

    def __repr__(self):
        return (f"in_intersection("
                f"{sorted(map(repr, self._inclusion_values))}, "
                f"{self._predicate_field!r})")


class in_lambda(PredicateBase):
    """Keep rows for which ``predicate_func(values [, state])`` is truthy.

    ``vectorized=True`` (our extension; no reference analogue) declares that
    ``predicate_func`` operates on whole numpy columns and returns a boolean
    mask — batch/columnar workers then evaluate it in one call instead of
    once per row: ``in_lambda(["x"], lambda cols: cols["x"] % 2 == 0,
    vectorized=True)``. Row readers still call it per row with scalar
    values; a numpy-ufunc-style function works for both.
    """

    def __init__(self, predicate_fields, predicate_func, state_arg=None,
                 vectorized=False):
        if not isinstance(predicate_fields, (list, tuple, set)):
            raise ValueError("predicate_fields must be a list/tuple/set of names")
        self._predicate_fields = set(predicate_fields)
        self._predicate_func = predicate_func
        self._state_arg = state_arg
        self._vectorized = vectorized

    def get_fields(self):
        return set(self._predicate_fields)

    def do_include(self, values):
        if self._state_arg is not None:
            return self._predicate_func(values, self._state_arg)
        return self._predicate_func(values)

    def do_include_vectorized(self, columns, num_rows):
        if not self._vectorized:
            return None
        import numpy as np

        if self._state_arg is not None:
            mask = self._predicate_func(columns, self._state_arg)
        else:
            mask = self._predicate_func(columns)
        mask = np.asarray(mask, dtype=bool)
        if mask.shape != (num_rows,):
            raise ValueError(
                f"vectorized predicate_func returned shape {mask.shape}, "
                f"expected ({num_rows},)")
        return mask

    def __repr__(self):
        return (f"in_lambda({sorted(self._predicate_fields)}, "
                f"{_func_fingerprint(self._predicate_func)}, "
                f"{_stable_repr(self._state_arg)}, "
                f"vectorized={self._vectorized})")


class in_negate(PredicateBase):
    """Logical NOT of another predicate."""

    def __init__(self, predicate):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)

    def do_include_vectorized(self, columns, num_rows):
        import numpy as np

        mask = self._predicate.do_include_vectorized(columns, num_rows)
        # asarray: the contract allows any bool-mask sequence (a list would
        # crash unary ~).
        return None if mask is None else ~np.asarray(mask, dtype=bool)

    def __repr__(self):
        return f"in_negate({self._predicate!r})"


class in_reduce(PredicateBase):
    """Combine several predicates with a reduction (``all``/``any``-style).

    ``reduce_func`` receives the list of per-predicate booleans.
    """

    def __init__(self, predicate_list, reduce_func):
        self._predicate_list = list(predicate_list)
        self._reduce_func = reduce_func

    def get_fields(self):
        fields = set()
        for predicate in self._predicate_list:
            fields |= predicate.get_fields()
        return fields

    def do_include(self, values):
        return self._reduce_func(
            [p.do_include(values) for p in self._predicate_list]
        )

    def do_include_vectorized(self, columns, num_rows):
        # Vectorizable only for the all/any builtins (arbitrary reductions
        # see a list of booleans, not arrays).
        import builtins

        import numpy as np

        if self._reduce_func is builtins.all:
            combine = np.logical_and.reduce
        elif self._reduce_func is builtins.any:
            combine = np.logical_or.reduce
        else:
            return None
        if not self._predicate_list:
            return None
        masks = []
        for predicate in self._predicate_list:
            mask = predicate.do_include_vectorized(columns, num_rows)
            if mask is None:  # short-circuit: don't waste the others' work
                return None
            masks.append(mask)
        return combine(masks)

    def __repr__(self):
        return (f"in_reduce({self._predicate_list!r}, "
                f"{_func_fingerprint(self._reduce_func)})")


class in_pseudorandom_split(PredicateBase):
    """Deterministic hash-of-field train/val/test splitting.

    ``fraction_list`` partitions [0, 1); a row belongs to subset ``i`` when
    the normalized md5 hash of its ``predicate_field`` value falls in the
    ``i``-th interval. The same value always lands in the same subset, on any
    host — which is what makes the split usable across a TPU pod with no
    coordination (reference parity: ``petastorm/predicates.py``).
    """

    def __init__(self, fraction_list, subset_index, predicate_field):
        if not 0 <= subset_index < len(fraction_list):
            raise ValueError(
                f"subset_index {subset_index} out of range for "
                f"{len(fraction_list)} fractions"
            )
        if sum(fraction_list) > 1.0 + 1e-9:
            raise ValueError(f"fractions sum to {sum(fraction_list)} > 1")
        self._fraction_list = list(fraction_list)
        self._subset_index = subset_index
        self._predicate_field = predicate_field

    def get_fields(self):
        return {self._predicate_field}

    def do_include(self, values):
        value = values[self._predicate_field]
        position = _hash_to_unit_interval(value)
        low = sum(self._fraction_list[: self._subset_index])
        high = low + self._fraction_list[self._subset_index]
        return low <= position < high

    def do_include_vectorized(self, columns, num_rows):
        # md5 itself cannot be numpy-vectorized, but hashing the bare column
        # values skips the per-row dict assembly + dispatch of the row path
        # (the actual cost on wide tabular scans).
        import numpy as np

        column = columns[self._predicate_field]
        low = sum(self._fraction_list[: self._subset_index])
        high = low + self._fraction_list[self._subset_index]
        mask = np.empty(num_rows, dtype=bool)
        for i in range(num_rows):
            position = _hash_to_unit_interval(column[i])
            mask[i] = low <= position < high
        return mask

    def __repr__(self):
        return (f"in_pseudorandom_split({self._fraction_list!r}, "
                f"{self._subset_index!r}, {self._predicate_field!r})")


def evaluate_predicate_mask(predicate, columns, num_rows):
    """Boolean keep-mask for ``num_rows`` rows of ``columns`` (name→array).

    Tries the predicate's columnar fast path (``do_include_vectorized``)
    first; falls back to the per-row ``do_include`` loop. Shared by the
    batch and columnar workers."""
    import numpy as np

    vectorized = predicate.do_include_vectorized(columns, num_rows)
    if vectorized is not None:
        return np.asarray(vectorized, dtype=bool)
    mask = np.empty(num_rows, dtype=bool)
    names = list(columns)
    for i in range(num_rows):
        mask[i] = bool(predicate.do_include(
            {name: columns[name][i] for name in names}))
    return mask


def _hash_to_unit_interval(value):
    if isinstance(value, bytes):
        data = value
    else:
        data = str(value).encode("utf-8")
    digest = hashlib.md5(data).hexdigest()  # noqa: S324 - splitting, not security
    return int(digest, 16) / float(1 << 128)
