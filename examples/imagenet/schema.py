"""ImageNet petastorm schema.

Reference analogue: ``examples/imagenet/schema.py`` — same field shapes
(noun_id, text, 375x500x3 uint8 png image), BASELINE.md config #2 pattern.
"""

import numpy as np

from petastorm_tpu.schema.codecs import CompressedImageCodec, ScalarCodec
from petastorm_tpu.schema.unischema import Unischema, UnischemaField

ImagenetSchema = Unischema("ImagenetSchema", [
    UnischemaField("noun_id", str, (), ScalarCodec(), False),
    UnischemaField("text", str, (), ScalarCodec(), False),
    UnischemaField("image", np.uint8, (375, 500, 3),
                   CompressedImageCodec("png"), False),
])
