"""JAX/TPU delivery layer — the rebuild's north-star addition.

The reference (``petastorm``, SURVEY.md §3 "boundary summary") never owns the
device boundary: TF/Torch adapters hand numpy to the framework and the user
calls ``.to(device)``. On TPU that design leaves HBM staging, per-host batch
cardinality, and input-stall measurement to every user. This package owns all
three:

- :func:`make_jax_dataloader` — fixed-size numpy batches with an explicit
  pad/drop policy (equal per-host step counts for SPMD lockstep), staged into
  device HBM via double-buffered async ``jax.device_put`` (or emitted as
  globally-sharded ``jax.Array`` s via
  ``jax.make_array_from_process_local_data`` when a sharding is given);
- NGram windows collate to ``[B, T, ...]`` arrays;
- built-in input-stall instrumentation (``loader.diagnostics``) — the
  north-star metric (BASELINE.md).
"""

from petastorm_tpu.jax_utils.batcher import (
    batch_iterator,
    collate_ngram_rows,
    collate_rows,
)
from petastorm_tpu.jax_utils.checkpoint import (
    restore_training_state,
    save_training_state,
)
from petastorm_tpu.jax_utils.device_stage import DeviceStage
from petastorm_tpu.jax_utils.loader import JaxDataLoader, make_jax_dataloader
from petastorm_tpu.jax_utils.packing import (
    PACK_POSITION_KEY,
    PACK_SEGMENT_KEY,
    count_packed_batches,
    iter_ragged_rows,
    make_packed_jax_dataloader,
    pack_ragged,
    packed_valid_mask,
)
from petastorm_tpu.jax_utils.sharding import (
    agree_max_batches,
    batch_sharding,
    count_deliverable_batches,
    default_shard_options,
    derive_equal_step_max_batches,
    global_step_count,
    local_data_to_global_array,
)

__all__ = [
    "make_jax_dataloader",
    "JaxDataLoader",
    "DeviceStage",
    "batch_iterator",
    "collate_rows",
    "collate_ngram_rows",
    "default_shard_options",
    "batch_sharding",
    "global_step_count",
    "derive_equal_step_max_batches",
    "agree_max_batches",
    "count_deliverable_batches",
    "local_data_to_global_array",
    "save_training_state",
    "restore_training_state",
    "pack_ragged",
    "packed_valid_mask",
    "count_packed_batches",
    "make_packed_jax_dataloader",
    "iter_ragged_rows",
    "PACK_SEGMENT_KEY",
    "PACK_POSITION_KEY",
]
