"""Mix several readers with given sampling probabilities.

Reference parity: ``petastorm/weighted_sampling_reader.py::WeightedSamplingReader``
— dataset mixing (BASELINE.md config #5 uses it for the multi-corpus shuffle).
"""

from __future__ import annotations

import logging
import random

logger = logging.getLogger(__name__)


class WeightedSamplingReader:
    """``next()`` draws from ``readers[i]`` with probability ``probabilities[i]``
    (normalized). Iteration stops when the drawn reader is exhausted
    (reference semantics: StopIteration propagates)."""

    def __init__(self, readers, probabilities, random_seed=None):
        if len(readers) != len(probabilities):
            raise ValueError(
                f"len(readers)={len(readers)} != len(probabilities)={len(probabilities)}"
            )
        if not readers:
            raise ValueError("At least one reader is required")
        total = float(sum(probabilities))
        if total <= 0:
            raise ValueError("Probabilities must sum to a positive value")
        self._readers = list(readers)
        self._cum = []
        acc = 0.0
        for p in probabilities:
            acc += p / total
            self._cum.append(acc)
        if random_seed is None:
            # Reference parity keeps the nondeterministic default, but
            # nothing downstream of it is reproducible or checkpointable
            # — the service-grade replacement is the seed-tree sampler.
            logger.warning(
                "WeightedSamplingReader(random_seed=None) draws from an "
                "unseeded RNG: the mix is not reproducible or "
                "resumable. Pass an explicit seed, or use "
                "petastorm_tpu.service.mixture.MixedBatchSource (seeded, "
                "checkpointable, hot-reloadable — docs/guides/llm.md)")
        self._random = random.Random(random_seed)

        # Mixing requires compatible row types; expose the first reader's
        # schema/ngram like a plain reader so adapters can wrap us.
        first = readers[0]
        self.schema = getattr(first, "schema", None)
        self.ngram = getattr(first, "ngram", None)
        self.batched_output = getattr(first, "batched_output", False)

    def __iter__(self):
        return self

    def __next__(self):
        draw = self._random.random()
        for index, threshold in enumerate(self._cum):
            if draw < threshold:
                return next(self._readers[index])
        return next(self._readers[-1])  # guard for fp rounding at 1.0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()

    def stop(self):
        for reader in self._readers:
            reader.stop()

    def join(self):
        for reader in self._readers:
            reader.join()
