"""LLM sequence-packing workload through the service (ISSUE 14).

Pins the new subsystem's contracts end to end (docs/guides/llm.md):

- packing as a pipeline stage with worker- AND trainer-side placement —
  packed batches piece-aligned worker-side, carry-over checkpointable
  trainer-side, cache entries holding packed frames whose batch count is
  not derivable from row count;
- deterministic weighted mixtures: seed-tree sampler (explicit seed
  required), exhaustion policies, checkpoint/resume, multi-corpus fleets
  under ONE dispatcher via per-corpus worker groups;
- hot-reloadable mixture weights: the journaled ``mixture_weights`` WAL
  op, applied at a deterministic pass boundary, replayed byte-identically
  across dispatcher restarts — the served stream a pure function of
  (seed, weight-change log);
- chaos: a packed, mixed, shuffled 2-pass run under worker-kill is
  zero-dup/zero-loss with a byte-identical stream digest (slow).
"""

import hashlib

import numpy as np
import pytest

from petastorm_tpu.service import (
    BatchWorker,
    Dispatcher,
    MixedBatchSource,
    MixtureSampler,
    MixtureSpec,
    PackedBatchSource,
    PackingSpec,
    ServiceBatchSource,
    get_mixture_weights,
    set_mixture_weights,
)
from petastorm_tpu.service.mixture import (
    MixtureExhausted,
    validate_weights,
)
from petastorm_tpu.service.packing_stage import (
    PACK_SEGMENT_KEY,
)

pytestmark = pytest.mark.service

SPEC = PackingSpec(slot_len=64, slots=2, sequence_fields=["tokens"],
                   length_field="length")
READER_KWARGS = {"reader_pool_type": "thread", "workers_count": 1,
                 "schema_fields": ["tokens", "length"]}


@pytest.fixture(scope="module")
def token_dataset(tmp_path_factory):
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_token_dataset,
    )

    path = tmp_path_factory.mktemp("llm") / "tok_a"
    url = f"file://{path}"
    rows = create_test_token_dataset(url, rows_count=40,
                                     rows_per_row_group=10)
    return url, rows


@pytest.fixture(scope="module")
def token_dataset_b(tmp_path_factory):
    from petastorm_tpu.test_util.dataset_factory import (
        create_test_token_dataset,
    )

    path = tmp_path_factory.mktemp("llm") / "tok_b"
    url = f"file://{path}"
    rows = create_test_token_dataset(url, rows_count=30,
                                     rows_per_row_group=10, skew=1.5)
    return url, rows


def _digest(batches):
    h = hashlib.blake2b(digest_size=16)
    for batch in batches:
        for key in sorted(batch):
            arr = np.ascontiguousarray(np.asarray(batch[key]))
            h.update(key.encode())
            h.update(str(arr.dtype).encode())
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
    return h.hexdigest()


def _token_worker(url, dispatcher, corpus="", **kwargs):
    return BatchWorker(url, dispatcher_address=dispatcher.address,
                       batch_size=8, reader_factory="row", corpus=corpus,
                       reader_kwargs=dict(READER_KWARGS), **kwargs).start()


def _unpacked_multiset(batches):
    """The multiset of original sequences across packed batches."""
    from petastorm_tpu.jax_utils.packing import unpack

    out = []
    for batch in batches:
        out.extend(tuple(int(x) for x in seq)
                   for seq in unpack(batch, "tokens"))
    return sorted(out)


# ---------------------------------------------------------------------------
# mixture sampler / spec units
# ---------------------------------------------------------------------------

def test_mixture_sampler_requires_explicit_seed():
    with pytest.raises(ValueError, match="explicit seed"):
        MixtureSampler(None, {"a": 1.0})


def test_mixture_sampler_deterministic_and_ratio_shaped():
    a = MixtureSampler(5, {"x": 0.75, "y": 0.25})
    b = MixtureSampler(5, {"x": 0.75, "y": 0.25})
    draws = [a.draw() for _ in range(400)]
    assert draws == [b.draw() for _ in range(400)]
    frac = draws.count("x") / len(draws)
    assert 0.65 < frac < 0.85  # weight-shaped, not exact


def test_mixture_sampler_epoch_changes_sequence():
    a = [MixtureSampler(5, {"x": 0.5, "y": 0.5}, epoch=0).draw()
         for _ in range(1)]
    seq0 = MixtureSampler(5, {"x": 0.5, "y": 0.5}, epoch=0)
    seq1 = MixtureSampler(5, {"x": 0.5, "y": 0.5}, epoch=1)
    assert [seq0.draw() for _ in range(64)] \
        != [seq1.draw() for _ in range(64)]
    assert a  # epoch-0 draw deterministic (smoke for the fold path)


def test_mixture_sampler_state_dict_resume_replays():
    a = MixtureSampler(9, {"x": 0.6, "y": 0.4})
    for _ in range(37):
        a.draw()
    b = MixtureSampler(9, {"x": 0.6, "y": 0.4})
    b.load_state_dict(a.state_dict())
    assert [a.draw() for _ in range(50)] == [b.draw() for _ in range(50)]


def test_mixture_exhaustion_policies():
    stop = MixtureSampler(3, {"x": 0.5, "y": 0.5}, exhaustion="stop")
    stop.draw()
    with pytest.raises(MixtureExhausted):
        stop.mark_exhausted("x")

    drain = MixtureSampler(3, {"x": 0.5, "y": 0.5}, exhaustion="exhaust")
    drain.draw()
    assert drain.mark_exhausted("x") == "y"  # deterministic re-roll
    assert drain.live_names() == ["y"]
    with pytest.raises(MixtureExhausted):
        drain.mark_exhausted("y")

    rew = MixtureSampler(3, {"x": 0.5, "y": 0.5}, exhaustion="reweight")
    rew.draw()
    assert rew.mark_exhausted("x") == "y"
    # the drop-out landed in the weight log as an explicit entry
    state = rew.state_dict()
    assert state["applied"][-1][1]["x"] == 0.0
    assert "exhausted:x" in state["applied"][-1][2]


def test_mixture_spec_and_weight_validation():
    with pytest.raises(ValueError, match="duplicate"):
        MixtureSpec([("a", None, 1.0), ("a", None, 1.0)])
    with pytest.raises(ValueError, match="positive"):
        MixtureSpec([("a", None, 0.0)])
    spec = MixtureSpec([("a", "file:///x", 2.0), ("b", None, 1.0)])
    assert MixtureSpec.from_dict(spec.to_dict()).names == ["a", "b"]
    with pytest.raises(ValueError, match="unknown corpora"):
        validate_weights({"zz": 1.0}, names=["a", "b"])
    with pytest.raises(ValueError, match="negative"):
        validate_weights({"a": -1.0})


# ---------------------------------------------------------------------------
# packing spec / placement units
# ---------------------------------------------------------------------------

def test_packing_spec_validation_and_round_trip():
    with pytest.raises(ValueError, match="at least one field"):
        PackingSpec(8, 2, [])
    with pytest.raises(ValueError, match="positive"):
        PackingSpec(0, 2, ["t"])
    with pytest.raises(ValueError, match="cannot also be"):
        PackingSpec(8, 2, ["t"], length_field="t")
    spec = PackingSpec(8, 2, ["t"], length_field="n")
    assert PackingSpec.from_dict(spec.to_dict()) == spec


class _ListSource:
    """Minimal batch source over canned row batches (trainer-placement
    packing needs nothing more). Honors the resume contract: a prior
    state_dict passed back as ``resume`` skips the consumed prefix."""

    def __init__(self, batches, resume=None):
        self._batches = batches
        self._skip = int(resume["consumed"]) if resume else 0
        self.calls = 0

    def __call__(self):
        self.calls += 1
        return iter([dict(b) for b in self._batches[self._skip:]])

    def state_dict(self, yielded_batches=None):
        return {"consumed": self._skip + int(yielded_batches or 0)}


def _row_batches(lengths, max_len=32, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for chunk in np.array_split(np.asarray(lengths), 4):
        tokens = np.zeros((len(chunk), max_len), np.int32)
        for i, n in enumerate(chunk):
            tokens[i, :n] = rng.randint(1, 999, size=int(n))
        out.append({"tokens": tokens,
                    "length": np.asarray(chunk, np.int32)})
    return out


def test_packed_source_trainer_placement_and_checkpoint():
    spec = PackingSpec(32, 2, ["tokens"], length_field="length")
    lengths = [5, 30, 11, 7, 22, 3, 18, 9, 27, 4, 15, 8]
    source = _ListSource(_row_batches(lengths))
    packed_all = list(PackedBatchSource(source, spec,
                                        placement="trainer")())
    assert packed_all and all(
        b[PACK_SEGMENT_KEY].shape == (2, 32) for b in packed_all)

    # checkpoint at every consumer position: resume replays bit-exactly
    for cut in range(len(packed_all)):
        wrapper = PackedBatchSource(_ListSource(_row_batches(lengths)),
                                    spec, placement="trainer")
        it = wrapper()
        got = [next(it) for _ in range(cut)]
        state = wrapper.state_dict(yielded_batches=cut)
        assert state["placement"] == "trainer"
        resumed = PackedBatchSource(
            _ListSource(_row_batches(lengths), resume=state["inner"]),
            spec, placement="trainer", resume_state=state)
        got += list(resumed())
        assert len(got) == len(packed_all)
        for a, b in zip(got, packed_all):
            for key in a:
                np.testing.assert_array_equal(a[key], b[key])
        it.close()


def test_packed_source_placement_flip_validates():
    spec = PackingSpec(32, 2, ["tokens"], length_field="length")
    wrapper = PackedBatchSource(_ListSource(_row_batches([4, 5])), spec,
                                placement="trainer")
    with pytest.raises(ValueError, match="worker' or 'trainer"):
        wrapper.set_packing_placement("device")
    # worker placement needs a source that forwards the spec
    wrapper.set_packing_placement("worker")
    with pytest.raises(ValueError, match="set_packing|forwards"):
        wrapper()


# ---------------------------------------------------------------------------
# dispatcher: mixture control plane + per-corpus registration
# ---------------------------------------------------------------------------

def test_set_mixture_weights_journaled_and_replayed(tmp_path):
    journal = str(tmp_path / "wal")
    with Dispatcher(mode="static", num_epochs=1,
                    journal_dir=journal).start() as disp:
        r1 = set_mixture_weights(disp.address, {"a": 0.7, "b": 0.3},
                                 job_id="default", effective_epoch=2)
        r2 = set_mixture_weights(disp.address, {"a": 0.2, "b": 0.8},
                                 job_id="default", effective_epoch=5)
        assert (r1["seq"], r2["seq"]) == (1, 2)
        log = get_mixture_weights(disp.address)
        assert [e["seq"] for e in log["entries"]] == [1, 2]
        before = disp.state_snapshot()["mixtures"]
    with Dispatcher(mode="static", num_epochs=1,
                    journal_dir=journal).start() as disp2:
        after = disp2.state_snapshot()["mixtures"]
        assert after == before  # byte-identical replay
        log2 = get_mixture_weights(disp2.address)
        assert log2["entries"] == log["entries"]


def test_set_mixture_weights_validates_and_fences():
    with Dispatcher(mode="static", num_epochs=1).start() as disp:
        with pytest.raises(Exception, match="positive"):
            set_mixture_weights(disp.address, {"a": 0.0})
        set_mixture_weights(disp.address, {"a": 1.0})
        # a stale fencing token is told to resync, not journaled
        from petastorm_tpu.reader_impl.framed_socket import (
            FramedConnection,
        )

        disp._bump_fencing_locked("test")
        with FramedConnection.connect(disp.address, timeout=5.0) as conn:
            reply, _ = conn.request({
                "type": "set_mixture_weights", "job_id": "default",
                "weights": {"a": 2.0}, "fencing_epoch": 0})
        assert reply["type"] == "stale_fencing"
        assert get_mixture_weights(disp.address)["seq"] == 1


def test_mixture_seq_idempotent_under_replayed_record():
    disp = Dispatcher(mode="static", num_epochs=1)
    with disp._lock:
        assert disp._install_mixture_locked("j", 1, {"a": 1.0}, None)
        assert not disp._install_mixture_locked("j", 1, {"a": 9.0}, None)
        assert disp._mixtures["j"]["entries"][0]["weights"] == {"a": 1.0}


def test_per_corpus_registration_and_piece_universes(
        token_dataset, token_dataset_b):
    url_a, _ = token_dataset
    url_b, _ = token_dataset_b
    with Dispatcher(mode="static", num_epochs=1).start() as disp:
        wa = _token_worker(url_a, disp, corpus="a")
        wb = _token_worker(url_b, disp, corpus="b")
        try:
            snap = disp.state_snapshot()
            assert snap["corpus_pieces"] == {"a": 4, "b": 3}
            # a same-corpus worker over a different-shaped dataset is
            # refused with the corpus named
            bad = BatchWorker(url_b, dispatcher_address=disp.address,
                              batch_size=8, reader_factory="row",
                              corpus="a", register_retries=0,
                              reader_kwargs=dict(READER_KWARGS))
            with pytest.raises(RuntimeError, match="corpus 'a'"):
                bad.start()
            bad.stop()
        finally:
            wa.stop()
            wb.stop()


# ---------------------------------------------------------------------------
# packed service runs (worker placement, end to end)
# ---------------------------------------------------------------------------

def test_packed_service_stream_deterministic_and_piece_aligned(
        token_dataset):
    url, _ = token_dataset
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=7).start() as disp:
        worker = _token_worker(url, disp)
        try:
            runs = []
            for _ in range(2):
                source = ServiceBatchSource(disp.address, ordered=True,
                                            packing=SPEC)
                runs.append(list(source()))
            assert _digest(runs[0]) == _digest(runs[1])
            assert all(b["tokens"].shape == (2, 64) for b in runs[0])
            # every original sequence served exactly once, intact
            from petastorm_tpu.jax_utils.packing import unpack

            seqs = []
            for batch in runs[0]:
                seqs.extend(unpack(batch, "tokens"))
            assert len(seqs) == 40
        finally:
            worker.stop()


def test_packed_placement_parity_worker_vs_trainer(token_dataset):
    """Both placements serve the SAME sequence multiset (batch
    boundaries legally differ: worker-side flushes per piece,
    trainer-side carries over)."""
    url, _ = token_dataset
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=3).start() as disp:
        worker = _token_worker(url, disp)
        try:
            worker_side = list(PackedBatchSource(
                ServiceBatchSource(disp.address, ordered=True), SPEC,
                placement="worker")())
            trainer_side = list(PackedBatchSource(
                ServiceBatchSource(disp.address, ordered=True), SPEC,
                placement="trainer")())
            assert _unpacked_multiset(worker_side) \
                == _unpacked_multiset(trainer_side)
        finally:
            worker.stop()


def test_packed_resume_mid_pack_bit_exact(token_dataset):
    """Kill-then-restore mid-pack: consume k packed batches, snapshot,
    rebuild the source from the snapshot — the resumed stream
    concatenates to the uninterrupted run byte-for-byte (watermarks
    number PACKED batches)."""
    url, _ = token_dataset
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=11).start() as disp:
        worker = _token_worker(url, disp)
        try:
            full = list(ServiceBatchSource(disp.address, ordered=True,
                                           packing=SPEC)())
            for cut in (1, 5, len(full) - 1):
                source = ServiceBatchSource(disp.address, ordered=True,
                                            packing=SPEC)
                it = source()
                got = [next(it) for _ in range(cut)]
                state = source.state_dict(yielded_batches=cut)
                assert state["packing"] == SPEC.to_dict()
                it.close()
                resumed = ServiceBatchSource(disp.address, ordered=True,
                                             packing=SPEC,
                                             resume_state=state)
                got += list(resumed())
                assert len(got) == len(full), f"cut={cut}"
                assert _digest(got) == _digest(full), f"cut={cut}"
        finally:
            worker.stop()


def test_packed_resume_refuses_spec_mismatch(token_dataset):
    url, _ = token_dataset
    with Dispatcher(mode="static", num_epochs=1).start() as disp:
        worker = _token_worker(url, disp)
        try:
            source = ServiceBatchSource(disp.address, ordered=True,
                                        packing=SPEC)
            it = source()
            next(it)
            state = source.state_dict(yielded_batches=1)
            it.close()
            other = PackingSpec(slot_len=32, slots=4,
                                sequence_fields=["tokens"],
                                length_field="length")
            with pytest.raises(ValueError, match="packing mismatch"):
                ServiceBatchSource(disp.address, ordered=True,
                                   packing=other, resume_state=state)
        finally:
            worker.stop()


def test_packed_cache_entries_hold_packed_frames(token_dataset):
    """Cache + packing: epoch 2 serves every piece warm (hit rate 1.0)
    with the entries' batch counts equal to the PACKED emission — not
    derivable from row count — and byte-identical batches."""
    from petastorm_tpu.cache_impl import CacheConfig

    url, _ = token_dataset
    with Dispatcher(mode="static", num_epochs=2).start() as disp:
        worker = _token_worker(
            url, disp,
            batch_cache=CacheConfig(mode="mem", mem_mb=64.0).build())
        try:
            source = ServiceBatchSource(disp.address, ordered=True,
                                        packing=SPEC)
            batches = list(source())
            by_epoch = worker.cache_stats_by_epoch()
            assert by_epoch[0]["misses"] == 4 and by_epoch[0]["hits"] == 0
            assert by_epoch[1]["hits"] == 4 and by_epoch[1]["misses"] == 0
            half = len(batches) // 2
            assert _digest(batches[:half]) == _digest(batches[half:])
            # packed entries: total cached batches == packed emission of
            # one epoch, and rows (slots) != source row count
            stats = worker._batch_cache.stats()
            assert stats["entries_mem"] == 4
            cached_batches = sum(
                entry.num_batches
                for entry in worker._batch_cache._entries.values())
            assert cached_batches == half
            assert cached_batches < 40  # not the row count
        finally:
            worker.stop()


def test_packing_rejected_on_fcfs_and_with_transform(token_dataset):
    url, _ = token_dataset
    with pytest.raises(ValueError, match="cannot combine"):
        ServiceBatchSource(("127.0.0.1", 1), packing=SPEC,
                           transform=lambda b: b)
    with Dispatcher(mode="fcfs", num_epochs=1).start() as disp:
        worker = _token_worker(url, disp)
        try:
            source = ServiceBatchSource(disp.address, packing=SPEC)
            with pytest.raises(ValueError, match="fcfs"):
                source()
            source2 = ServiceBatchSource(disp.address, corpus="zz")
            with pytest.raises(ValueError, match="fcfs"):
                source2()
        finally:
            worker.stop()


# ---------------------------------------------------------------------------
# multi-corpus mixture through one dispatcher
# ---------------------------------------------------------------------------

def _mixture(disp, seed=17, weights=None, exhaustion="stop", job="default",
             packing=SPEC, dispatcher_address=True):
    def factory(corpus):
        return lambda: ServiceBatchSource(disp.address, corpus=corpus,
                                          ordered=True, packing=packing,
                                          job_id=(None if job == "default"
                                                  else job))

    return MixedBatchSource(
        {"a": factory("a"), "b": factory("b")},
        weights=dict(weights or {"a": 0.6, "b": 0.4}), seed=seed,
        exhaustion=exhaustion,
        dispatcher_address=(disp.address if dispatcher_address else None),
        job_id=job, factories=True)


def test_mixed_packed_service_digest_pure_function_of_seed_and_log(
        token_dataset, token_dataset_b):
    url_a, _ = token_dataset
    url_b, _ = token_dataset_b
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=23).start() as disp:
        wa = _token_worker(url_a, disp, corpus="a")
        wb = _token_worker(url_b, disp, corpus="b")
        try:
            digests = []
            for _ in range(2):
                mix = _mixture(disp)
                digests.append(_digest(list(mix())))
            assert digests[0] == digests[1]
            # a different mixture seed serves a different stream
            assert _digest(list(_mixture(disp, seed=18)())) != digests[0]
        finally:
            wa.stop()
            wb.stop()


def test_mixture_weight_reload_applies_at_pass_boundary(
        token_dataset, token_dataset_b):
    url_a, _ = token_dataset
    url_b, _ = token_dataset_b
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=23).start() as disp:
        wa = _token_worker(url_a, disp, corpus="a")
        wb = _token_worker(url_b, disp, corpus="b")
        try:
            mix = _mixture(disp, weights={"a": 0.9, "b": 0.1},
                           exhaustion="stop")
            list(mix())
            pass1 = dict(mix.diagnostics["mixture"]["draws"])
            reply = set_mixture_weights(disp.address,
                                        {"a": 0.1, "b": 0.9},
                                        effective_epoch=1)
            assert reply["seq"] == 1
            list(mix())
            pass2 = dict(mix.diagnostics["mixture"]["draws"])
            total1 = max(sum(pass1.values()), 1)
            total2 = max(sum(pass2.values()), 1)
            assert pass1.get("a", 0) / total1 > 0.6
            assert pass2.get("b", 0) / total2 > 0.6
            assert mix.diagnostics["mixture"]["weights"] == {
                "a": 0.1, "b": 0.9}
        finally:
            wa.stop()
            wb.stop()


def test_mixture_reload_reproducible_from_log(token_dataset,
                                              token_dataset_b):
    """The acceptance digest: same seed + same weight-change log =>
    byte-identical two-pass stream, reload included."""
    url_a, _ = token_dataset
    url_b, _ = token_dataset_b
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=31).start() as disp:
        wa = _token_worker(url_a, disp, corpus="a")
        wb = _token_worker(url_b, disp, corpus="b")
        try:
            set_mixture_weights(disp.address, {"a": 0.2, "b": 0.8},
                                effective_epoch=1)

            def two_pass_digest():
                mix = _mixture(disp, weights={"a": 0.8, "b": 0.2})
                batches = list(mix()) + list(mix())
                return _digest(batches)

            assert two_pass_digest() == two_pass_digest()
        finally:
            wa.stop()
            wb.stop()


# ---------------------------------------------------------------------------
# chaos: packed + mixed + shuffled under worker-kill (slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_packed_mixed_shuffled_worker_kill_byte_identical(
        token_dataset, token_dataset_b):
    """The ISSUE 14 chaos acceptance: a packed, mixed, shuffled 2-pass
    run with a worker killed mid-pass is zero-dup/zero-loss with a
    byte-identical stream digest vs the unperturbed same-seed run
    (takeover re-serves at packed watermarks inside the corpus's worker
    group)."""
    url_a, _ = token_dataset
    url_b, _ = token_dataset_b

    def run(kill=False):
        with Dispatcher(mode="static", num_epochs=1,
                        shuffle_seed=41).start() as disp:
            workers = [
                _token_worker(url_a, disp, corpus="a",
                              worker_id="chaos-a0"),
                _token_worker(url_a, disp, corpus="a",
                              worker_id="chaos-a1"),
                _token_worker(url_b, disp, corpus="b",
                              worker_id="chaos-b0"),
                _token_worker(url_b, disp, corpus="b",
                              worker_id="chaos-b1"),
            ]
            try:
                batches = []
                mix = _mixture(disp, seed=43,
                               weights={"a": 0.5, "b": 0.5})
                for pass_index in range(2):
                    it = iter(mix())
                    first = next(it, None)
                    if first is not None:
                        batches.append(first)
                    if kill and pass_index == 0:
                        # Synchronous mid-stream kill: the rest of the
                        # pass MUST ride the takeover path (corpus-a
                        # pieces re-granted to the surviving corpus-a
                        # worker at their packed watermarks).
                        workers[0].kill()
                    batches.extend(it)
                return batches
            finally:
                for worker in workers:
                    worker.stop()

    clean = run(kill=False)
    chaotic = run(kill=True)
    assert len(chaotic) == len(clean)  # zero-dup / zero-loss
    assert _digest(chaotic) == _digest(clean)


# ---------------------------------------------------------------------------
# pipeline graph: the pack stage and its placement knob
# ---------------------------------------------------------------------------

def test_graph_declares_pack_stage_and_placement_knob():
    from petastorm_tpu.jax_utils.loader import JaxDataLoader
    from petastorm_tpu.pipeline.graph import build_loader_graph

    source = PackedBatchSource(
        ServiceBatchSource(("127.0.0.1", 1)), SPEC, placement="worker")
    loader = JaxDataLoader(None, SPEC.slots, batch_source=source,
                           stage_to_device=False)
    graph = build_loader_graph(loader)
    pack = graph.node("pack")
    assert pack.placement == "worker"
    knob = graph.knobs["packing_placement"]
    assert tuple(knob.descriptor()["choices"]) == ("worker", "trainer")
    knob.set("trainer")
    assert source.packing_placement == "trainer"
    assert graph.node("pack").placement == "trainer"
    assert ("collate", "pack") in graph.edges
    assert ("pack", "serialize") in graph.edges
    # an unpacked source declares no pack node and no knob
    plain = ServiceBatchSource(("127.0.0.1", 1))
    loader2 = JaxDataLoader(None, 4, batch_source=plain,
                            stage_to_device=False)
    graph2 = build_loader_graph(loader2)
    with pytest.raises(KeyError):
        graph2.node("pack")
    assert "packing_placement" not in graph2.knobs


def test_packed_dynamic_two_epochs_deterministic(token_dataset):
    """Dynamic sharding × packing: a 2-epoch packed run over two workers
    (steals live, ordinals numbering packed batches, dedup by
    (piece, generation)) is byte-deterministic across repeats."""
    url, _ = token_dataset
    with Dispatcher(mode="dynamic", num_epochs=2,
                    shuffle_seed=9).start() as disp:
        w1 = _token_worker(url, disp, worker_id="dyn-w0")
        w2 = _token_worker(url, disp, worker_id="dyn-w1")
        try:
            runs = []
            for _ in range(2):
                source = ServiceBatchSource(disp.address, ordered=True,
                                            packing=SPEC,
                                            dynamic_sync_interval_s=0.1)
                runs.append(list(source()))
            assert len(runs[0]) == len(runs[1])
            assert _digest(runs[0]) == _digest(runs[1])
            # two epochs of the same 4-piece dataset: epoch 2's packed
            # emission repeats epoch 1's bytes as a multiset (the piece
            # order differs per epoch under the seed tree)
            half = len(runs[0]) // 2
            assert sorted(_unpacked_multiset(runs[0][:half])) \
                == sorted(_unpacked_multiset(runs[0][half:]))
        finally:
            w1.stop()
            w2.stop()


# ---------------------------------------------------------------------------
# review-fix regressions
# ---------------------------------------------------------------------------

def test_quarantine_is_corpus_scoped():
    """Corpus A's poison piece 3 must not block corpus B's healthy piece
    3 — and B's own piece 3 turning poison must still be recordable."""
    disp = Dispatcher(mode="static", num_epochs=1)
    with disp._lock:
        assert disp._quarantine_piece_locked(3, {"corpus": "a",
                                                 "error": "boom"})
        assert disp._grantable_pieces_locked([1, 3], corpus="b") == [1, 3]
        assert disp._grantable_pieces_locked([1, 3], corpus="a") == [1]
        # B's piece 3 is independently quarantinable (not a duplicate)
        assert disp._quarantine_piece_locked(3, {"corpus": "b",
                                                 "error": "boom"})
        assert disp._grantable_pieces_locked([3], corpus="b") == []
    # round-trips through the snapshot shape
    snap = disp.state_snapshot()
    assert set(snap["quarantined"]) == {"a:3", "b:3"}
    disp2 = Dispatcher(mode="static", num_epochs=1)
    with disp2._lock:
        disp2._install_state_locked(snap)
        assert disp2._grantable_pieces_locked([3], corpus="a") == []
        assert disp2._grantable_pieces_locked([3], corpus="") == [3]


def test_set_mixture_weights_retry_token_is_idempotent():
    """A retried RPC (same idempotency token — the dropped-reply case)
    must answer for the already-journaled entry, not double-apply."""
    from petastorm_tpu.reader_impl.framed_socket import FramedConnection

    with Dispatcher(mode="static", num_epochs=1).start() as disp:
        header = {"type": "set_mixture_weights", "job_id": "default",
                  "weights": {"a": 1.0}, "token": "tok-1"}
        replies = []
        for _ in range(2):
            with FramedConnection.connect(disp.address,
                                          timeout=5.0) as conn:
                reply, _ = conn.request(dict(header))
            replies.append(reply)
        assert [r["seq"] for r in replies] == [1, 1]
        assert get_mixture_weights(disp.address)["seq"] == 1


def test_bad_weight_log_entry_does_not_wedge_the_mixture():
    """A journaled entry naming an unknown corpus (operator typo) is
    dropped with a warning — the mix keeps serving and a corrected
    later entry still applies."""
    sources = {"a": lambda: iter([]), "b": lambda: iter([])}

    class _Empty:
        def __call__(self):
            return iter([{"tokens": np.zeros((1, 4), np.int32),
                          "length": np.asarray([2], np.int32)}])

    mix = MixedBatchSource({"a": _Empty(), "b": _Empty()},
                           {"a": 0.5, "b": 0.5}, seed=3,
                           exhaustion="stop")
    del sources
    mix._pending_entries = [
        {"seq": 1, "weights": {"typo": 1.0}, "effective_epoch": 0},
        {"seq": 2, "weights": {"a": 0.9, "b": 0.1}, "effective_epoch": 0},
    ]
    batches = list(mix())
    assert batches  # the pass served despite the bad entry
    assert mix._applied_seq == 2
    assert mix.diagnostics["mixture"]["weights"] == {"a": 0.9, "b": 0.1}


def test_packed_source_checkpoint_of_a_resume_is_exact():
    """Checkpoint → resume → checkpoint again → resume again: the
    loader's instance-relative yielded_batches counts must translate
    through the resume cut, so a second-generation resume still
    concatenates bit-exactly."""
    spec = PackingSpec(32, 2, ["tokens"], length_field="length")
    lengths = [5, 30, 11, 7, 22, 3, 18, 9, 27, 4, 15, 8, 21, 6, 13]
    full = list(PackedBatchSource(_ListSource(_row_batches(lengths)),
                                  spec, placement="trainer")())
    for cut1 in (1, 2, 3):
        for cut2 in (0, 1, 2):
            if cut1 + cut2 >= len(full):
                continue  # nothing left for the second generation
            w1 = PackedBatchSource(_ListSource(_row_batches(lengths)),
                                   spec, placement="trainer")
            it1 = w1()
            got = [next(it1) for _ in range(cut1)]
            s1 = w1.state_dict(yielded_batches=cut1)
            it1.close()
            w2 = PackedBatchSource(
                _ListSource(_row_batches(lengths), resume=s1["inner"]),
                spec, placement="trainer", resume_state=s1)
            it2 = w2()
            got += [next(it2) for _ in range(cut2)]
            s2 = w2.state_dict(yielded_batches=cut2)
            it2.close()
            w3 = PackedBatchSource(
                _ListSource(_row_batches(lengths), resume=s2["inner"]),
                spec, placement="trainer", resume_state=s2)
            got += list(w3())
            assert len(got) == len(full), (cut1, cut2)
            for a, b in zip(got, full):
                for key in a:
                    np.testing.assert_array_equal(a[key], b[key])


def test_mid_pass_mixture_resume_does_not_apply_pending_entries(
        token_dataset, token_dataset_b):
    """A weight entry landing while a pass runs applies at the NEXT pass
    boundary in the uninterrupted run — a mid-pass resume must not
    apply it early, or the resumed stream diverges."""
    url_a, _ = token_dataset
    url_b, _ = token_dataset_b
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=53).start() as disp:
        wa = _token_worker(url_a, disp, corpus="a")
        wb = _token_worker(url_b, disp, corpus="b")
        try:
            def build(resume=None, inner_resumes=None):
                def factory(corpus):
                    def make(_epoch=None):
                        state = (inner_resumes or {}).get(corpus)
                        return ServiceBatchSource(
                            disp.address, corpus=corpus, ordered=True,
                            packing=SPEC, resume_state=state)
                    return make
                return MixedBatchSource(
                    {"a": factory("a"), "b": factory("b")},
                    weights={"a": 0.5, "b": 0.5}, seed=61,
                    exhaustion="stop",
                    dispatcher_address=disp.address, factories=True,
                    resume_state=resume)

            # Both runs' pass 0 starts (weights fetched) BEFORE the
            # entry lands — the uninterrupted run finishes the pass
            # under the old weights.
            clean_it = build()()
            mix = build()
            it = mix()
            got = [next(it) for _ in range(2)]
            # The reload lands mid-pass, with no effective_epoch: the
            # uninterrupted run applies it only at its next pass start.
            set_mixture_weights(disp.address, {"a": 0.9, "b": 0.1})
            clean = list(clean_it)
            state = mix.state_dict(yielded_batches=2)
            it.close()
            # The resumed trainer fetches the journaled entry at its
            # resume __call__ — it must STAGE it for the next pass, not
            # apply it to the remaining draws of pass 0.
            resumed = build(resume=state,
                            inner_resumes=state["inner"])
            got += list(resumed())
            assert len(got) == len(clean)
            assert _digest(got) == _digest(clean)
        finally:
            wa.stop()
            wb.stop()


def test_reweight_last_corpus_exhaustion_ends_cleanly():
    """Draining the LAST live corpus under 'reweight' is the clean end
    of the mix (MixtureExhausted), never an invalid-weights crash."""
    sampler = MixtureSampler(3, {"x": 0.5, "y": 0.5},
                             exhaustion="reweight")
    sampler.draw()
    assert sampler.mark_exhausted("x") == "y"
    with pytest.raises(MixtureExhausted):
        sampler.mark_exhausted("y")


def test_zero_weight_corpus_sources_not_opened():
    """A corpus reloaded to weight 0 must not cost a fleet of open
    streams per pass — its source is never built or iterated."""
    opened = []

    def factory(name):
        def make():
            opened.append(name)
            return _ListSource(_row_batches([4, 5]))
        return make

    mix = MixedBatchSource({"a": factory("a"), "b": factory("b")},
                           {"a": 1.0, "b": 0.0}, seed=5,
                           exhaustion="stop", factories=True)
    batches = list(mix())
    assert batches
    assert opened == ["a"]
    state = mix.state_dict()
    assert "b" not in state["inner"]


def test_worker_resume_snapshot_not_misapplied_after_flip(token_dataset):
    """A worker-kind resume snapshot is consumed by the worker pass; a
    later trainer-placement iteration (autotuner flip) must start
    clean, and its checkpoints must use the right iteration base."""
    url, _ = token_dataset
    with Dispatcher(mode="static", num_epochs=1,
                    shuffle_seed=3).start() as disp:
        worker = _token_worker(url, disp)
        try:
            base = ServiceBatchSource(disp.address, ordered=True,
                                      packing=SPEC)
            wrapped = PackedBatchSource(base, SPEC, placement="worker")
            it = wrapped()
            next(it)
            state = wrapped.state_dict(yielded_batches=1)
            assert state["placement"] == "worker"
            it.close()
            inner2 = ServiceBatchSource(disp.address, ordered=True,
                                        packing=SPEC,
                                        resume_state=state["inner"])
            w2 = PackedBatchSource(inner2, SPEC, resume_state=state)
            rest = list(w2())  # worker pass consumes the snapshot
            assert w2._resume is None
            assert rest  # the resumed worker pass actually served
            # The stale worker-kind snapshot must NOT leak trainer-side
            # skip/base accounting: a wrapper holding one that is
            # flipped to trainer placement BEFORE its first iteration
            # serves the identical stream as a fresh trainer run (the
            # old bug skipped `skip` packed batches — data loss).
            w3 = PackedBatchSource(
                ServiceBatchSource(disp.address, ordered=True), SPEC,
                resume_state=state)
            w3.set_packing_placement("trainer")
            flipped = list(w3())
            fresh = list(PackedBatchSource(
                ServiceBatchSource(disp.address, ordered=True), SPEC,
                placement="trainer")())
            assert _digest(flipped) == _digest(fresh)
        finally:
            worker.stop()
