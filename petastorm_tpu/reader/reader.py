"""`make_reader` / `make_batch_reader` / `Reader`.

Reference parity: ``petastorm/reader.py`` — SURVEY.md §2.1 (full kwarg
checklist), call stacks §3.1/§3.2. TPU-first notes:

- row groups shard round-robin ``pieces[cur_shard::shard_count]`` exactly like
  the reference; on a pod each host passes its ``jax.process_index()`` /
  ``jax.process_count()`` (the JAX loader does this for you) and no data-plane
  traffic ever crosses hosts;
- equal-cardinality delivery for SPMD lockstep is owned by the JAX loader's
  pad/drop policy (``petastorm_tpu/jax_utils/loader.py``), not the Reader —
  mirroring the reference split where Horovod-style consumers tolerate ragged
  shards but pjit does not;
- predicate pushdown: ``filters`` prune row groups via Parquet statistics
  before any ventilation (pyarrow dataset fragments), then ``predicate``
  filters rows worker-side with a two-phase column read.
"""

from __future__ import annotations

import logging
import warnings

from petastorm_tpu.cache import NullCache
from petastorm_tpu.errors import NoDataAvailableError, PetastormMetadataError
from petastorm_tpu.etl import metadata as etl_metadata
from petastorm_tpu.etl.metadata import RowGroupPiece, load_row_groups
from petastorm_tpu.fs_utils import FilesystemResolver, get_filesystem_and_path_or_paths
from petastorm_tpu.local_disk_arrow_table_cache import LocalDiskArrowTableCache
from petastorm_tpu.local_disk_cache import LocalDiskCache
from petastorm_tpu.ngram import NGram
from petastorm_tpu.predicates import PredicateBase
from petastorm_tpu.reader.arrow_worker import ArrowReaderWorker, ArrowResultsQueueReader
from petastorm_tpu.reader.columnar_worker import (
    ColumnarDecodeWorker,
    ColumnarResultsQueueReader,
)
from petastorm_tpu.reader.py_dict_worker import PyDictReaderWorker, PyDictResultsQueueReader
from petastorm_tpu.reader_impl.arrow_table_serializer import ArrowTableSerializer
from petastorm_tpu.reader_impl.pickle_serializer import PickleSerializer
from petastorm_tpu.schema.transform import transform_schema
from petastorm_tpu.schema.unischema import Unischema, match_unischema_fields
from petastorm_tpu.workers_pool import EmptyResultError
from petastorm_tpu.workers_pool.dummy_pool import DummyPool
from petastorm_tpu.workers_pool.process_pool import ProcessPool
from petastorm_tpu.workers_pool.thread_pool import ThreadPool
from petastorm_tpu.workers_pool.ventilator import ConcurrentVentilator

logger = logging.getLogger(__name__)


def make_reader(dataset_url,
                schema_fields=None,
                reader_pool_type="thread", workers_count=10,
                results_queue_size=50,
                shuffle_row_groups=True, shuffle_row_drop_partitions=1,
                predicate=None,
                rowgroup_selector=None,
                num_epochs=1,
                cur_shard=None, shard_count=None, shard_seed=None,
                cache_type="null", cache_location=None, cache_size_limit=None,
                cache_row_size_estimate=None, cache_extra_settings=None,
                hdfs_driver="libhdfs",
                transform_spec=None,
                filters=None,
                storage_options=None,
                zmq_copy_buffers=True,
                filesystem=None,
                reader_engine=None,
                resume_state=None,
                fast_gcs_listing=True,
                piece_indices=None,
                dynamic_ventilation=False):
    """Reader for **petastorm-format** datasets (Unischema + codecs attached).

    Reference parity: ``petastorm/reader.py::make_reader`` — same knob surface.
    Raises a pointed error directing to :func:`make_batch_reader` when the
    store is plain Parquet.

    ``reader_engine``: legacy knob accepted for API parity
    (``'reader_v1'`` is the only value the reference ever shipped; anything
    else raises as it does upstream). Deprecated — has no effect.
    """
    if reader_engine is not None:
        if reader_engine != "reader_v1":
            raise ValueError(
                f"reader_engine {reader_engine!r} is not supported; the only "
                f"legacy value is 'reader_v1' (deprecated, no effect)")
        warnings.warn(
            "reader_engine is deprecated and has no effect; the experimental "
            "v2 engine never left the reference. For a faster columnar path "
            "use make_columnar_reader.", DeprecationWarning, stacklevel=2)
    cur_shard, shard_count = _default_shard_options(cur_shard, shard_count)
    resolver = FilesystemResolver(dataset_url, hdfs_driver=hdfs_driver,
                                  storage_options=storage_options,
                                  filesystem=filesystem,
                                  fast_gcs_listing=fast_gcs_listing)
    fs = resolver.filesystem()
    path = resolver.get_dataset_path()
    try:
        stored_schema = etl_metadata.get_schema(fs, path)
    except PetastormMetadataError as exc:
        raise RuntimeError(
            f"Dataset at {dataset_url!r} is not a petastorm dataset (no "
            f"Unischema metadata). Use make_batch_reader for plain Parquet "
            f"stores. Original error: {exc}"
        ) from exc

    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings,
                        arrow_cache=False)
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      PickleSerializer(), zmq_copy_buffers)

    return Reader(fs, path,
                  schema=stored_schema,
                  schema_fields=schema_fields,
                  worker_class=PyDictReaderWorker,
                  results_queue_reader=PyDictResultsQueueReader(),
                  reader_pool=pool,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate,
                  rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs,
                  cur_shard=cur_shard, shard_count=shard_count,
                  shard_seed=shard_seed,
                  cache=cache,
                  transform_spec=transform_spec,
                  filters=filters,
                  resume_state=resume_state,
                  piece_indices=piece_indices,
                  dynamic_ventilation=dynamic_ventilation)


def make_columnar_reader(dataset_url,
                         schema_fields=None,
                         reader_pool_type="thread", workers_count=10,
                         results_queue_size=50,
                         shuffle_row_groups=True,
                         shuffle_row_drop_partitions=1,
                         predicate=None,
                         rowgroup_selector=None,
                         num_epochs=1,
                         cur_shard=None, shard_count=None, shard_seed=None,
                         cache_type="null", cache_location=None,
                         cache_size_limit=None, cache_row_size_estimate=None,
                         cache_extra_settings=None,
                         hdfs_driver="libhdfs",
                         transform_spec=None,
                         filters=None,
                         storage_options=None,
                         zmq_copy_buffers=True,
                         filesystem=None,
                         resume_state=None,
                         fast_gcs_listing=True,
                         piece_indices=None,
                         dynamic_ventilation=False):
    """Columnar reader for **petastorm-format** datasets — the TPU-native
    fast path feeding :func:`petastorm_tpu.jax_utils.make_jax_dataloader`.

    Decodes codec columns **vectorized** (``codec.decode_column``: imdecode /
    frombuffer straight into preallocated ``[N, *shape]`` arrays — no per-row
    python objects) and yields column-batch namedtuples like
    :func:`make_batch_reader` (``batched_output=True``). Measured ~1.3-1.4x
    the row path's decode throughput on png/ndarray schemas (the advantage
    shrinks when a heavy per-cell codec like jpeg dominates), which directly
    raises the input-bound training ceiling (BASELINE.md north star).

    Differences from :func:`make_reader` (row path, reference architecture —
    ``petastorm/py_dict_reader_worker.py``):

    - ``transform_spec.func`` receives the decoded ``{field: [N, ...]}`` dict
      (vectorize your transform), not one row at a time;
    - NGram windows are not supported (inherently row-wise — use
      ``make_reader``);
    - shuffling is at row-group granularity (``shuffle_row_groups``); use the
      loader's ``shuffle_buffer_size``-free batch shuffling or pre-shuffle.
    """
    if isinstance(schema_fields, NGram):
        raise ValueError("NGram is not supported by make_columnar_reader; "
                         "use make_reader")
    cur_shard, shard_count = _default_shard_options(cur_shard, shard_count)
    resolver = FilesystemResolver(dataset_url, hdfs_driver=hdfs_driver,
                                  storage_options=storage_options,
                                  filesystem=filesystem,
                                  fast_gcs_listing=fast_gcs_listing)
    fs = resolver.filesystem()
    path = resolver.get_dataset_path()
    try:
        stored_schema = etl_metadata.get_schema(fs, path)
    except PetastormMetadataError as exc:
        raise RuntimeError(
            f"Dataset at {dataset_url!r} is not a petastorm dataset (no "
            f"Unischema metadata). Use make_batch_reader for plain Parquet "
            f"stores. Original error: {exc}"
        ) from exc

    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings,
                        arrow_cache=False)
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      PickleSerializer(), zmq_copy_buffers)

    return Reader(fs, path,
                  schema=stored_schema,
                  schema_fields=schema_fields,
                  worker_class=ColumnarDecodeWorker,
                  results_queue_reader=ColumnarResultsQueueReader(),
                  reader_pool=pool,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate,
                  rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs,
                  cur_shard=cur_shard, shard_count=shard_count,
                  shard_seed=shard_seed,
                  cache=cache,
                  transform_spec=transform_spec,
                  filters=filters,
                  resume_state=resume_state,
                  piece_indices=piece_indices,
                  dynamic_ventilation=dynamic_ventilation)


def make_batch_reader(dataset_url_or_urls,
                      schema_fields=None,
                      reader_pool_type="thread", workers_count=10,
                      results_queue_size=50,
                      shuffle_row_groups=True, shuffle_row_drop_partitions=1,
                      predicate=None,
                      rowgroup_selector=None,
                      num_epochs=1,
                      cur_shard=None, shard_count=None, shard_seed=None,
                      cache_type="null", cache_location=None,
                      cache_size_limit=None, cache_row_size_estimate=None,
                      cache_extra_settings=None,
                      hdfs_driver="libhdfs",
                      transform_spec=None,
                      filters=None,
                      storage_options=None,
                      zmq_copy_buffers=True,
                      filesystem=None,
                      resume_state=None,
                      fast_gcs_listing=True,
                      piece_indices=None,
                      dynamic_ventilation=False):
    """Batch reader for **plain Parquet** stores (no petastorm metadata needed).

    Reference parity: ``petastorm/reader.py::make_batch_reader``. Yields
    namedtuples of numpy *column batches* (record-batch-sized, not training
    batch size); ``schema_fields`` must be column names/regexes (no NGram);
    ``transform_spec`` operates on pandas DataFrames.
    """
    if isinstance(schema_fields, NGram):
        raise ValueError("NGram is not supported by make_batch_reader")
    cur_shard, shard_count = _default_shard_options(cur_shard, shard_count)
    fs, path_or_paths = get_filesystem_and_path_or_paths(
        dataset_url_or_urls, hdfs_driver=hdfs_driver,
        storage_options=storage_options, filesystem=filesystem,
        fast_gcs_listing=fast_gcs_listing)
    paths = path_or_paths if isinstance(path_or_paths, list) else [path_or_paths]

    try:
        stored_schema = etl_metadata.get_schema(fs, paths[0])
        logger.info("Dataset carries a Unischema; make_batch_reader will read "
                    "it as plain Parquet (codec columns stay encoded)")
    except PetastormMetadataError:
        pass
    import pyarrow.dataset as pads

    dataset = pads.dataset(paths if len(paths) > 1 else paths[0],
                           filesystem=fs, format="parquet")
    inferred_schema = Unischema.from_arrow_schema(dataset.schema,
                                                  omit_unsupported_fields=True)

    cache = _make_cache(cache_type, cache_location, cache_size_limit,
                        cache_row_size_estimate, cache_extra_settings,
                        arrow_cache=True)
    pool = _make_pool(reader_pool_type, workers_count, results_queue_size,
                      ArrowTableSerializer(), zmq_copy_buffers)

    return Reader(fs, paths if len(paths) > 1 else paths[0],
                  schema=inferred_schema,
                  schema_fields=schema_fields,
                  worker_class=ArrowReaderWorker,
                  results_queue_reader=ArrowResultsQueueReader(),
                  reader_pool=pool,
                  shuffle_row_groups=shuffle_row_groups,
                  shuffle_row_drop_partitions=shuffle_row_drop_partitions,
                  predicate=predicate,
                  rowgroup_selector=rowgroup_selector,
                  num_epochs=num_epochs,
                  cur_shard=cur_shard, shard_count=shard_count,
                  shard_seed=shard_seed,
                  cache=cache,
                  transform_spec=transform_spec,
                  filters=filters,
                  resume_state=resume_state,
                  piece_indices=piece_indices,
                  dynamic_ventilation=dynamic_ventilation)


def _default_shard_options(cur_shard, shard_count):
    """On a multi-host JAX pod with no explicit sharding, default to
    ``jax.process_index()/process_count()`` so every host reads a disjoint
    row-group shard (the docstring promise 'the JAX loader does this for
    you'). Single-process (or JAX absent): unchanged."""
    from petastorm_tpu.jax_utils.sharding import default_shard_options

    return default_shard_options(cur_shard, shard_count)


def _make_cache(cache_type, cache_location, cache_size_limit,
                cache_row_size_estimate, cache_extra_settings, arrow_cache):
    if cache_type in (None, "null", "none"):
        return NullCache()
    if cache_type == "local-disk":
        if not cache_location or not cache_size_limit:
            raise ValueError(
                "cache_type='local-disk' requires cache_location and "
                "cache_size_limit"
            )
        cls = LocalDiskArrowTableCache if arrow_cache else LocalDiskCache
        return cls(cache_location, cache_size_limit, cache_row_size_estimate,
                   **(cache_extra_settings or {}))
    raise ValueError(f"Unknown cache_type {cache_type!r}")


def _make_pool(reader_pool_type, workers_count, results_queue_size, serializer,
               zmq_copy_buffers):
    if reader_pool_type == "thread":
        return ThreadPool(workers_count, results_queue_size=results_queue_size)
    if reader_pool_type == "process":
        return ProcessPool(workers_count, serializer=serializer,
                           zmq_copy_buffers=zmq_copy_buffers,
                           results_queue_size=results_queue_size)
    if reader_pool_type == "dummy":
        return DummyPool()
    raise ValueError(f"Unknown reader_pool_type {reader_pool_type!r}")


class Reader:
    """Iterator/context-manager over dataset rows (or column batches).

    Reference parity: ``petastorm/reader.py::Reader`` — iterator protocol,
    ``stop()``/``join()``/``reset()``, ``last_row_consumed``,
    ``batched_output``, ``diagnostics``.
    """

    def __init__(self, pyarrow_filesystem, dataset_path,
                 schema, schema_fields, worker_class, results_queue_reader,
                 reader_pool,
                 shuffle_row_groups=True, shuffle_row_drop_partitions=1,
                 predicate=None, rowgroup_selector=None, num_epochs=1,
                 cur_shard=None, shard_count=None, shard_seed=None,
                 cache=None, transform_spec=None, filters=None,
                 resume_state=None, piece_indices=None,
                 dynamic_ventilation=False):
        if predicate is not None and not isinstance(predicate, PredicateBase):
            raise ValueError("predicate must be an instance of PredicateBase")
        if (cur_shard is None) != (shard_count is None):
            raise ValueError("cur_shard and shard_count must be used together")
        if cur_shard is not None and not 0 <= cur_shard < shard_count:
            raise ValueError(f"cur_shard {cur_shard} out of range "
                             f"[0, {shard_count})")
        if num_epochs is not None and num_epochs <= 0:
            raise ValueError("num_epochs must be a positive integer or None")

        self._filesystem = pyarrow_filesystem
        self._dataset_path = dataset_path
        self._results_queue_reader = results_queue_reader
        self._workers_pool = reader_pool
        self._predicate = predicate
        self._transform_spec = transform_spec
        self.num_epochs = num_epochs
        self.last_row_consumed = False
        self.stopped = False

        # --- schema resolution -------------------------------------------
        self.ngram = schema_fields if isinstance(schema_fields, NGram) else None
        if self.ngram is not None:
            self.ngram.resolve_regex_field_names(schema)
            read_schema = self.ngram.get_schema_view(schema)
            if not self.ngram.timestamp_overlap and shuffle_row_drop_partitions > 1:
                raise NotImplementedError(
                    "shuffle_row_drop_partitions with non-overlapping NGram "
                    "windows is not supported (reference parity)"
                )
        elif schema_fields is None:
            read_schema = schema
        elif isinstance(schema_fields, (list, tuple)):
            read_schema = schema.create_schema_view(list(schema_fields))
        else:
            raise ValueError(
                "schema_fields must be None, a list of field names/regexes/"
                "UnischemaFields, or an NGram"
            )
        self._read_schema = read_schema
        self.schema = (transform_schema(read_schema, transform_spec)
                       if transform_spec else read_schema)

        # --- row-group planning ------------------------------------------
        pieces = self._enumerate_pieces(filters)
        if rowgroup_selector is not None:
            # With filters=None (single path) pieces IS the canonical
            # load_row_groups list — don't enumerate the store twice.
            canonical = (pieces if filters is None
                         and not isinstance(dataset_path, list) else None)
            pieces = self._apply_selector(pieces, rowgroup_selector, canonical)
        if piece_indices is not None:
            # Explicit split plan (the data service's dispatcher hands these
            # out): indices into the canonical enumeration order AFTER
            # filters/selector for the same planning config — assigner and
            # reader must plan with identical filters/selector arguments.
            piece_indices = sorted(set(int(i) for i in piece_indices))
            out_of_range = [i for i in piece_indices
                            if not 0 <= i < len(pieces)]
            if out_of_range:
                raise ValueError(
                    f"piece_indices {out_of_range} out of range for the "
                    f"{len(pieces)} row-group pieces this planning config "
                    f"enumerates")
            pieces = [pieces[i] for i in piece_indices]
        self._piece_indices = piece_indices
        pre_shard_count = len(pieces)
        pieces = self._shard_pieces(pieces, cur_shard, shard_count, shard_seed)
        if not pieces and pre_shard_count > 0:
            # Empty *shard* of a non-empty dataset: a valid reader that yields
            # nothing, so the host process survives to coordinate (raising
            # would kill it outright). NOTE equal SPMD step counts are NOT
            # automatic in this state — pad can't synthesize batches from zero
            # rows; the training loop must agree on steps (e.g. loader
            # max_batches=0 everywhere, or fewer shards than row groups).
            pass
        elif not pieces:
            raise NoDataAvailableError(
                "No row groups left after filters/selector — nothing to read"
            )
        self._pieces = pieces

        # --- ventilation --------------------------------------------------
        items = [
            {"piece_index": piece_index,
             "worker_predicate": predicate,
             "shuffle_row_drop_partition": (drop_partition,
                                            shuffle_row_drop_partitions)}
            for piece_index in range(len(pieces))
            for drop_partition in range(shuffle_row_drop_partitions)
        ]

        # --- resumable iteration (no reference analogue — SURVEY.md §5) ---
        # Payloads arrive tagged with their work-item identity; the tracker
        # counts deliveries at consumption time. state_dict() exports the
        # counts; resume_state re-ventilates each item only for its remaining
        # epochs (at-least-once at row-group granularity — see
        # reader_impl/delivery_tracker.py for the exact semantics).
        from petastorm_tpu.reader_impl.delivery_tracker import (
            DeliveryTracker, item_key)

        self._dynamic = dynamic_ventilation
        if dynamic_ventilation:
            # The externally-fed mode behind the service's streaming piece
            # engine: the piece queue is owned by the caller (mutable
            # mid-stream — work stealing appends/revokes), so pre-planned
            # epochs, shuffling and resume trimming have no meaning here.
            if resume_state is not None:
                raise ValueError(
                    "dynamic_ventilation readers have no pre-planned "
                    "ventilation to trim — resume_state is not supported")
            if shuffle_row_groups:
                raise ValueError(
                    "dynamic_ventilation serves an externally-ordered piece "
                    "queue; shuffle_row_groups must be False")
            if shuffle_row_drop_partitions != 1:
                raise ValueError(
                    "dynamic_ventilation does not support "
                    "shuffle_row_drop_partitions")
        self._shard_seed = shard_seed
        self._shuffle_row_drop_partitions = shuffle_row_drop_partitions
        # filters/selector (and an explicit piece_indices plan) change which
        # pieces the positional item keys denote — they must be part of the
        # resume fingerprint. The two-element repr is kept when no explicit
        # plan is given so pre-existing checkpoints stay resumable.
        self._planning_repr = repr(
            (filters, rowgroup_selector) if piece_indices is None
            else (filters, rowgroup_selector, tuple(piece_indices)))
        self._resume_state = resume_state
        self._num_items = len(items)  # full item universe (pre-resume trim)
        iterations = num_epochs
        per_item_iterations = None
        prior_counts = None
        if resume_state is not None:
            self._validate_resume_state(resume_state, items)
            delivered = resume_state["delivered"]
            keys = [item_key(it["piece_index"],
                             it["shuffle_row_drop_partition"][0])
                    for it in items]
            per_item_iterations = [
                max(0, num_epochs - delivered.get(k, 0)) for k in keys]
            prior_counts = dict(delivered)
            iterations = max(per_item_iterations, default=0)
            if iterations == 0:
                # Everything already delivered: a valid reader yielding
                # nothing more (mirrors an exhausted stream).
                items, per_item_iterations = [], None
        self._delivery_tracker = DeliveryTracker(preload=prior_counts)
        self._results_queue_reader.delivery_tracker = self._delivery_tracker

        if dynamic_ventilation:
            from petastorm_tpu.workers_pool.ventilator import (
                DynamicVentilator,
            )

            self._ventilator = DynamicVentilator(self._workers_pool.ventilate)
        else:
            self._ventilator = ConcurrentVentilator(
                self._workers_pool.ventilate,
                items,
                iterations=iterations if items else 1,
                randomize_item_order=shuffle_row_groups,
                random_seed=shard_seed,
                max_ventilation_queue_size=min(len(items), 1000) or 1,
                per_item_iterations=per_item_iterations,
            )
        # Kept as an attribute so lifecycle owners (``stop()``, the service
        # worker's drain) can release cache resources — a local-disk cache
        # with ``cleanup=True`` would otherwise leak its directory.
        self.cache = cache or NullCache()
        worker_args = (pyarrow_filesystem, pieces, schema, read_schema,
                       self.ngram, self.cache, transform_spec)
        self._workers_pool.start(worker_class, worker_args,
                                 ventilator=self._ventilator)
        self._static_diagnostics = {
            "rowgroups_total": len(pieces),
            "items_per_epoch": len(items),
            "workers_count": getattr(reader_pool, "workers_count", 1),
        }
        # Registry mirror (telemetry.metrics): readers constructed and the
        # latest plan size become scrapeable alongside the pool/ventilator
        # counters this reader's `diagnostics` property snapshots.
        from petastorm_tpu.telemetry.metrics import (
            READER_READERS,
            READER_ROWGROUPS_PLANNED,
        )

        READER_READERS.inc()
        READER_ROWGROUPS_PLANNED.set(len(pieces))

    # --- planning helpers -----------------------------------------------

    def _enumerate_pieces(self, filters):
        return enumerate_row_group_pieces(self._filesystem, self._dataset_path,
                                          filters)

    def _apply_selector(self, pieces, rowgroup_selector, canonical=None):
        from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes

        if isinstance(self._dataset_path, list):
            raise ValueError("rowgroup_selector is not supported with multiple "
                             "dataset URLs")
        index_dict = get_row_group_indexes(self._filesystem, self._dataset_path)
        selected = rowgroup_selector.select_row_groups(index_dict)
        # Selector ordinals are canonical (load_row_groups order); ``pieces``
        # may already be pruned by ``filters``, so match by (path, row_group)
        # identity rather than by position in the pruned list.
        if canonical is None:
            canonical = load_row_groups(self._filesystem, self._dataset_path)
        selected_ids = {(p.path, p.row_group)
                        for index, p in enumerate(canonical) if index in selected}
        return [piece for piece in pieces
                if (piece.path, piece.row_group) in selected_ids]

    def _shard_pieces(self, pieces, cur_shard, shard_count, shard_seed):
        from petastorm_tpu.jax_utils.sharding import split_pieces_for_shards

        shards = split_pieces_for_shards(pieces, shard_count, shard_seed)
        # Every shard's piece list is kept so equal-step coordination
        # (jax_utils.sharding.derive_equal_step_max_batches) can compute the
        # global-min batch count locally on each host — no collective needed.
        # Row counts resolve lazily (shard_row_counts property): the metadata
        # fast path doesn't open footers unless coordination asks for counts.
        self._shard_piece_lists = shards
        self._shard_row_counts = None
        self.cur_shard = cur_shard
        self.shard_count = shard_count
        if shard_count is None:
            return shards[0]
        sharded = shards[cur_shard]
        if not sharded:
            warnings.warn(
                f"Shard {cur_shard}/{shard_count} received zero row groups "
                f"(dataset has only {len(pieces)}); this reader yields "
                f"nothing. SPMD consumers must agree on a global step count "
                f"— make_jax_dataloader(sharding=...) derives it "
                f"automatically, or use jax_utils.sharding."
                f"global_step_count — prefer shard_count <= row-group count",
                UserWarning, stacklevel=3,
            )
        return sharded

    @property
    def shard_row_counts(self):
        """Row count of *every* shard (not just this reader's) — the input to
        equal-step SPMD coordination. Lazily resolves ``num_rows=None`` pieces
        with one footer read per file."""
        if self._shard_row_counts is None:
            all_pieces = [p for shard in self._shard_piece_lists for p in shard]
            counts = etl_metadata.piece_row_counts(self._filesystem, all_pieces)
            self._shard_row_counts = [
                sum(counts[(p.path, p.row_group)] for p in shard)
                for shard in self._shard_piece_lists]
        return self._shard_row_counts

    def state_dict(self, yielded_rows=None):
        """Snapshot of iteration progress for checkpoint/resume.

        Returns a JSON-serializable dict; persist it with your model
        checkpoint and pass it back as ``resume_state=`` to the same factory
        with the same arguments. Semantics: at-least-once at row-group
        granularity — fully-delivered row groups are never re-read; the row
        group being consumed at snapshot time is re-read on resume. Requires
        finite ``num_epochs`` to resume (an infinite stream restarts
        instead). Safe to call mid-iteration from another thread.

        ``yielded_rows``: for a downstream consumer that prefetches past the
        reader interface — the number of rows it has actually surfaced. The
        newest deliveries beyond that count are excluded from the snapshot
        (atomically, so concurrent pulls only widen the re-read window) —
        ``JaxDataLoader.state_dict()`` passes this for you.
        """
        delivered = (
            self._delivery_tracker.counts_rolled_back_to(yielded_rows)
            if yielded_rows is not None
            else self._delivery_tracker.counts())
        return {
            "version": 1,
            "dataset_path": self._dataset_path_signature(),
            "num_items": self._num_items,
            "num_epochs": self.num_epochs,
            "shard": [self.cur_shard, self.shard_count, self._shard_seed],
            "drop_partitions": self._shuffle_row_drop_partitions,
            "planning": self._planning_repr,
            "delivered": delivered,
        }

    def _dataset_path_signature(self):
        path = self._dataset_path
        return sorted(str(p) for p in path) if isinstance(path, list) \
            else str(path)

    def _validate_resume_state(self, state, items):
        if state.get("version") != 1:
            raise ValueError(
                f"Unsupported resume_state version {state.get('version')!r}")
        if self.num_epochs is None:
            raise ValueError(
                "resume_state requires finite num_epochs (an infinite stream "
                "has no resumable endpoint — just restart it)")
        expected = {
            "dataset_path": self._dataset_path_signature(),
            "num_items": len(items),
            "num_epochs": self.num_epochs,
            "shard": [self.cur_shard, self.shard_count, self._shard_seed],
            "drop_partitions": self._shuffle_row_drop_partitions,
            "planning": self._planning_repr,
        }
        for key, want in expected.items():
            got = state.get(key)
            got = list(got) if isinstance(got, tuple) else got
            if got != want:
                raise ValueError(
                    f"resume_state mismatch on {key!r}: checkpoint has "
                    f"{got!r}, this reader has {want!r} — resume requires "
                    f"the same dataset and reader configuration")

    @property
    def diagnostics(self):
        """Live runtime counters (reference ``Reader.diagnostics`` — SURVEY.md
        §5): items ventilated/in-flight from the ventilator, items processed
        and results-queue depth from the pool, plus static planning facts.
        Safe to read mid-iteration; each read is a fresh snapshot."""
        snapshot = dict(self._static_diagnostics)
        snapshot.update(getattr(self._workers_pool, "diagnostics", {}) or {})
        ventilator = getattr(self, "_ventilator", None)
        if ventilator is not None:
            snapshot.update(ventilator.diagnostics)
        return snapshot

    def resize_workers(self, workers_count):
        """Live-resize the decode pool's parallelism (thread pools only —
        the pipeline autotuner's ``workers_count`` knob,
        ``docs/guides/pipeline.md``). Raises for pools without runtime
        resize (process pools fork at start)."""
        pool = self._workers_pool
        resize = getattr(pool, "resize", None)
        if resize is None:
            raise NotImplementedError(
                f"{type(pool).__name__} cannot resize at runtime — use "
                f"reader_pool_type='thread'")
        resize(workers_count)

    # --- iterator protocol ----------------------------------------------

    @property
    def batched_output(self):
        return self._results_queue_reader.batched_output

    def __iter__(self):
        return self

    def __next__(self):
        if self.stopped:
            raise StopIteration
        try:
            return self._results_queue_reader.read_next(
                self._workers_pool, self.schema, self.ngram)
        except EmptyResultError:
            self.last_row_consumed = True
            raise StopIteration from None

    def next(self):
        return self.__next__()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()
        self.join()

    # --- lifecycle -------------------------------------------------------

    def stop(self):
        self._workers_pool.stop()
        self.stopped = True
        try:
            self.cache.cleanup()
        except Exception:  # cache teardown must never mask the stop
            logger.warning("reader cache cleanup failed", exc_info=True)

    def join(self):
        self._workers_pool.join()

    def reset(self):
        """Restart epoch iteration. Only valid once the previous epochs fully
        finished (reference parity: raises otherwise)."""
        if not self.last_row_consumed:
            raise NotImplementedError(
                "Currently, reset() can only be called after all rows were "
                "consumed"
            )
        if self._resume_state is not None:
            # The resumed ventilation plan is trimmed to the checkpoint's
            # remaining work; replaying it would NOT be a full pass (items
            # already delivered before the checkpoint would be skipped).
            raise NotImplementedError(
                "reset() is not supported on a resumed reader — construct a "
                "fresh reader (without resume_state) for a new full pass")
        self.last_row_consumed = False
        # Reset delivery accounting with the epochs: a state_dict() taken
        # after reset() must describe the new pass, not accumulate the
        # finished one (stale counts would make resume yield nothing).
        from petastorm_tpu.reader_impl.delivery_tracker import DeliveryTracker

        self._delivery_tracker = DeliveryTracker()
        self._results_queue_reader.delivery_tracker = self._delivery_tracker
        self._ventilator.reset()

    # --- dynamic piece feed (dynamic_ventilation=True readers) -----------

    @property
    def dynamic(self):
        """True for externally-fed readers (``dynamic_ventilation=True``)."""
        return self._dynamic

    def _require_dynamic(self):
        if not self._dynamic:
            raise RuntimeError(
                "this Reader was not constructed with "
                "dynamic_ventilation=True")

    def submit_piece(self, piece_index):
        """Feed one planned piece (canonical enumeration index) into the
        pool. Dynamic readers only; the caller owns admission control."""
        self._require_dynamic()
        piece_index = int(piece_index)
        if not 0 <= piece_index < len(self._pieces):
            raise ValueError(
                f"piece_index {piece_index} out of range for the "
                f"{len(self._pieces)} row-group pieces planned")
        self._ventilator.submit({
            "piece_index": piece_index,
            "worker_predicate": self._predicate,
            "shuffle_row_drop_partition": (0, 1)})

    def finish_pieces(self):
        """Declare the piece feed closed: once in-flight pieces drain, the
        consumer sees end-of-data instead of blocking."""
        self._require_dynamic()
        self._ventilator.finish()

    def set_publish_transform(self, fn):
        """Install ``fn(PiecePayload) -> payload`` on the pool's publish
        path — it runs ON THE POOL WORKER THREAD, which is how the
        stage-fusion rewrite collapses collate/transform/serialize into
        the decode task (``docs/guides/pipeline.md#graph-rewrites``).
        Returns True when the pool supports it (thread/dummy pools);
        False otherwise (process pools serialize payloads across a
        process boundary — a closure cannot ride along)."""
        self._require_dynamic()
        pool = self._workers_pool
        if not hasattr(pool, "publish_transform"):
            return False
        pool.publish_transform = fn
        return True

    def set_item_done_hook(self, hook):
        """Install ``hook(item_kwargs)``, fired on the consuming thread as
        it drains a work item's completion marker — strictly after every
        output of that item was returned (thread/dummy pools only)."""
        self._require_dynamic()
        if not getattr(self._workers_pool, "supports_item_done_hook", False):
            raise ValueError(
                "the streaming piece feed needs per-item completion "
                "attribution, which only thread and dummy reader pools "
                "provide — use reader_pool_type='thread' (or 'dummy')")
        self._workers_pool.item_done_hook = hook

    def read_next_tagged(self, timeout=None):
        """``(next output, piece_index)`` — one reader output plus the
        canonical index of the piece it came from (``None`` if untagged).
        Raises the pool's timeout/end-of-data exceptions unchanged."""
        out = self._results_queue_reader.read_next(
            self._workers_pool, self.schema, self.ngram, timeout=timeout)
        key = getattr(self._results_queue_reader, "last_item_key", None)
        piece = int(key.split(":", 1)[0]) if key else None
        return out, piece


def enumerate_row_group_pieces(filesystem, dataset_path, filters=None):
    """Enumerate row-group pieces, optionally pruned by Parquet-stats filters.

    Module-level so metadata-only planning (``jax_utils.sharding.
    global_step_count``) shares the exact enumeration the Reader plans with.
    """
    if filters is None and not isinstance(dataset_path, list):
        return load_row_groups(filesystem, dataset_path)
    import pyarrow.dataset as pads

    expression = _filters_to_expression(filters) if filters is not None else None
    dataset = pads.dataset(dataset_path, filesystem=filesystem,
                           format="parquet")
    pieces = []
    fragments = sorted(dataset.get_fragments(filter=expression),
                       key=lambda f: f.path)
    for fragment in fragments:
        split = (fragment.split_by_row_group(expression)
                 if expression is not None else fragment.split_by_row_group())
        for rg_fragment in split:
            rg = rg_fragment.row_groups[0]
            pieces.append(RowGroupPiece(fragment.path, rg.id, rg.num_rows))
    return pieces


def _filters_to_expression(filters):
    """DNF filter list (or pyarrow expression) → ``pyarrow.dataset.Expression``.

    Accepts the same DNF shape the reference forwards to pyarrow:
    ``[(col, op, value), ...]`` (ANDed) or ``[[...], [...]]`` (OR of ANDs).
    """
    import pyarrow.dataset as pads
    import pyarrow.compute as pc

    if isinstance(filters, pads.Expression):
        return filters

    ops = {
        "=": lambda f, v: f == v, "==": lambda f, v: f == v,
        "!=": lambda f, v: f != v, "<": lambda f, v: f < v,
        ">": lambda f, v: f > v, "<=": lambda f, v: f <= v,
        ">=": lambda f, v: f >= v,
        "in": lambda f, v: f.isin(list(v)),
        "not in": lambda f, v: ~f.isin(list(v)),
    }

    def conjunction(triples):
        expr = None
        for col, op, value in triples:
            if op not in ops:
                raise ValueError(f"Unsupported filter op {op!r}")
            term = ops[op](pc.field(col), value)
            expr = term if expr is None else expr & term
        if expr is None:
            raise ValueError("Empty filter conjunction")
        return expr

    if all(isinstance(f, (list, tuple)) and len(f) == 3 and isinstance(f[1], str)
           for f in filters):
        return conjunction(filters)
    result = None
    for clause in filters:
        term = conjunction(clause)
        result = term if result is None else result | term
    return result
