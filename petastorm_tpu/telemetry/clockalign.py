"""NTP-style per-peer clock alignment for fleet trace assembly.

Each process's :class:`~petastorm_tpu.telemetry.tracing.TraceCollector`
anchors its ``perf_counter`` timestamps to that process's wall clock —
good enough for eyeballing a loopback run, wrong across hosts (and even
across processes on one host, NTP steps and anchor jitter move the axes
apart). The fix is the classic NTP midpoint estimate, piggybacked on
traffic the service already sends:

- a peer (worker or client) wraps one control RPC with two local
  ``perf_counter`` readings ``t0``/``t1`` and converts their midpoint
  into its trace timebase;
- the dispatcher's reply carries ``dispatcher_time_us`` — its own trace
  timebase read while handling the request;
- assuming symmetric network delay, the dispatcher's reading corresponds
  to the peer's midpoint, so ``offset = dispatcher_time - midpoint``
  maps the peer's axis onto the dispatcher's. The estimate's error is
  bounded by ±RTT/2, so the estimator keeps the samples with the
  SMALLEST round-trips (least queueing noise) and takes the median of
  their offsets — jitter-robust without any clock-discipline loop.

At merge time every shipped peer event gets ``ts += offset`` and the
dispatcher's own events pass through unshifted: one Perfetto-loadable
fleet trace on the dispatcher's axis. Asymmetric paths (one congested
direction) bias the midpoint by the asymmetry/2 — see the caveats in
``docs/guides/diagnostics.md#clock-alignment``.

Everything here is pure arithmetic over caller-provided readings: no
clock reads, no I/O — unit-testable with fabricated skew and jitter.
"""

from __future__ import annotations

#: Keep this many lowest-RTT samples for the median; more buys little
#: (the low-RTT population is already the low-noise one) and a small k
#: converges within a handful of heartbeats.
DEFAULT_BEST_K = 5

#: Ring bound on retained samples: heartbeats arrive forever, the
#: estimate only ever needs the recent low-RTT population (retaining
#: everything would let one ancient pre-NTP-step sample pin the median).
DEFAULT_MAX_SAMPLES = 64


class OffsetEstimator:
    """Streaming per-peer offset estimate from RPC round-trip samples.

    ``add(local_mid_us, remote_us, rtt_us)`` feeds one wrapped RPC:
    the local midpoint and the remote reading both already converted to
    their respective trace timebases (microseconds), plus the measured
    round-trip. ``offset_us()`` is the median offset of the ``best_k``
    lowest-RTT samples — ``None`` until the first sample lands.
    """

    def __init__(self, max_samples=DEFAULT_MAX_SAMPLES,
                 best_k=DEFAULT_BEST_K):
        self._max_samples = int(max_samples)
        self._best_k = int(best_k)
        self._samples = []  # (rtt_us, offset_us), insertion-ordered

    def add(self, local_mid_us, remote_us, rtt_us):
        self._samples.append((float(rtt_us),
                              float(remote_us) - float(local_mid_us)))
        if len(self._samples) > self._max_samples:
            self._samples.pop(0)

    def __len__(self):
        return len(self._samples)

    def offset_us(self):
        if not self._samples:
            return None
        best = sorted(self._samples)[:self._best_k]
        offsets = sorted(offset for _, offset in best)
        mid = len(offsets) // 2
        if len(offsets) % 2:
            return offsets[mid]
        return (offsets[mid - 1] + offsets[mid]) / 2.0

    def min_rtt_us(self):
        """The tightest round-trip seen — the ±RTT/2 error bound on the
        current estimate (reported alongside the offset so trace readers
        know how much to trust sub-millisecond alignment)."""
        if not self._samples:
            return None
        return min(rtt for rtt, _ in self._samples)


def shift_events(events, offset_us):
    """Copy ``events`` with ``ts`` moved by ``offset_us`` (a no-op pass
    for offset 0/None — the dispatcher's own events)."""
    if not offset_us:
        return list(events)
    shifted = []
    for event in events:
        event = dict(event)
        if "ts" in event:
            event["ts"] = event["ts"] + offset_us
        shifted.append(event)
    return shifted


def process_name_metadata(events, name):
    """Chrome ``M``-phase ``process_name`` records for every pid seen in
    ``events`` — Perfetto then shows the peer's name (worker id, client
    id) instead of a bare pid on each process track."""
    pids = []
    for event in events:
        pid = event.get("pid")
        if pid is not None and pid not in pids:
            pids.append(pid)
    return [{"name": "process_name", "ph": "M", "pid": pid,
             "args": {"name": name}} for pid in pids]


def assemble_fleet_trace(local_events, peers, local_name="dispatcher",
                         local_dropped=0):
    """Merge the dispatcher's own ring with every peer's shipped buffer
    into one Perfetto-loadable trace document.

    :param peers: ``{peer_name: {"events": [...], "offset_us": x|None,
        "dropped": n}}`` — buffers as shipped (peer timebase); each is
        shifted onto the local axis by its offset at merge.
    :return: the trace-JSON document dict (``traceEvents`` sorted by
        ``ts`` so offline consumers can stream it).
    """
    merged = list(local_events)
    merged.extend(process_name_metadata(local_events, local_name))
    dropped = int(local_dropped)
    alignment = {}
    for name in sorted(peers):
        buf = peers[name]
        offset = buf.get("offset_us")
        shifted = shift_events(buf.get("events") or [], offset)
        merged.extend(shifted)
        merged.extend(process_name_metadata(shifted, name))
        dropped += int(buf.get("dropped") or 0)
        alignment[name] = {"offset_us": offset,
                           "min_rtt_us": buf.get("min_rtt_us")}
    merged.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": merged, "displayTimeUnit": "ms",
            "otherData": {"producer": "petastorm_tpu.telemetry",
                          "dropped_events": dropped,
                          "clock_alignment": alignment}}
