"""Pickle payload serializer for the process pool's zmq transport.

Reference parity: ``petastorm/reader_impl/pickle_serializer.py`` — plus the
zero-copy multipart surface backing ``zmq_copy_buffers=True``
(``petastorm/workers_pool/process_pool.py`` semantics): pickle protocol 5
emits large contiguous buffers (numpy arrays, arrow buffers) OUT-OF-BAND, so
the worker can ``send_multipart(copy=False)`` raw array memory and the
consumer reassembles from received frame buffers without an intermediate
pickle-bytes copy on either side.
"""

from __future__ import annotations

import pickle


class PickleSerializer:
    def serialize(self, rows):
        return pickle.dumps(rows, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, serialized_rows):
        return pickle.loads(serialized_rows)  # noqa: S301 - host-local IPC from our own workers

    # -- zero-copy multipart surface (zmq_copy_buffers=True) ---------------

    def serialize_to_frames(self, rows):
        """Serialize to ``[head, buffer, buffer, ...]`` frames.

        ``head`` is the protocol-5 pickle with out-of-band buffer markers;
        the remaining frames are the raw buffers themselves (zero-copy views
        of array memory — keep the source alive until sent).
        """
        buffers = []
        head = pickle.dumps(rows, protocol=5, buffer_callback=buffers.append)
        return [head] + [b.raw() for b in buffers]

    def deserialize_from_frames(self, frames):
        """Inverse of :meth:`serialize_to_frames`; ``frames`` may be bytes,
        memoryviews, or zmq frame buffers."""
        head, buffers = frames[0], frames[1:]
        if not isinstance(head, (bytes, bytearray, memoryview)):
            # pickle.loads accepts any buffer-like; memoryview() wraps zmq
            # frames and friends without the bytes() copy.
            head = memoryview(head)
        return pickle.loads(head, buffers=buffers)  # noqa: S301
