"""Trainer-side client of the disaggregated data service.

:class:`ServiceBatchSource` is a zero-arg callable returning an iterator of
``{field: ndarray}`` batches — exactly the ``batch_source=`` contract of
:class:`~petastorm_tpu.jax_utils.loader.JaxDataLoader`, so a trainer swaps
its local reader pipeline for remote workers by changing one constructor
argument and keeps the loader's staging/prefetch/stall accounting unchanged.

Delivery (static mode) is multiplexed: one reader thread per worker stream,
all feeding a single bounded ready-queue the consumer yields from —
whichever worker is ready is consumed, so a slow worker never head-of-line
blocks batches already buffered on its peers. Credit-based flow control
(``credits=``) bounds each worker's un-acknowledged batches in flight: the
``stream`` request carries the window and the client replenishes one credit
per consumed batch, so backpressure composes end to end (worker blocks out
of credits → ready-queue bounds client-side buffering → the loader's
prefetch queue bounds staging).

Failure handling (static mode): a broken worker connection first retries
against the same worker with bounded exponential backoff + jitter
(:func:`petastorm_tpu.utils.retry_with_backoff` — the same policy the GCS
listing sweep uses); if the worker stays dead, the client reports it to the
dispatcher, which re-partitions the dead worker's piece set across the
survivors. Re-delivery restarts those pieces from the beginning:
at-least-once, no sample loss, duplicates possible — the service-tier
analogue of the reader layer's buffered-row resume contract.

Checkpointing: :meth:`ServiceBatchSource.state_dict` snapshots the epoch and
the piece sets whose streams fully completed;
``JaxDataLoader.state_dict()`` delegates here when this source is plugged
in. Pass the snapshot back as ``resume_state=`` to skip completed pieces on
restart (static mode only — fcfs has no per-client resumable position).
"""

from __future__ import annotations

import itertools
import os
import queue
import threading
import time
import uuid

from petastorm_tpu import failpoints
from petastorm_tpu.reader_impl.framed_socket import (
    ConnectionClosedError,
    FramedConnection,
    ProtocolError,
)
from petastorm_tpu.telemetry import tracing
from petastorm_tpu.telemetry.clockalign import OffsetEstimator
from petastorm_tpu.telemetry.flight import RECORDER as FLIGHT
from petastorm_tpu.telemetry.log import service_logger
from petastorm_tpu.service.resilience import (
    CircuitBreaker,
    GapTracker,
    RetryBudget,
    attach_deadline,
    note_brownout_level,
)
from petastorm_tpu.service.seedtree import piece_order
from petastorm_tpu.telemetry.metrics import (
    CLIENT_BATCHES,
    CLIENT_DEDUP_DROPPED,
    CLIENT_FILTER_ROWS,
    CLIENT_READY_QUEUE_DEPTH,
    CLIENT_RECOVERY_EVENTS,
    CLIENT_RECV_STALL,
    CLIENT_TRANSFORM_SECONDS,
    CLIENT_WATERMARK_LAG,
    QUARANTINE_REPORTS,
    RESILIENCE_BREAKER_STATE,
    RESILIENCE_HEDGES,
    RESILIENCE_RETRY_BUDGET,
)
from petastorm_tpu.utils import resize_bounded_queue, retry_with_backoff

logger = service_logger(__name__)


class ServiceError(RuntimeError):
    """A non-transient service-protocol failure (dispatcher/worker replied
    ``error``, or the service cannot make progress)."""


class DegradedDispatcherError(OSError):
    """The dispatcher refused a state-mutating request because it is in
    degraded read-only mode (a journal write failed — ENOSPC). An
    ``OSError`` on purpose: the shared retry policy treats it as
    transient, because every mutating request first attempts recovery (a
    full snapshot compaction) and the next retry may find a healed
    dispatcher (``docs/guides/service.md#failure-model-and-recovery``)."""


class _WorkerStream:
    """One ``stream`` request against one worker; connects lazily so every
    connection failure funnels through ``next_event`` (one recovery path).

    ``credits`` arms flow control: the ``stream`` request carries the
    window, the worker keeps at most that many un-acknowledged batches in
    flight, and :meth:`add_credit` replenishes as batches are consumed.
    ``auto_replenish=True`` acks each batch as soon as it is received —
    the sequential consumption paths (fcfs splits, reconnect probes) where
    receive and consume are the same event; the multiplexed drain uses
    ``False`` and acks from the consumer side of its ready-queue, so the
    window bounds worker-sent-but-unconsumed batches end to end.

    ``tagged=True`` (the static drain's default) requests the exactly-once
    protocol: piece-aligned batches tagged ``(piece, ordinal)`` plus
    ``piece_done`` frames, with ``starts`` naming the per-piece delivery
    watermark the worker must resume each piece at — a re-serve then
    duplicates nothing. A worker whose pool cannot attribute per-piece
    completion ignores the flag and streams untagged batches; the consumer
    detects that per batch (``last_piece is None``) and keeps the legacy
    at-least-once bookkeeping for that stream."""

    def __init__(self, worker_id, address, pieces, epoch, connect_timeout,
                 credits=None, auto_replenish=False, tagged=False,
                 starts=None, shuffle_seed=None, transform_placement=None,
                 job_id=None, recv_timeout=None, packing=None,
                 predicate=None, projection=None, fused=False,
                 cache_stage=None, reader_family=None, transport="auto"):
        self.worker_id = worker_id
        #: Transport tier policy for this stream ("auto"/"tcp"/"shm" —
        #: docs/guides/service.md#transport-tiers): anything but "tcp"
        #: advertises shm on the stream request; the worker decides.
        self.transport = transport
        #: Graph-rewrite stream attributes (frozen per iteration, like the
        #: transform placement — docs/guides/pipeline.md#graph-rewrites):
        #: a hoisted row filter (wire dict) + column projection applied
        #: worker-side below decode, stage fusion, and the cache insertion
        #: point. ``None``/False = the baseline topology.
        self.predicate = predicate
        self.projection = projection
        self.fused = fused
        self.cache_stage = cache_stage
        #: Reader family the worker should serve this stream through
        #: (``row_vs_columnar`` rewrite): ``"columnar"`` asks for
        #: vectorized per-column codec decode; ``None`` keeps the
        #: worker's constructed factory. The worker may fall back to the
        #: row path per stream (exotic codecs/readers) — bytes identical.
        self.reader_family = reader_family
        #: Worker-placement sequence packing: the spec's dict form rides
        #: the stream request; the worker packs pre-serialization and
        #: ordinals/watermarks number PACKED batches. ``None`` = no
        #: packing (or trainer placement).
        self.packing = packing
        #: The trainer job this stream belongs to (multi-tenant fleets):
        #: carried on the stream request so the worker attributes rows
        #: and cache lookups per job. ``None`` = single-tenant legacy.
        self.job_id = job_id
        self.address = tuple(address)
        self.pieces = list(pieces)
        self.epoch = epoch
        self.credits = credits
        self.tagged = tagged
        self.starts = dict(starts or {})
        #: Where the placement-flippable batch transform runs for THIS
        #: stream ("remote"/"local"; None = no transform armed). Carried
        #: on the stream request: "local" tells the worker to skip its
        #: batch_transform — the client applies it instead.
        self.transform_placement = transform_placement
        #: The dispatcher's shuffle seed, forwarded on the stream request
        #: so the worker serves each piece's batches through the epoch's
        #: seed-tree permutation (shuffle-compatible caching: order is
        #: composed at serve time, cached bytes stay canonical).
        self.shuffle_seed = shuffle_seed
        #: Batch id (minted worker-side at decode) of the batch the last
        #: ``next_event`` returned — the tracing key correlating this
        #: stream's receive with the worker's decode/send spans.
        self.last_bid = None
        #: Piece/ordinal tags of the last batch (``None`` on untagged
        #: streams — the legacy protocol).
        self.last_piece = None
        self.last_ordinal = None
        self._auto_replenish = auto_replenish
        self._connect_timeout = connect_timeout
        #: Optional hard deadline on every stream recv (the blocking-read
        #: audit's knob): ``None`` keeps the deliberate timeout-less
        #: socket (keepalive covers silent host death); a value turns a
        #: socket.timeout into the ordinary broken-stream retry path.
        self._recv_timeout = recv_timeout
        self._conn = None
        self._closed = False

    def next_event(self):
        """``(kind, payload)`` — ``("batch", payload_dict)`` (tags exposed
        via ``last_piece``/``last_ordinal``/``last_bid``), ``("piece_done",
        piece)``, ``("piece_failed", (piece, error))`` (the worker
        quarantined a poison piece and keeps streaming the rest), or
        ``("end", None)`` when the stream ended cleanly."""
        if self._closed:
            # Terminal: a teardown close() must not be mistaken for the
            # lazy not-yet-connected state — reconnecting here would send
            # the worker a spurious full stream request nobody consumes.
            raise ConnectionClosedError("stream closed")
        if self._conn is None:
            # connect_timeout bounds the dial only: an inter-batch gap has
            # no upper bound (reader construction, cold storage reads), so
            # the stream socket must not inherit the dial timeout — a slow
            # healthy worker must not be misread as a dead one. Keepalive
            # covers the opposite failure: a worker HOST dying without
            # FIN/RST surfaces as an OSError within ~2 minutes instead of
            # blocking this timeout-less recv forever.
            from petastorm_tpu.service.transport import NegotiatedConnection

            self._conn = NegotiatedConnection(
                FramedConnection.connect(
                    self.address, timeout=self._connect_timeout,
                    stream_timeout=self._recv_timeout, keepalive=True),
                mode=self.transport)
            if self._closed:
                # close() raced the dial: tear the fresh socket down
                # instead of streaming into an abandoned stream object.
                self._conn.close()
                self._conn = None
                raise ConnectionClosedError("stream closed")
            request = {"type": "stream", "pieces": self.pieces,
                       "epoch": self.epoch}
            # Deadline propagation: the stream-open budget is the dial
            # timeout — a request still sitting unstarted in the
            # worker's accept backlog past it is refused worker-side
            # (retryable) instead of building a reader nobody waits for.
            if self._connect_timeout is not None:
                attach_deadline(request,
                                time.monotonic() + self._connect_timeout)
            advert = self._conn.advertisement()
            if advert is not None:
                request["transport"] = advert
            if self.job_id is not None:
                request["job_id"] = self.job_id
            if self.shuffle_seed is not None:
                request["shuffle_seed"] = int(self.shuffle_seed)
            if self.transform_placement is not None:
                request["transform_placement"] = self.transform_placement
            if self.packing is not None:
                request["packing"] = dict(self.packing)
            if self.predicate is not None:
                request["predicate"] = dict(self.predicate)
            if self.projection is not None:
                request["projection"] = list(self.projection)
            if self.fused:
                request["fused"] = True
            if self.cache_stage is not None:
                request["cache_stage"] = self.cache_stage
            if self.reader_family is not None:
                request["reader_family"] = self.reader_family
            if self.tagged:
                request["tagged"] = True
                if self.starts:
                    # JSON object keys are strings on the wire.
                    request["starts"] = {str(p): int(s)
                                         for p, s in self.starts.items()
                                         if s}
            if self.credits is not None:
                request["credits"] = self.credits
            self._conn.send(request)
        header, payload = self._conn.recv()
        kind = header.get("type")
        if kind == "batch":
            self.last_bid = header.get("bid")
            piece = header.get("piece")
            self.last_piece = int(piece) if piece is not None else None
            ordinal = header.get("ordinal")
            self.last_ordinal = int(ordinal) if ordinal is not None else None
            if self._auto_replenish:
                self.add_credit(1)
            return ("batch", payload)
        if kind == "piece_done":
            return ("piece_done", int(header["piece"]))
        if kind == "piece_failed":
            return ("piece_failed", (int(header["piece"]),
                                     str(header.get("error", ""))))
        if kind == "end":
            self.close()
            return ("end", None)
        if kind == "error":
            if header.get("retryable"):
                # DEADLINE_EXCEEDED and kin: transient by contract —
                # funnel into the broken-stream retry/takeover path
                # (ConnectionError ⊂ OSError) instead of the fatal
                # bad-plan ServiceError.
                raise ConnectionClosedError(
                    f"worker {self.worker_id} refused stream (retryable): "
                    f"{header.get('error')}")
            raise ServiceError(
                f"worker {self.worker_id} failed streaming pieces "
                f"{self.pieces}: {header.get('error')}")
        raise ServiceError(f"unexpected stream message {kind!r}")

    def next_batch(self):
        """Next batch dict, or ``None`` when the stream ended cleanly —
        the sequential-consumption convenience over :meth:`next_event`
        (fcfs splits and reconnect probes; ``piece_done`` markers are
        consumed silently)."""
        while True:
            kind, payload = self.next_event()
            if kind == "batch":
                return payload
            if kind == "end":
                return None

    def add_credit(self, n=1):
        """Replenish ``n`` credits of the worker's flow-control window.

        Send-only (safe against the reader thread's concurrent ``recv`` —
        opposite directions of the same socket); a no-op without credits
        or after close, and a broken socket is swallowed — the receive
        path owns failure detection and recovery."""
        conn = self._conn
        if conn is None or self.credits is None:
            return
        try:
            conn.send({"type": "credit", "n": n})
        except OSError:
            pass

    def close(self):
        self._closed = True
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class _SourceIterator:
    """Iterator wrapper carrying delivery metadata the loader reads.

    ``prefetched=True`` declares that the underlying iteration already runs
    its own producer threads and bounded buffering (the multiplexed drain's
    reader threads + ready-queue), so a consumer like ``JaxDataLoader`` can
    skip its own producer-thread prefetch hop and pull batches directly —
    one fewer thread wakeup per batch on the hot path, with the same
    end-to-end buffering bound."""

    def __init__(self, gen, prefetched):
        self._gen = gen
        self.prefetched = prefetched

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._gen)

    def close(self):
        self._gen.close()


class _OrderedSequencer:
    """Reorder buffer enforcing the deterministic delivery order.

    Workers race batches into the shared ready-queue in whatever order the
    fleet produces them; byte-identical streams need one canonical order
    — the seed-tree piece order, batches within a piece by ordinal. The
    drain pushes every received batch (and every ``piece_done``) in here
    and yields only what :meth:`push`/:meth:`finish_piece` release: the
    current piece's batches immediately, later pieces' buffered until
    their turn. Per-piece arrival is already ordinal-ordered (FIFO per
    stream; watermark re-serves continue where delivery stopped), so
    buffering is append-only.

    Buffer depth (exported as the ``client_watermark_lag`` gauge) is
    ~(streams × credits) in the common case, but it is NOT a hard bound:
    credits must be acked at dequeue, not at release — the engine's
    decode lookahead (and wholesale warm-cache staging, and dynamic-mode
    steals re-queueing a canonically-early piece behind later ones) can
    legally fill a stream's window with batches of a canonically-later
    piece while an earlier one is still pending, and a release-parked
    window would deadlock the epoch. Under a persistent head-of-line
    stall (one dead-slow worker owning the current piece) the buffer can
    therefore grow toward the stalled-behind remainder of the epoch —
    watch the gauge; ordered mode trades memory and head-of-line waiting
    for byte-identical delivery.
    """

    def __init__(self, order):
        self._order = [int(p) for p in order]
        self._pos = 0
        self._buffered = {}    # piece -> [items]
        self._done = {}        # piece -> worker_id (piece_done arrived)
        self.lag = 0           # buffered batches (watermark-lag gauge)

    def push(self, piece, item):
        """Buffer one received batch; return the ``("batch", piece, item)``
        / ``("piece_done", piece, wid)`` events now releasable in order."""
        self._buffered.setdefault(piece, []).append(item)
        self.lag += 1
        return self._release()

    def finish_piece(self, piece, worker_id):
        self._done[piece] = worker_id
        return self._release()

    def _release(self):
        out = []
        while self._pos < len(self._order):
            piece = self._order[self._pos]
            buffered = self._buffered.get(piece)
            if buffered:
                for item in buffered:
                    out.append(("batch", piece, item))
                self.lag -= len(buffered)
                buffered.clear()
            if piece in self._done:
                self._buffered.pop(piece, None)
                out.append(("piece_done", piece, self._done.pop(piece)))
                self._pos += 1
                continue
            break
        return out

    def drain(self):
        """Flush everything still buffered, in order (epoch teardown
        safety net — empty when every piece announced ``piece_done``)."""
        out = []
        for piece in self._order[self._pos:] + sorted(
                set(self._buffered) - set(self._order[self._pos:])):
            for item in self._buffered.pop(piece, []):
                out.append(("batch", piece, item))
                self.lag -= 1
            if piece in self._done:
                out.append(("piece_done", piece, self._done.pop(piece)))
        self._pos = len(self._order)
        return out


class _DeliveryBook:
    """Consumer-side delivery bookkeeping shared by the static and dynamic
    drains: production counts, per-worker attribution, tagged batch events
    (the provenance ``state_dict`` computes watermarks from), piece
    completion, and the ordered-mode release loop. One implementation so
    the two drains' snapshots cannot silently diverge.
    """

    def __init__(self, source, epoch):
        self._source = source
        self._epoch = epoch

    def account_yielded(self, piece, ordinal, wid, bid):
        """One batch is about to be yielded to the consumer."""
        source = self._source
        with source._lock:
            source._production_count += 1
            source._note_consumed_locked(wid)
            if piece is not None and ordinal is not None:
                source._batch_events.append(
                    (source._production_count, self._epoch, piece, ordinal))
        source.last_bid = bid

    def complete_piece(self, piece, wid):
        """One piece fully yielded to the consumer (its ``piece_done``
        cleared the drain — in ordered mode, cleared the sequencer)."""
        source = self._source
        with source._lock:
            if piece in source._completed:
                return
            source._completed.add(piece)
            source._events.append(
                (source._production_count, self._epoch, [piece]))
            source._note_pieces_locked(wid, 1)

    def emit(self, released):
        """Yield a sequencer's released events in order (generator — the
        drain ``yield from``s it). Buffered batch items are
        ``(ordinal, payload, stream, bid, t_enqueued)``."""
        collector = tracing.COLLECTOR
        for ev in released:
            if ev[0] == "batch":
                _, rpiece, (rordinal, rpayload, rstream, rbid, rt) = ev
                self.account_yielded(rpiece, rordinal, rstream.worker_id,
                                     rbid)
                if collector.enabled:
                    collector.record_span("client.queue", rt,
                                          time.perf_counter(), bid=rbid)
                yield rpayload
            else:
                _, rpiece, rwid = ev
                self.complete_piece(rpiece, rwid)


class _StreamReader(threading.Thread):
    """One worker stream's receive loop: pulls events and feeds the shared
    ready-queue as ``(kind, sid, item)`` events — ``batch`` per payload
    (piece/ordinal tags riding along on the exactly-once protocol),
    ``piece_done`` per finished piece (tagged streams only), then one
    terminal ``end`` (clean), ``broken`` (connection-type failure →
    consumer retry/takeover), or ``error`` (``ServiceError`` → consumer
    re-raises). Bookkeeping stays on the consumer side of the queue; this
    thread only reports its receive-stall seconds via ``note_recv``."""

    def __init__(self, sid, stream, ready, stop, note_recv):
        super().__init__(daemon=True,
                         name=f"service-stream-{stream.worker_id}")
        self._sid = sid
        self._stream = stream
        self._ready = ready
        # NB: Thread owns a private `_stop` method — don't shadow it.
        self._stopped = stop
        self._note_recv = note_recv

    def run(self):
        collector = tracing.COLLECTOR
        try:
            while not self._stopped.is_set():
                t0 = time.perf_counter()
                try:
                    kind, payload = self._stream.next_event()
                except (ConnectionClosedError, ConnectionError,
                        OSError, ProtocolError) as exc:
                    # A close() from the consumer's teardown also lands here
                    # — the stop flag distinguishes it from a real failure.
                    # ProtocolError = the socket desynced (torn frame):
                    # framing is lost, so it is a broken connection too.
                    if not self._stopped.is_set():
                        self._put(("broken", self._sid, exc))
                    return
                t1 = time.perf_counter()
                self._note_recv(self._stream.worker_id, t1 - t0,
                                kind == "batch")
                if kind == "end":
                    self._put(("end", self._sid, None))
                    return
                if kind in ("piece_done", "piece_failed"):
                    self._put((kind, self._sid, payload))
                    continue
                bid = self._stream.last_bid
                if collector.enabled:
                    collector.record_span("client.recv", t0, t1, bid=bid)
                # The enqueue timestamp travels with the batch so the
                # consumer can record the ready-queue residency span.
                self._put(("batch", self._sid,
                           (payload, self._stream.last_piece,
                            self._stream.last_ordinal, bid, t1)))
        except BaseException as exc:
            # ServiceError and anything unexpected: forward as a terminal
            # event for the consumer to re-raise — a reader dying silently
            # would hang the consumer's queue.get forever.
            self._put(("error", self._sid, exc))

    def _put(self, event):
        # Bounded queue: block with a stop check so teardown never hangs a
        # reader behind a full queue the consumer abandoned.
        while not self._stopped.is_set():
            try:
                self._ready.put(event, timeout=0.1)
                return
            except queue.Full:
                continue


class _DynamicStream:
    """One persistent dynamic-mode stream against one worker.

    Unlike :class:`_WorkerStream`, the piece set is editable mid-stream:
    :meth:`extend` appends steal grants, :meth:`revoke` asks the worker's
    streaming engine to drop not-yet-sent pieces (acked with a ``revoked``
    frame naming the subset actually removed), and :meth:`finish` closes
    the queue so the worker drains and sends ``end``. All senders are
    send-only and safe against the reader thread's concurrent ``recv``
    (opposite directions of one socket, like credit replenishment); a
    broken socket is swallowed — the receive path owns failure detection,
    and every piece still outstanding on this worker is re-granted by the
    takeover path when the stream reports broken."""

    def __init__(self, worker_id, address, pairs, epoch, connect_timeout,
                 credits=None, shuffle_seed=None, transform_placement=None,
                 job_id=None, recv_timeout=None, packing=None,
                 predicate=None, projection=None, fused=False,
                 cache_stage=None, reader_family=None, transport="auto"):
        self.worker_id = worker_id
        self.transport = transport  # see _WorkerStream.transport
        self.job_id = job_id  # see _WorkerStream.job_id
        self.packing = packing  # see _WorkerStream.packing
        self.predicate = predicate  # see _WorkerStream: rewrite attributes
        self.projection = projection
        self.fused = fused
        self.cache_stage = cache_stage
        self.reader_family = reader_family
        self.address = tuple(address)
        # initial [(piece, generation, start)] — start = the client's
        # delivery watermark, so a (re)opened stream never repeats batches
        self.pairs = [self._triple(t) for t in pairs]
        self.epoch = epoch
        self.credits = credits
        self.shuffle_seed = shuffle_seed  # see _WorkerStream.shuffle_seed
        self.transform_placement = transform_placement  # see _WorkerStream
        self._connect_timeout = connect_timeout
        self._recv_timeout = recv_timeout  # see _WorkerStream._recv_timeout
        self._conn = None
        self._closed = False
        self._send_lock = threading.Lock()
        self._pre_conn = []  # control messages queued before the handshake

    def _ensure_conn(self):
        if self._closed:
            raise ConnectionClosedError("stream closed")
        with self._send_lock:
            if self._conn is not None:
                return self._conn
            from petastorm_tpu.service.transport import NegotiatedConnection

            conn = NegotiatedConnection(
                FramedConnection.connect(
                    self.address, timeout=self._connect_timeout,
                    stream_timeout=self._recv_timeout, keepalive=True),
                mode=self.transport)
            if self._closed:
                conn.close()
                raise ConnectionClosedError("stream closed")
            request = {"type": "stream", "dynamic": True,
                       "pieces": [list(t) for t in self.pairs],
                       "epoch": self.epoch}
            advert = conn.advertisement()
            if advert is not None:
                request["transport"] = advert
            if self.job_id is not None:
                request["job_id"] = self.job_id
            if self.shuffle_seed is not None:
                request["shuffle_seed"] = int(self.shuffle_seed)
            if self.transform_placement is not None:
                request["transform_placement"] = self.transform_placement
            if self.packing is not None:
                request["packing"] = dict(self.packing)
            if self.predicate is not None:
                request["predicate"] = dict(self.predicate)
            if self.projection is not None:
                request["projection"] = list(self.projection)
            if self.fused:
                request["fused"] = True
            if self.cache_stage is not None:
                request["cache_stage"] = self.cache_stage
            if self.reader_family is not None:
                request["reader_family"] = self.reader_family
            if self.credits is not None:
                request["credits"] = self.credits
            try:
                conn.send(request)
                # Flush control traffic (extend/revoke/finish) that raced
                # the handshake: the stream request always goes first, and
                # queued edits follow in their original order.
                for message in self._pre_conn:
                    conn.send(message)
            except BaseException:
                conn.close()
                raise
            del self._pre_conn[:]
            self._conn = conn
            return self._conn

    @staticmethod
    def _triple(t):
        t = list(t)
        return (int(t[0]), int(t[1]), int(t[2]) if len(t) > 2 else 0)

    def next_event(self):
        """``(kind, payload)`` — ``("batch", (piece, gen, ordinal,
        payload, bid))``, ``("piece_done", (piece, gen, rows))``,
        ``("revoked", (req, pieces))``, or ``("end", None)``."""
        conn = self._ensure_conn()
        header, payload = conn.recv()
        kind = header.get("type")
        if kind == "batch":
            ordinal = header.get("ordinal")
            return ("batch", (int(header.get("piece", -1)),
                              int(header.get("generation", 0)),
                              int(ordinal) if ordinal is not None else None,
                              payload, header.get("bid")))
        if kind == "piece_done":
            return ("piece_done", (int(header["piece"]),
                                   int(header.get("generation", 0)),
                                   int(header.get("rows", 0))))
        if kind == "piece_failed":
            return ("piece_failed", (int(header["piece"]),
                                     int(header.get("generation", 0)),
                                     str(header.get("error", ""))))
        if kind == "revoked":
            return ("revoked", (header.get("req"),
                                [int(p) for p in header.get("pieces", [])]))
        if kind == "end":
            self.close()
            return ("end", None)
        if kind == "error":
            raise ServiceError(
                f"worker {self.worker_id} failed its dynamic stream: "
                f"{header.get('error')}")
        raise ServiceError(f"unexpected dynamic stream message {kind!r}")

    def _send(self, message):
        with self._send_lock:
            if self._closed:
                return
            if self._conn is None:
                # The reader thread has not dialed yet: queue the edit —
                # dropping it would orphan a stolen piece (ownership maps
                # already say this worker has it) and hang the epoch.
                self._pre_conn.append(message)
                return
            try:
                self._conn.send(message)
            except OSError:
                pass  # receive path detects and recovers the broken stream

    def extend(self, pairs):
        self._send({"type": "extend",
                    "pieces": [list(self._triple(t)) for t in pairs]})

    def revoke(self, pieces, req):
        self._send({"type": "revoke", "pieces": [int(p) for p in pieces],
                    "req": req})

    def finish(self):
        self._send({"type": "finish_pieces"})

    def add_credit(self, n=1):
        if self.credits is None:
            return
        self._send({"type": "credit", "n": n})

    def close(self):
        self._closed = True
        if self._conn is not None:
            self._conn.close()
            self._conn = None


class _DynamicStreamReader(threading.Thread):
    """Receive loop of one dynamic stream: every event is posted to the
    shared ready-queue as ``(kind, sid, item)`` — the dynamic analogue of
    :class:`_StreamReader`, with the richer event vocabulary (``dbatch``,
    ``piece_done``, ``revoked``, terminal ``end``/``broken``/``error``)."""

    def __init__(self, sid, stream, ready, stop, note_recv):
        super().__init__(daemon=True,
                         name=f"service-dynstream-{stream.worker_id}")
        self._sid = sid
        self._stream = stream
        self._ready = ready
        self._stopped = stop
        self._note_recv = note_recv

    def run(self):
        collector = tracing.COLLECTOR
        try:
            while not self._stopped.is_set():
                t0 = time.perf_counter()
                try:
                    kind, item = self._stream.next_event()
                except (ConnectionClosedError, ConnectionError,
                        OSError, ProtocolError) as exc:
                    if not self._stopped.is_set():
                        self._put(("broken", self._sid, exc))
                    return
                t1 = time.perf_counter()
                self._note_recv(self._stream.worker_id, t1 - t0,
                                kind == "batch")
                if kind == "end":
                    self._put(("end", self._sid, None))
                    return
                if kind == "batch":
                    piece, gen, ordinal, payload, bid = item
                    if collector.enabled:
                        collector.record_span("client.recv", t0, t1,
                                              bid=bid)
                    self._put(("dbatch", self._sid,
                               (piece, gen, ordinal, payload, bid, t1)))
                else:  # piece_done / revoked
                    self._put((kind, self._sid, item))
        except BaseException as exc:
            self._put(("error", self._sid, exc))

    def _put(self, event):
        while not self._stopped.is_set():
            try:
                self._ready.put(event, timeout=0.1)
                return
            except queue.Full:
                continue


class ServiceBatchSource:
    """Stream remote batches from a dispatcher's worker fleet.

    :param dispatcher_address: ``(host, port)`` of the dispatcher.
    :param client_index/num_clients: this trainer's static shard (static
        mode; ignored by fcfs).
    :param max_retries: reconnect attempts per failed worker before the
        failure is reported to the dispatcher for re-assignment.
    :param backoff_base/backoff_max: exponential-backoff bounds (seconds).
    :param resume_state: a prior :meth:`state_dict` snapshot — completed
        pieces are skipped on the resumed epoch (static mode only).
    :param credits: per-worker flow-control window — a worker keeps at most
        this many un-acknowledged batches in flight; the client replenishes
        as it consumes. ``None`` disables flow control (unbounded push,
        the pre-credit protocol). Default 8: deep enough to hide a
        consume-ack round trip, shallow enough that a pause stops pulling
        within ~`credits` batches per worker.
    :param ready_queue_depth: bound of the shared ready-queue the
        multiplexed drain yields from. ``None`` derives it from the
        flow-control window: ``max(4, min(streams × credits, 256))`` —
        the queue can absorb every un-acked batch the credit windows
        allow in flight, so a full window never wedges reader threads
        mid-handoff (overrun) and the consumer never drains the queue dry
        while credits still permit deliveries (starvation). Without
        credits (``credits=None``, unbounded push) the legacy
        ``max(4, 2 × streams)`` sizing applies
        (``docs/guides/service.md#flow-control``). Settable live via
        :meth:`set_ready_queue_depth` (the autotuner's binding).
    :param heartbeat_interval_s: poll the dispatcher's ``client_heartbeat``
        this often while a static drain is live. The heartbeat carries the
        dispatcher's fencing epoch: when it moves past the epoch this
        client last synced its assignment at (dispatcher restart, worker
        eviction), the drain resyncs — it re-fetches the assignment and
        retires only the streams whose piece→worker mapping actually
        changed, so a journal-backed restart that restores identical
        assignments is a no-op (zero duplicate rows). ``None`` disables
        the loop (fencing changes are then only seen through broken
        streams and ``stale_fencing`` replies).
    :param rpc_deadline_s: total time budget per dispatcher control RPC
        across all retries (the shared ``retry_with_backoff`` policy) —
        bounds how long a dispatcher outage can stall a control call.
    :param max_frame_bytes: receive frame cap for this client's
        connections (``None`` = the module default).
    :param dynamic_sync_interval_s: dynamic mode only — how often the
        rebalance loop reports progress/backlog to the dispatcher and
        applies the steal deltas it replies with. A drained worker also
        pokes the loop immediately, so steal latency is not bounded by
        this interval; it mostly caps how stale the dispatcher's
        backlog/rate view may get.
    :param ordered: deterministic delivery order. The multiplexed drain
        normally yields whichever worker's batch is ready (fast, but the
        interleaving varies run to run); ``ordered=True`` re-sequences
        delivery into the canonical order — pieces in the seed-tree order
        of the dispatcher's ``shuffle_seed`` (ascending without one),
        batches within a piece by ordinal — so two runs (any fleet shape,
        any steal/failure history) yield byte-identical streams. Costs a
        reorder buffer (~streams × credits batches in the common case,
        exported live as ``client_watermark_lag``; a persistent
        head-of-line stall can grow it past that — see
        ``_OrderedSequencer``) and re-introduces head-of-line waiting
        on the piece whose turn it is. Static and dynamic modes only.
    :param transform: the placement-flippable collated-batch transform —
        a ``{field: ndarray} -> {field: ndarray}`` callable, the SAME
        computation the service's workers were configured with
        (``BatchWorker(batch_transform=...)``). Where it runs is decided
        by ``transform_placement``; the callable must be armed on both
        sides for the flip to be meaningful
        (``docs/guides/pipeline.md#transform-placement``).
    :param transform_placement: ``"remote"`` (default — workers apply
        their ``batch_transform`` before serializing, today's layout) or
        ``"local"`` (stream requests tell workers to skip it and this
        client applies ``transform`` to each received batch on the
        trainer host). Sampled once per iteration: a
        :meth:`set_transform_placement` flip (the autotuner's binding)
        takes effect at the next epoch/iteration boundary, never
        mid-stream.
    :param job_id: the trainer JOB this source belongs to (multi-tenant
        fleets — ``docs/guides/service.md#multi-tenancy-and-autoscaling``).
        Carried on every control request and stream, so the dispatcher
        scopes fencing and assignments per job and workers attribute rows
        and cache lookups per job. Register the job first with
        :func:`petastorm_tpu.service.fleet.register_job` for non-default
        weights/quotas (and always pair with ``end_job``); an
        unregistered job id materializes with weight 1.0. ``None``
        (default) = the implicit single-tenant job — today's behavior,
        bit for bit. The dispatcher's fair-share plan may scale this
        job's flow-control windows (``credit_scale`` on assignment
        replies): a job granted half the fair share opens its next
        streams with half the configured credit window.
    :param on_piece_error: poison-piece policy, the client half (pair
        with ``BatchWorker(on_piece_error=...)``). ``"fail"`` (default):
        a worker's ``piece_failed`` frame raises :class:`ServiceError`
        into the training loop. ``"quarantine"``: the piece is recorded
        (``diagnostics["quarantined_pieces"]``, recovery counter
        ``pieces_quarantined``), reported to the dispatcher
        (``report_poison_piece`` — journaled, excluded from re-grant),
        and the drain completes the piece with zero rows so every
        HEALTHY piece still delivers exactly-once and the epoch
        finishes (``docs/guides/service.md#failure-model-and-recovery``).
    :param stream_recv_timeout_s: optional hard deadline (seconds) on
        every batch-stream ``recv``. Default ``None`` — deliberately
        timeout-less, because an inter-batch gap has no upper bound
        (reader construction, cold storage reads) and TCP keepalive
        already bounds silent host death to ~2 minutes. Set it when the
        deployment wants a hard latency ceiling instead: a tick without
        a byte then surfaces as an ordinary broken stream and rides the
        shared ``retry_with_backoff`` recovery (same-worker retry →
        takeover), exactly-once throughout.
    :param transport: data-plane tier — ``"auto"`` (default: streams
        against a colocated worker negotiate the shared-memory ring,
        everything else rides TCP), ``"tcp"`` (never negotiate), or
        ``"shm"`` (same negotiation as auto — still TCP when the worker
        is cross-host or setup fails; the tier is never required for
        correctness). ``None`` defers to the ``PETASTORM_TRANSPORT``
        env var (``docs/guides/service.md#transport-tiers``). Delivery
        semantics — ordering, watermarks, dedup, fencing — are
        byte-identical across tiers.
    """

    def __init__(self, dispatcher_address, client_index=0, num_clients=1,
                 client_id=None, connect_timeout=10.0, max_retries=3,
                 backoff_base=0.05, backoff_max=2.0, resume_state=None,
                 credits=8, ready_queue_depth=None, heartbeat_interval_s=2.0,
                 rpc_deadline_s=30.0, max_frame_bytes=None,
                 dynamic_sync_interval_s=0.25, ordered=False,
                 transform=None, transform_placement="remote",
                 job_id=None, on_piece_error="fail",
                 stream_recv_timeout_s=None, packing=None, corpus="",
                 predicate=None, projection=None, filter_placement="client",
                 stage_fusion="off", cache_placement="post-transform",
                 reader_family=None, transport=None, hedging=False,
                 hedge_quantile=0.99, hedge_multiplier=4.0,
                 hedge_min_samples=16, hedge_floor_s=0.25,
                 breaker_threshold=5, breaker_cooldown_s=5.0,
                 retry_budget=10.0):
        from petastorm_tpu.service.transport import resolve_mode

        # Transport tier policy, resolved once (explicit arg >
        # PETASTORM_TRANSPORT env > "auto") and carried by every stream
        # this source opens — takeover/resync relaunches included
        # (docs/guides/service.md#transport-tiers).
        self._transport = resolve_mode(transport)
        if credits is not None and credits < 1:
            raise ValueError("credits must be a positive integer or None")
        if on_piece_error not in ("fail", "quarantine"):
            raise ValueError(
                "on_piece_error must be 'fail' or 'quarantine', got "
                f"{on_piece_error!r}")
        if ready_queue_depth is not None and ready_queue_depth < 1:
            raise ValueError(
                "ready_queue_depth must be a positive integer or None")
        if transform_placement not in ("remote", "local"):
            raise ValueError(
                "transform_placement must be 'remote' or 'local'")
        if transform is None and transform_placement == "local":
            raise ValueError(
                "transform_placement='local' needs the transform callable: "
                "workers are told to skip their batch_transform, so "
                "without one here the stage would silently not run at all")
        self._dispatcher_address = tuple(dispatcher_address)
        self.client_index = client_index
        self.num_clients = num_clients
        self.job_id = str(job_id) if job_id is not None else None
        # Multi-corpus fleets: request assignments over the named corpus's
        # worker group ("" = the default single-dataset corpus). Rides
        # every control request that plans or repairs piece ownership.
        self.corpus = str(corpus or "")
        # Worker-placement sequence packing (docs/guides/llm.md): the
        # spec rides every stream request; workers pack pre-serialization
        # and delivered batches arrive packed. Flipped (next-iteration)
        # by PackedBatchSource.set_packing_placement via set_packing.
        self._packing = None
        if packing is not None:
            from petastorm_tpu.service.packing_stage import PackingSpec

            self._packing = PackingSpec.from_dict(packing)
        if self._packing is not None and transform is not None:
            raise ValueError(
                "packing= and transform= cannot combine on one source: "
                "the batch transform is a row-batch stage and packing "
                "changes the batch vocabulary — apply the transform "
                "upstream (transform_spec) instead")
        self._iter_packing = self._packing
        # Declared row filter + column projection (the filter-hoisting
        # rewrite's operands — docs/guides/pipeline.md#graph-rewrites).
        # The predicate must be declarative (ColumnPredicate / wire dict):
        # only pure data can cross to the workers when the planner hoists
        # it below decode. filter_placement names where it runs THIS
        # iteration's topology: "client" (the baseline — batches arrive
        # unfiltered and are masked trainer-side) or "worker" (hoisted —
        # dropped rows never decode, never cross the wire).
        self._predicate = None
        if predicate is not None:
            from petastorm_tpu.predicates import ColumnPredicate

            if isinstance(predicate, ColumnPredicate):
                self._predicate = predicate
            else:
                self._predicate = ColumnPredicate.from_wire(predicate)
        if filter_placement not in ("client", "worker"):
            raise ValueError(
                "filter_placement must be 'client' or 'worker'")
        if predicate is None and filter_placement == "worker":
            raise ValueError(
                "filter_placement='worker' needs predicate=: there is "
                "no row filter to hoist")
        if self._predicate is not None and self._packing is not None:
            raise ValueError(
                "predicate= and packing= cannot combine on one source: "
                "packing changes the batch vocabulary (token slots, not "
                "rows) — filter upstream (reader predicate) or drop one")
        if projection and transform is not None \
                and (predicate is None or filter_placement != "worker"):
            # A client-side projection would prune AFTER a remote
            # transform but BEFORE a local one — a placement flip would
            # change the transform's input. Hoisted projection (rides the
            # worker-placed filter, pruned below decode) transforms the
            # projected batch identically under both placements.
            raise ValueError(
                "projection= with transform= requires the hoisted filter "
                "topology (predicate= with filter_placement='worker'): "
                "client-side pruning would run after a remote transform "
                "but before a local one, so a transform_placement flip "
                "would change the transform's input")
        if self._predicate is not None and transform is not None \
                and filter_placement != "worker":
            # A remote transform runs BEFORE a client-placed filter would,
            # so the filter would evaluate post-transform values (or miss
            # its column entirely) — a different survivor set than the
            # hoisted topology, silently. The hoisted placement is the
            # only one where filter (below decode) and transform (above
            # collate) compose unambiguously: require it.
            raise ValueError(
                "predicate= with transform= requires "
                "filter_placement='worker': a client-placed filter would "
                "see post-transform batches (the worker transforms before "
                "shipping), diverging from the hoisted topology's "
                "stored-value semantics")
        self._projection = (sorted(str(f) for f in projection)
                            if projection else None)
        self._filter_placement = filter_placement
        if stage_fusion not in ("off", "fused"):
            raise ValueError("stage_fusion must be 'off' or 'fused'")
        self._stage_fusion = stage_fusion
        if cache_placement not in ("post-transform", "post-decode"):
            raise ValueError(
                "cache_placement must be 'post-transform' or 'post-decode'")
        if cache_placement == "post-decode" and transform is None:
            raise ValueError(
                "cache_placement='post-decode' is only meaningful with a "
                "transform= armed (without one the two placements cache "
                "identical bytes)")
        self._cache_placement = cache_placement
        # Reader family the workers serve this source's streams through
        # (the row_vs_columnar rewrite — docs/guides/pipeline.md#graph-
        # rewrites): None keeps each worker's constructed factory, "row"
        # pins per-row decode, "columnar" asks for vectorized per-column
        # codec kernels. Decoded bytes are identical either way; workers
        # lacking a columnar path for the stream (exotic codecs, ngram,
        # batch-family datasets) fall back to the row path per stream.
        if reader_family not in (None, "row", "columnar"):
            raise ValueError(
                "reader_family must be None, 'row', or 'columnar', got "
                f"{reader_family!r}")
        self._reader_family = reader_family
        # Iteration-frozen copies (set at __call__, like the transform
        # placement): every stream of one iteration — takeover/resync
        # relaunches included — carries the same rewrite attributes.
        self._iter_predicate = None
        self._iter_projection = None
        self._iter_filter_placement = None
        self._iter_hoisted = False
        self._iter_fused = False
        self._iter_cache_stage = None
        self._iter_reader_family = None
        # Batches the trainer-local filter dropped ENTIRELY this iteration
        # (every row failed the predicate): breaks the 1:1 received↔
        # yielded correspondence the prefetch-lag-exact state_dict needs —
        # tracked so state_dict can refuse loudly instead of silently
        # mispositioning a resume (hoist the filter for checkpointable
        # filtered pipelines).
        self._filter_dropped_batches = 0
        # The dispatcher's fair-share credit scaling for this job (1.0 =
        # full window). Updated from assignment/plan/sync replies; applied
        # to streams opened AFTER the update, like set_credits.
        self._credit_scale = 1.0
        self.client_id = client_id or (
            f"client-{client_index}-{os.getpid()}-{uuid.uuid4().hex[:6]}")
        self._connect_timeout = connect_timeout
        self._max_retries = max_retries
        self._backoff_base = backoff_base
        self._backoff_max = backoff_max
        self._credits = credits
        self._ready_queue_depth = ready_queue_depth
        self.transform = transform
        self._transform_placement = transform_placement
        # Placement in force for the CURRENT iteration (frozen at
        # __call__): all of an iteration's streams — including takeover /
        # resync relaunches — carry the same placement, so the client-side
        # applier can wrap the whole iterator instead of tracking
        # placement per batch.
        self._iter_transform_placement = (transform_placement
                                          if transform is not None else None)
        self._heartbeat_interval_s = heartbeat_interval_s
        self._rpc_deadline_s = rpc_deadline_s
        self._max_frame_bytes = max_frame_bytes
        self._on_piece_error = on_piece_error
        self._stream_recv_timeout_s = stream_recv_timeout_s
        self._quarantined = []  # [{"piece","worker_id","error","epoch"}]
        self._dynamic_sync_interval_s = dynamic_sync_interval_s
        self._ordered = bool(ordered)
        self._shuffle_seed = None     # dispatcher config, read at __call__
        self._ready_queue = None      # live queue while a drain is active
        self._live_stream_count = 1   # streams feeding the live queue
        self._per_worker = {}         # worker_id -> delivery counters
        self._lock = threading.Lock()
        self._log = logger.bind(client_id=self.client_id)
        #: Batch id of the most recently yielded batch (tracing: the
        #: loader reads it right after pulling on the direct path — same
        #: thread, so the association is exact).
        self.last_bid = None
        self._mode = None
        self._epoch = 0
        self._completed = set()
        # Fencing: the dispatcher's epoch at which the current assignment
        # was fetched (or last resynced). The heartbeat loop compares the
        # dispatcher's live epoch against it; _fence_pending dedupes fence
        # events posted into the drain's ready-queue.
        self._synced_fencing_epoch = 0
        self._fence_pending = False
        self._recovery = {
            "resyncs": 0,             # fence-triggered assignment refreshes
            "resync_failures": 0,     # resyncs deferred (dispatcher not
            #                           ready) — retried by the heartbeat
            "streams_retired": 0,     # live streams torn down by a resync
            "takeovers": 0,           # dead-worker piece re-assignments
            "stale_fencing_retries": 0,
            "heartbeat_failures": 0,  # dispatcher unreachable at a tick
            "steals_applied": 0,      # dynamic: revoke-ack'd piece moves
            "steals_failed": 0,       # dynamic: steals the donor beat
            "dedup_dropped": 0,       # dynamic: stale-generation batches
            "duplicates_dropped": 0,  # sub-watermark batches a re-serve
            #                           repeated (the exactly-once safety
            #                           net — 0 when the worker-side
            #                           watermark skip did its job)
            "pieces_quarantined": 0,  # poison pieces recorded under
            #                           on_piece_error="quarantine"
            "fencing_epoch": 0,       # last fencing epoch observed
            "dispatcher": {},         # dispatcher recovery counters (last
        }                             # heartbeat reply)
        # Per-piece delivery watermarks for the epoch in flight: the next
        # batch ordinal expected from the network (batches below it were
        # already received — yielded or sitting in the ordered-mode reorder
        # buffer). Every re-serve path (retry, takeover, resync relaunch)
        # reads these as the `starts` it re-grants pieces at; sub-watermark
        # arrivals are dropped as duplicates. Guarded by ``_lock`` (the
        # recovery threads and the heartbeat read them concurrently).
        self._recv_watermarks = {}
        self._resume_watermarks = {}
        # -- resilience layer (service/resilience.py) ----------------------
        # Per-peer circuit breakers + retry budgets: consecutive stream
        # failures against one worker trip its breaker (fail fast, report
        # to the dispatcher for routing exclusion, take the takeover path
        # immediately); retries spend its budget and successes refill it,
        # so a degraded worker gets a bounded retry rate. Guarded by
        # ``_lock`` (recovery threads race the drain).
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_cooldown_s = float(breaker_cooldown_s)
        self._retry_budget_capacity = float(retry_budget)
        self._breakers = {}        # worker_id -> CircuitBreaker
        self._budgets = {}         # worker_id -> RetryBudget
        self._breakers_reported = set()  # wids reported breaker-open
        self._dispatcher_budget = RetryBudget(
            capacity=self._retry_budget_capacity)
        # Hedged watermark re-serves: when a live stream's inter-batch
        # gap exceeds the GapTracker's fitted threshold, the drain
        # launches a duplicate re-grant of its in-flight piece at the
        # delivery watermark from a peer worker — first batch wins, the
        # loser is cancelled, sub-watermark duplicates drop through the
        # existing dedup. OFF by default: identical topology to PR 17.
        self._hedging = bool(hedging)
        self._gap_tracker = GapTracker(
            quantile=hedge_quantile, multiplier=hedge_multiplier,
            min_samples=hedge_min_samples, floor_s=hedge_floor_s)
        self._hedge_counts = {"launched": 0, "won": 0, "lost": 0}
        # Fleet-clock alignment + tracing beacon state, mirroring the
        # worker's (docs/guides/diagnostics.md#clock-alignment): NTP-style
        # offset samples around each heartbeat, and whether the
        # dispatcher's heartbeat replies currently arm fleet tracing.
        self._clock = OffsetEstimator()
        self._trace_armed_remote = False
        FLIGHT.set_context(role="client", client_id=self.client_id)
        # Injection point for the fcfs retry loop's backoff sleeps (the
        # budget-aware analogue of ``retry_with_backoff``'s ``sleep=``).
        self._retry_sleep = time.sleep
        if resume_state is not None:
            self._validate_resume_state(resume_state)
            self._epoch = int(resume_state["epoch"])
            self._completed = set(int(p)
                                  for p in resume_state["completed_pieces"])
            self._resume_watermarks = {
                int(p): int(n)
                for p, n in (resume_state.get("watermarks") or {}).items()}
            self._resume_seed = resume_state.get("shuffle_seed")
            self._resume_has_seed = "shuffle_seed" in resume_state
        self._resumed = resume_state is not None
        # Production-order bookkeeping for state_dict(): the n-th produced
        # batch is the n-th batch the consumer yields (FIFO through the
        # loader), so "piece set completed after batch c" events let a
        # snapshot be computed relative to what the TRAINER has seen, not
        # what this source has produced into the loader's prefetch queue.
        self._production_count = 0
        self._events = []        # (production_count, epoch, [pieces])
        # Per-batch provenance in production order: (production_count,
        # epoch, piece, ordinal) for every TAGGED batch yielded — what a
        # state_dict() computes mid-piece watermarks from, at any consumer
        # position (untagged legacy batches record nothing and fall back
        # to per-piece-set completion granularity).
        self._batch_events = []
        self._epoch_starts = [(0, self._epoch, set(self._completed),
                               dict(self._resume_watermarks))]

    def _recovery_inc(self, event, n=1):
        """Bump a client recovery counter in BOTH surfaces at once: the
        legacy ``diagnostics["recovery"]`` dict and the registry family
        (``petastorm_service_client_recovery_events_total``). Callers must
        hold ``_lock``."""
        self._recovery[event] += n
        CLIENT_RECOVERY_EVENTS.labels(event).inc(n)

    # -- poison-piece quarantine -------------------------------------------

    def _note_quarantined(self, piece, worker_id, error, epoch):
        """Record one quarantined piece (worker sent ``piece_failed``
        under policy ``"quarantine"``) and report it to the dispatcher on
        a helper thread — journaled there, excluded from every future
        grant. The report is best-effort with the shared retry policy: if
        the dispatcher is unreachable the piece is simply re-granted (and
        re-quarantined) next epoch, which converges."""
        piece = int(piece)
        with self._lock:
            if any(entry["piece"] == piece and entry["epoch"] == epoch
                   for entry in self._quarantined):
                return  # duplicate frame (re-serve raced the quarantine)
            self._quarantined.append({"piece": piece,
                                      "worker_id": worker_id,
                                      "error": str(error),
                                      "epoch": int(epoch)})
            self._recovery_inc("pieces_quarantined")
        QUARANTINE_REPORTS.labels("client").inc()
        self._log.warning(
            "piece %d quarantined by worker (%s) — continuing without it",
            piece, error, worker_id=worker_id)

        def report():
            try:
                self._dispatcher_request({
                    "type": "report_poison_piece",
                    "client_id": self.client_id, "piece": piece,
                    "worker_id": worker_id, "error": str(error),
                    "epoch": int(epoch)}, retries=1)
            except (ServiceError, OSError):
                self._log.warning(
                    "poison-piece report for piece %d did not reach the "
                    "dispatcher — it will be re-reported when the piece "
                    "is re-granted", piece)

        threading.Thread(target=report, daemon=True,
                         name=f"service-quarantine-{self.client_id}").start()

    # -- circuit breakers + retry budgets (service/resilience.py) ----------

    def _breaker(self, worker_id):
        """This worker's circuit breaker (created on first touch)."""
        with self._lock:
            breaker = self._breakers.get(worker_id)
            if breaker is None:
                breaker = self._breakers[worker_id] = CircuitBreaker(
                    threshold=self._breaker_threshold,
                    cooldown_s=self._breaker_cooldown_s)
            return breaker

    def _budget(self, worker_id):
        """This worker's retry token budget (created on first touch)."""
        with self._lock:
            budget = self._budgets.get(worker_id)
            if budget is None:
                budget = self._budgets[worker_id] = RetryBudget(
                    capacity=self._retry_budget_capacity)
            return budget

    def _note_stream_success(self, worker_id):
        """A stream delivered (batch or clean end): close/reset the
        peer's breaker, refill its retry budget, mirror the gauges."""
        breaker = self._breaker(worker_id)
        breaker.record_success()
        budget = self._budget(worker_id)
        budget.record_success()
        with self._lock:
            self._breakers_reported.discard(worker_id)
        RESILIENCE_BREAKER_STATE.labels(worker_id).set(breaker.state_code)
        RESILIENCE_RETRY_BUDGET.labels(worker_id).set(budget.balance)

    def _note_stream_failure(self, worker_id):
        """One stream failure against a peer: feed its breaker; on the
        trip edge, report the exclusion to the dispatcher (journaled
        there — new grants route around the worker until its heartbeat
        probe closes it). Returns the breaker so callers can consult
        ``allow``."""
        breaker = self._breaker(worker_id)
        tripped = breaker.record_failure(time.monotonic())
        RESILIENCE_BREAKER_STATE.labels(worker_id).set(breaker.state_code)
        if tripped:
            self._note_breaker_open(worker_id)
        return breaker

    def _note_breaker_open(self, worker_id):
        """Report a tripped breaker to the dispatcher on a helper thread
        (the quarantine-report pattern): best-effort — if the dispatcher
        is unreachable the exclusion is only local, which still fails
        fast, and the next trip re-reports."""
        with self._lock:
            if worker_id in self._breakers_reported:
                return
            self._breakers_reported.add(worker_id)
        self._log.warning(
            "circuit breaker tripped OPEN for worker %s (%d consecutive "
            "stream failures) — failing fast and reporting for routing "
            "exclusion", worker_id, self._breaker_threshold)

        def report():
            try:
                self._dispatcher_request({
                    "type": "report_breaker",
                    "client_id": self.client_id,
                    "worker_id": worker_id,
                    "error": f"{self._breaker_threshold} consecutive "
                             f"stream failures",
                    "epoch": int(self._epoch)}, retries=1)
            except (ServiceError, OSError):
                with self._lock:
                    self._breakers_reported.discard(worker_id)
                self._log.warning(
                    "breaker-open report for worker %s did not reach the "
                    "dispatcher — exclusion is client-local only",
                    worker_id)

        threading.Thread(target=report, daemon=True,
                         name=f"service-breaker-{self.client_id}").start()

    def _note_hedge(self, outcome):
        """One hedged re-serve outcome (``launched``/``won``/``lost``) —
        mirrored to telemetry, to the counters ``diagnostics()`` reports,
        and (when tracing is armed) to the fleet trace as an instant so a
        hedge race is visible against the batch spans it raced."""
        RESILIENCE_HEDGES.labels(outcome).inc()
        collector = tracing.COLLECTOR
        if collector.enabled:
            collector.instant(f"client.hedge_{outcome}",
                              time.perf_counter(),
                              args={"client_id": self.client_id})
        FLIGHT.note(f"client.hedge_{outcome}")
        with self._lock:
            self._hedge_counts[outcome] = (
                self._hedge_counts.get(outcome, 0) + 1)

    # -- dispatcher control channel ---------------------------------------

    def _dispatcher_request(self, header, retries=None):
        """One request/reply against the dispatcher under the shared retry
        policy (bounded attempts, backoff with jitter, total
        ``rpc_deadline_s`` budget); transient socket failures retry,
        protocol errors raise immediately. Replies carrying a
        ``fencing_epoch`` update the observed-epoch counter."""

        if self.job_id is not None and "job_id" not in header:
            # Every control request carries the job identity: the
            # dispatcher scopes fencing, assignment records, and recovery
            # attribution by it (multi-tenant fleets).
            header = dict(header, job_id=self.job_id)
        if self.corpus:
            # Multi-corpus fleets: assignment planning, takeover
            # re-partitions, and quarantine reports all scope to this
            # source's corpus worker group.
            header = dict(header, corpus=self.corpus)
        if "trace" not in header:
            # Propagated trace context: the dispatcher's RPC span records
            # who called (and which job), joining this client's data-plane
            # batch spans in the merged fleet trace.
            ctx = {"peer": self.client_id}
            if self.job_id is not None:
                ctx["job_id"] = self.job_id
            header = dict(header, trace=ctx)

        # One deadline for the whole request (attempts + backoff), from
        # the same budget the retry loop enforces — stamped per attempt
        # so a retry ships its SMALLER remaining budget, and the handler
        # refuses work this client has already stopped waiting for.
        deadline = (time.monotonic() + self._rpc_deadline_s
                    if self._rpc_deadline_s is not None else None)

        def once():
            attach_deadline(header, deadline)
            with FramedConnection.connect(
                    self._dispatcher_address,
                    timeout=self._connect_timeout,
                    max_frame_bytes=self._max_frame_bytes) as conn:
                reply, _ = conn.request(header)
            if reply.get("type") == "error":
                if reply.get("retryable"):
                    # A degraded (read-only) dispatcher heals itself via
                    # a recovery snapshot on a later request — transient,
                    # so it rides the OSError retry path instead of
                    # killing training like a protocol error would.
                    raise DegradedDispatcherError(
                        reply.get("error", "dispatcher degraded"))
                raise ServiceError(reply.get("error", "dispatcher error"))
            return reply

        reply = retry_with_backoff(
            once, retries=self._max_retries if retries is None else retries,
            base_delay=self._backoff_base,
            max_delay=self._backoff_max,
            # ProtocolError = a desynced control connection (torn frame):
            # the conn is dropped and a fresh dial retries cleanly.
            retry_on=(OSError, ProtocolError),
            no_retry_on=(ServiceError,), deadline_s=self._rpc_deadline_s,
            # Retry-budget bound: control-plane retries against a
            # degraded dispatcher spend tokens successes refill, so a
            # fleet of clients cannot multiply its load into a storm.
            budget=self._dispatcher_budget,
            description=f"dispatcher request {header.get('type')!r}")
        RESILIENCE_RETRY_BUDGET.labels("dispatcher").set(
            self._dispatcher_budget.balance)
        if "fencing_epoch" in reply:
            with self._lock:
                self._recovery["fencing_epoch"] = max(
                    self._recovery["fencing_epoch"],
                    int(reply["fencing_epoch"]))
        if "credit_scale" in reply:
            # The fair-share plan's flow-control scaling for this job —
            # applied to streams opened after this reply (a live stream's
            # window was negotiated on its request, like set_credits).
            self._credit_scale = float(reply["credit_scale"])
        if "brownout_level" in reply:
            # The dispatcher's journaled overload level: ≥ 2 sheds
            # optional stages (tracing spans) process-wide.
            note_brownout_level(reply["brownout_level"])
        return reply

    # -- runtime knobs (live-adjustable: the autotuner's bindings) ---------

    @property
    def credits(self):
        """The per-worker flow-control window in force."""
        return self._credits

    def set_credits(self, credits):
        """Adjust the credit window. Applies to streams opened AFTER the
        call (epoch starts, takeover/resync relaunches) — a live stream's
        window was negotiated on its request and keeps its size. When
        ``ready_queue_depth`` was left derived (``None``), the live
        ready-queue's bound is re-derived from the new window too."""
        if credits is not None and credits < 1:
            raise ValueError("credits must be a positive integer or None")
        with self._lock:
            self._credits = credits
            ready = self._ready_queue
            streams = self._live_stream_count
        if self._ready_queue_depth is None and ready is not None:
            resize_bounded_queue(ready, self._derived_ready_depth(streams))

    @property
    def ready_queue_depth(self):
        """The configured ready-queue bound (``None`` = derived)."""
        if self._ready_queue_depth is not None:
            return self._ready_queue_depth
        with self._lock:
            ready = self._ready_queue
        return (ready.maxsize if ready is not None
                else self._derived_ready_depth(1))

    def set_ready_queue_depth(self, depth):
        """Pin (and live-resize) the shared ready-queue bound: a raise
        wakes reader threads blocked on the old bound immediately; a
        shrink lets the queue drain down to the new bound."""
        if depth is not None and depth < 1:
            raise ValueError(
                "ready_queue_depth must be a positive integer or None")
        with self._lock:
            self._ready_queue_depth = depth
            ready = self._ready_queue
            streams = self._live_stream_count
        if ready is not None:
            resize_bounded_queue(ready, depth if depth is not None
                          else self._derived_ready_depth(streams))

    @property
    def transform_placement(self):
        """Where the batch transform will run from the NEXT iteration on."""
        return self._transform_placement

    def set_transform_placement(self, placement):
        """Flip the batch-transform stage between the workers ("remote")
        and this trainer host ("local"). Takes effect at the next
        iteration/epoch boundary — the placement each iteration runs
        under is frozen when it starts, so every one of its streams (and
        the client-side applier) agree."""
        if placement not in ("remote", "local"):
            raise ValueError(
                "transform_placement must be 'remote' or 'local'")
        if self.transform is None:
            raise ValueError(
                "no transform callable armed — construct the source with "
                "transform= to make placement meaningful")
        self._transform_placement = placement

    @property
    def packing(self):
        """The worker-placement packing spec in force from the next
        iteration on (``None`` = workers serve row batches)."""
        return self._packing

    def set_packing(self, packing):
        """Arm (or disarm, ``None``) worker-placement sequence packing.
        Takes effect at the next iteration boundary, like
        :meth:`set_transform_placement` — the placement wrapper
        (:class:`~petastorm_tpu.service.packing_stage.PackedBatchSource`)
        calls this when its ``packing_placement`` knob flips."""
        if packing is None:
            self._packing = None
            return
        from petastorm_tpu.service.packing_stage import PackingSpec

        if self.transform is not None:
            raise ValueError(
                "packing and transform= cannot combine on one source "
                "(the transform is a row-batch stage)")
        self._packing = PackingSpec.from_dict(packing)

    def _iter_packing_dict(self):
        """The frozen iteration's packing spec in wire form (``None``
        when the iteration serves row batches)."""
        return (self._iter_packing.to_dict()
                if self._iter_packing is not None else None)

    # -- graph-rewrite knobs (docs/guides/pipeline.md#graph-rewrites) ------

    @property
    def filter_placement(self):
        """Where the declared row filter runs from the NEXT iteration on:
        ``"client"`` (baseline — batches arrive unfiltered, masked here)
        or ``"worker"`` (hoisted below the workers' decode)."""
        return self._filter_placement

    def set_filter_placement(self, placement):
        """Flip the row filter between trainer-side masking and the
        hoisted worker-side two-phase read. Next-iteration, like every
        placement flip — an iteration's streams and its local applier
        must agree on one topology."""
        if placement not in ("client", "worker"):
            raise ValueError(
                "filter_placement must be 'client' or 'worker'")
        if self._predicate is None:
            raise ValueError(
                "no predicate armed — construct the source with "
                "predicate= to make filter placement meaningful")
        if placement == "client" and self.transform is not None:
            raise ValueError(
                "filter_placement='client' is unavailable with a "
                "transform= armed: the workers transform before shipping, "
                "so the client filter would evaluate post-transform "
                "values — the filter stays hoisted (worker-placed)")
        if placement == "worker":
            self._reject_rewrite_on_fcfs("filter_placement='worker'")
        self._filter_placement = placement

    @property
    def stage_fusion(self):
        """``"off"`` or ``"fused"`` from the next iteration on."""
        return self._stage_fusion

    def set_stage_fusion(self, mode):
        """Arm/disarm worker-side stage fusion (collate→transform(→pack)→
        serialize collapsed into the decode pool task). Next-iteration;
        byte-identical output either way — fusion only moves where the
        work runs."""
        if mode not in ("off", "fused"):
            raise ValueError("stage_fusion must be 'off' or 'fused'")
        if mode == "fused":
            self._reject_rewrite_on_fcfs("stage_fusion='fused'")
        self._stage_fusion = mode

    @property
    def cache_placement(self):
        """The worker cache's insertion point from the next iteration on:
        ``"post-transform"`` (entries hold post-transform bytes) or
        ``"post-decode"`` (pre-transform bytes; warm serves re-apply the
        transform)."""
        return self._cache_placement

    def set_cache_placement(self, placement):
        """Move the worker-side batch cache above or below the batch
        transform. Next-iteration; the two placements' cache keys differ,
        so a flip RE-FILLS rather than serving the other placement's
        bytes."""
        if placement not in ("post-transform", "post-decode"):
            raise ValueError(
                "cache_placement must be 'post-transform' or "
                "'post-decode'")
        if placement == "post-decode" and self.transform is None:
            raise ValueError(
                "cache_placement='post-decode' needs a transform= armed")
        if placement == "post-decode":
            self._reject_rewrite_on_fcfs("cache_placement='post-decode'")
        self._cache_placement = placement

    @property
    def reader_family(self):
        """The reader family workers serve this source through from the
        next iteration on (``None`` = each worker's constructed
        factory; ``"row"`` / ``"columnar"``)."""
        return self._reader_family

    def set_reader_family(self, family):
        """Flip the workers' serving family between per-row codec decode
        and vectorized columnar kernels (the ``row_vs_columnar``
        rewrite). Next-iteration; decoded bytes are identical — a worker
        that cannot serve a stream columnar (exotic codecs, ngram
        windows, batch-family datasets) falls back to the row path for
        that stream, still byte-identical. The two families key cache
        entries apart, so a flip re-fills rather than cross-serving."""
        if family not in (None, "row", "columnar"):
            raise ValueError(
                "reader_family must be None, 'row', or 'columnar', got "
                f"{family!r}")
        if family == "columnar":
            self._reject_rewrite_on_fcfs("reader_family='columnar'")
        self._reader_family = family

    def _reject_rewrite_on_fcfs(self, what):
        """Rewrite setters refuse on a known-fcfs source: the flip would
        not probe, it would crash the NEXT iteration's __call__ — a
        failure mode the planner's revert machinery cannot see. (The
        graph also declines to bind rewrite knobs on fcfs sources; this
        is the direct-setter guard.)"""
        if self._mode == "fcfs":
            raise ValueError(
                f"{what} requires static or dynamic sharding: this "
                f"source's dispatcher runs fcfs, whose untagged per-split "
                f"streams bypass the streaming piece engine rewrites run "
                f"in (docs/guides/pipeline.md#graph-rewrites)")

    def _iter_rewrite_kwargs(self):
        """The frozen iteration's rewrite attributes as stream kwargs —
        shared by every tagged/dynamic stream construction site (initial
        launch, retry, takeover, resync relaunch), so a re-serve can
        never disagree with the topology the iteration froze."""
        hoisted = getattr(self, "_iter_hoisted", False)
        return {
            "predicate": (self._iter_predicate.to_wire()
                          if hoisted and self._iter_predicate is not None
                          else None),
            "projection": self._iter_projection if hoisted else None,
            "fused": self._iter_fused,
            "cache_stage": self._iter_cache_stage,
            "reader_family": self._iter_reader_family,
            # Not a rewrite, but frozen the same way: every stream of an
            # iteration negotiates under the same transport policy.
            "transport": self._transport,
        }

    def _apply_filter_local(self, inner):
        """Trainer-side execution of the declared row filter + projection
        (the UNREWRITTEN topology): every received batch is masked with
        the predicate's columnar form and pruned to the projection.
        Row-stream content and order are identical to the hoisted run;
        batch boundaries are not (hoisted streams collate survivors into
        full batches below decode) — which is exactly the overhead the
        hoist removes: every dropped row here was decoded, serialized,
        and shipped first. Fully-emptied batches are skipped (and
        counted: they break prefetch-exact checkpoint positioning — see
        ``state_dict``)."""
        import numpy as np

        from petastorm_tpu.predicates import evaluate_predicate_mask

        predicate = self._iter_predicate
        projection = self._iter_projection
        m_in = CLIENT_FILTER_ROWS.labels("in")
        m_kept = CLIENT_FILTER_ROWS.labels("kept")
        try:
            for batch in inner:
                if predicate is not None and batch:
                    num_rows = len(next(iter(batch.values())))
                    mask = evaluate_predicate_mask(predicate, batch,
                                                   num_rows)
                    kept = int(np.count_nonzero(mask))
                    m_in.inc(num_rows)
                    m_kept.inc(kept)
                    if kept == 0:
                        with self._lock:
                            self._filter_dropped_batches += 1
                        continue
                    if kept < num_rows:
                        batch = {name: column[mask]
                                 for name, column in batch.items()}
                if projection is not None:
                    batch = {name: column for name, column in batch.items()
                             if name in projection}
                yield batch
        finally:
            close = getattr(inner, "close", None)
            if callable(close):
                close()

    def _effective_credits(self):
        """The configured credit window scaled by this job's fair share
        (``credit_scale`` from the dispatcher): a job granted half the
        capacity opens streams with half the window, which is how the
        fair-scheduling plan actually bounds a tenant's in-flight claim
        on each worker. Floor 1 (a stream must be able to move); 1.0 —
        the single-tenant / equal-weight / largest-share case — is the
        identity."""
        credits = self._credits
        if credits is None or self._credit_scale >= 1.0:
            return credits
        return max(1, int(round(credits * self._credit_scale)))

    def _derived_ready_depth(self, streams):
        """The default ready-queue bound when none was pinned: wide
        enough for every credit the flow-control windows can have in
        flight (capped — a huge fleet should pin explicitly), falling
        back to the legacy 2-per-stream sizing when credits are off."""
        streams = max(1, int(streams))
        if self._credits is not None:
            return max(4, min(streams * self._credits, 256))
        return max(4, 2 * streams)

    def _apply_transform_local(self, inner):
        """Trainer-local execution of the batch-transform stage: applied
        to each batch as it leaves the drain, timed into
        ``petastorm_service_client_transform_seconds``."""
        transform = self.transform
        try:
            for batch in inner:
                t0 = time.perf_counter()
                batch = transform(batch)
                CLIENT_TRANSFORM_SECONDS.observe(time.perf_counter() - t0)
                yield batch
        finally:
            close = getattr(inner, "close", None)
            if callable(close):
                close()

    # -- the batch_source contract ----------------------------------------

    def __call__(self):
        info = self._dispatcher_request({"type": "list_workers"})
        with self._lock:
            self._mode = info["mode"]
            self._shuffle_seed = info.get("shuffle_seed")
            # Fresh iteration: the consumer's batch counter restarts, so
            # production bookkeeping (and delivery diagnostics) restart
            # with it.
            self._production_count = 0
            self._events = []
            self._batch_events = []
            self._epoch_starts = [(0, self._epoch, set(self._completed),
                               dict(self._resume_watermarks))]
            self._per_worker = {}
        if self._resumed and getattr(self, "_resume_has_seed", False) \
                and self._resume_seed != self._shuffle_seed:
            self._log.warning(
                "resume_state was saved under shuffle_seed=%r but the "
                "dispatcher runs %r — delivery stays exactly-once, but "
                "the resumed stream's ORDER will not be bit-identical to "
                "the original run's", self._resume_seed,
                self._shuffle_seed)
        if self._ordered and info["mode"] == "fcfs":
            raise ValueError(
                "ordered delivery requires static or dynamic sharding: "
                "fcfs hands splits out first-come-first-served, so no "
                "canonical piece order exists to sequence against")
        if self.job_id is not None and info["mode"] == "fcfs":
            raise ValueError(
                "job_id requires static or dynamic sharding: fcfs hands "
                "splits out of ONE shared queue with no per-job "
                "assignment, so concurrent jobs would silently split — "
                "not share — each epoch's data. Run the dispatcher with "
                "mode='dynamic' (or 'static') for multi-tenant fleets")
        if self._packing is not None and info["mode"] == "fcfs":
            raise ValueError(
                "packing requires static or dynamic sharding: fcfs "
                "serves untagged per-split streams outside the streaming "
                "engine, which is where worker-side packing runs — or "
                "pack trainer-side (PackedBatchSource placement="
                "'trainer')")
        if self.corpus and info["mode"] == "fcfs":
            raise ValueError(
                "corpus= requires static or dynamic sharding: fcfs "
                "splits one shared default-corpus queue (multi-corpus "
                "mixes need per-corpus assignments)")
        # Freeze the transform placement for this whole iteration: every
        # stream it opens (takeover/resync relaunches included) carries
        # the same placement, and the local applier wraps the iterator
        # exactly when the workers were told to skip the stage.
        self._iter_transform_placement = (self._transform_placement
                                          if self.transform is not None
                                          else None)
        # Packing is frozen the same way: an iteration's streams (and
        # their cache keys) all agree on whether the workers pack.
        self._iter_packing = self._packing
        # Graph-rewrite attributes freeze the same way (the planner's
        # flips are next-iteration by construction): one topology per
        # iteration, on every stream and on the local filter applier.
        hoisted = (self._predicate is not None
                   and self._filter_placement == "worker")
        self._iter_filter_placement = (self._filter_placement
                                       if self._predicate is not None
                                       else None)
        self._iter_hoisted = hoisted
        self._iter_predicate = self._predicate
        self._iter_projection = self._projection
        self._iter_fused = self._stage_fusion == "fused"
        self._iter_cache_stage = (self._cache_placement
                                  if self._cache_placement != "post-transform"
                                  else None)
        self._iter_reader_family = self._reader_family
        self._filter_dropped_batches = 0
        rewriting = (hoisted or self._iter_fused
                     or self._iter_cache_stage is not None
                     or self._iter_reader_family is not None)
        if rewriting and info["mode"] == "fcfs":
            raise ValueError(
                "graph rewrites (filter_placement='worker', stage_fusion, "
                "cache_placement='post-decode', reader_family) require "
                "static or dynamic sharding: fcfs serves untagged "
                "per-split streams outside the streaming piece engine, "
                "which is where rewrites run "
                "(docs/guides/pipeline.md#graph-rewrites)")
        local = self._iter_transform_placement == "local"
        client_filtered = (self._predicate is not None and not hoisted)

        def wrap(it):
            # Stage order matches the worker side: filter sits BELOW the
            # batch transform (the worker applies the predicate under
            # decode, the transform after collation).
            if client_filtered or (self._projection is not None
                                   and not hoisted):
                it = self._apply_filter_local(it)
            if local:
                it = self._apply_transform_local(it)
            return it

        if info["mode"] == "static":
            # The multiplexed drain prefetches into its ready-queue behind
            # reader threads — consumers may pull it directly.
            return _SourceIterator(wrap(self._iter_static(info)),
                                   prefetched=True)
        if info["mode"] == "dynamic":
            return _SourceIterator(wrap(self._iter_dynamic(info)),
                                   prefetched=True)
        if self._resumed:
            raise ValueError(
                "resume_state was supplied but the dispatcher is in fcfs "
                "mode: fcfs has no per-client resumable position, so the "
                "snapshot's completed pieces cannot be skipped — silently "
                "re-streaming everything would duplicate trained data. "
                "Run the dispatcher in static or dynamic mode to resume")
        # fcfs consumes streams sequentially (no reader threads): a
        # prefetching consumer should keep its own producer thread.
        # (Rewrites were rejected above; a CLIENT-placed filter is pure
        # trainer-side post-processing and works on any mode.)
        return _SourceIterator(wrap(self._iter_fcfs(info)),
                               prefetched=False)

    # -- static mode -------------------------------------------------------

    def _iter_static(self, info):
        num_epochs = info["num_epochs"]
        epoch = self._epoch
        heartbeat_stop = threading.Event()
        heartbeat = None
        if self._heartbeat_interval_s is not None:
            heartbeat = threading.Thread(
                target=self._heartbeat_loop, args=(heartbeat_stop,),
                daemon=True, name=f"service-heartbeat-{self.client_id}")
            heartbeat.start()
        try:
            yield from self._iter_static_epochs(num_epochs, epoch)
        finally:
            heartbeat_stop.set()
            if heartbeat is not None:
                heartbeat.join(timeout=5)

    def _request_assignment(self, epoch):
        """The raw get_assignment request/reply — no fencing side effects
        (callers that only need a piece→worker mapping for a SUBSET of the
        shard, like the stale-fencing takeover path, must NOT mark the
        whole drain synced: other streams moved by the same bump still
        need the heartbeat-triggered resync to reconcile them)."""
        return self._dispatcher_request({
            "type": "get_assignment", "client_id": self.client_id,
            "client_index": self.client_index,
            "num_clients": self.num_clients, "epoch": epoch})

    def _fetch_assignment(self, epoch):
        """Fetch this client's assignment for ``epoch`` and sync the
        fencing bookkeeping to it: the assignment is the freshest plan
        there is, so whatever fencing epoch it was computed at is what the
        drain is synced to (and any pending fence event is satisfied) —
        valid only for callers that APPLY the full assignment (epoch
        start, resync)."""
        reply = self._request_assignment(epoch)
        with self._lock:
            self._synced_fencing_epoch = int(reply.get("fencing_epoch", 0))
            self._fence_pending = False
        return reply

    def _iter_static_epochs(self, num_epochs, epoch):
        first = True
        while num_epochs is None or epoch < num_epochs:
            reply = self._fetch_assignment(epoch)
            if not reply["assignments"] and num_epochs is None:
                # This client's static shard has no pieces at all (more
                # clients than row groups). With infinite epochs the loop
                # would otherwise spin get_assignment requests forever with
                # nothing to yield — end the stream instead; the shard can
                # never become non-empty (num_pieces is fixed).
                self._log.warning(
                    "empty static shard and num_epochs is None — ending "
                    "the stream (prefer num_clients <= row-group count)",
                    client_index=self.client_index,
                    num_clients=self.num_clients)
                return
            with self._lock:
                skip = set(self._completed)
                # A resumed first epoch starts mid-piece at the snapshot's
                # watermarks; later epochs start clean.
                self._recv_watermarks = (
                    dict(self._resume_watermarks) if first else {})
                starts = dict(self._recv_watermarks)
            first = False
            streams = {}
            pending_all = []
            for wid, pieces in reply["assignments"].items():
                pending = [p for p in pieces if p not in skip]
                if pending:
                    pending_all.extend(pending)
                    streams[len(streams)] = _WorkerStream(
                        wid, reply["workers"][wid], pending, epoch,
                        self._connect_timeout,
                        credits=self._effective_credits(), tagged=True,
                        starts={p: starts.get(p, 0) for p in pending},
                        shuffle_seed=self._shuffle_seed,
                        transform_placement=self._iter_transform_placement,
                        job_id=self.job_id,
                        recv_timeout=self._stream_recv_timeout_s,
                        packing=self._iter_packing_dict(),
                        **self._iter_rewrite_kwargs())
            sequencer = (_OrderedSequencer(
                piece_order(self._shuffle_seed, epoch, pending_all))
                if self._ordered else None)
            yield from self._drain_streams(streams, epoch, sequencer,
                                           workers=reply["workers"])
            epoch += 1
            with self._lock:
                self._roll_epoch_locked(epoch)

    def _drain_streams(self, streams, epoch, sequencer=None, workers=None):
        """Multiplexed drain: one reader thread per worker stream, all
        feeding a single bounded ready-queue this generator yields from —
        whichever worker is ready is consumed, so a stalled worker never
        head-of-line blocks batches already buffered on its peers (the
        round-robin ``next_batch`` loop this replaces blocked on one slow
        stream while the others' batches sat in socket buffers).

        Delivery is **exactly-once** on the tagged protocol: every batch
        carries ``(piece, ordinal)``, the consumer tracks a per-piece
        receive watermark, every re-serve path (same-worker retry,
        takeover, resync relaunch) re-grants pieces AT their watermarks so
        the worker's engine skips already-delivered batches at the source,
        and a sub-watermark arrival that slips through anyway is dropped
        here (counted as ``duplicates_dropped`` — the safety net, 0 in a
        healthy run). Untagged streams (a worker whose pool cannot
        attribute per-piece completion) keep the legacy at-least-once
        re-serve.

        Semantics preserved from the blocking drain:

        - a broken stream is retried against the same worker, then reported
          and re-assigned — recovery runs on a helper thread, so a dead
          worker's connect timeouts and backoff never block this consumer
          from yielding the survivors' batches (recovery completing posts
          a ``recovered`` event and the new streams' readers launch here);
        - production-count accounting happens HERE, on the consumer side of
          the queue: events flow per-stream FIFO, so a stream's ``end`` is
          dequeued only after all its batches were yielded and completion
          events carry the same production counts as before;
        - credits replenish on dequeue, so the per-worker window bounds
          worker-sent-but-unconsumed batches end to end (socket buffer +
          ready-queue share);
        - a ``fence`` event (the heartbeat loop saw the dispatcher's
          fencing epoch move past this drain's) resyncs the assignment:
          streams whose piece→worker mapping is unchanged keep flowing
          untouched (a journal-backed dispatcher restart is a no-op — zero
          duplicates); only streams whose mapping changed are retired and
          their pending pieces relaunched per the fresh plan, at their
          watermarks.

        ``sequencer`` (ordered mode) re-sequences yields into the
        canonical seed-tree order: received batches are pushed through it
        and only what it releases is yielded — checkpoint bookkeeping
        happens at RELEASE, so ``state_dict`` snapshots stay consistent
        with what the consumer actually saw.
        """
        if not streams:
            return
        depth = (self._ready_queue_depth
                 if self._ready_queue_depth is not None
                 else self._derived_ready_depth(len(streams)))
        ready = queue.Queue(maxsize=depth)
        stop = threading.Event()
        readers = []
        retired = set()   # sids closed by a resync: terminal events ignored
        sid_counter = itertools.count(max(streams) + 1)
        # Hedged watermark re-serves (tail-latency, not fault, recovery —
        # docs/guides/service.md#failure-model-and-recovery): when a
        # stream goes silent for longer than the gap tracker's fitted
        # threshold (a high quantile of this run's OWN inter-batch gaps,
        # not a magic constant), its in-flight piece is re-granted AT its
        # watermark from a peer worker. First ``piece_done`` wins, the
        # losing hedge is cancelled, and any duplicate the race slips
        # through is dropped by the exactly-once watermark dedup below —
        # hedging changes WHEN batches arrive, never WHAT is delivered.
        hedge_armed = bool(self._hedging)
        hedge_sids = set()       # sids that ARE hedge streams
        hedges = {}              # hedged piece -> {"primary", "hedge"} sids
        hedge_won = set()        # pieces a hedge won: late markers dedup
        last_seen = {}           # sid -> monotonic time of last batch
        untagged_sids = set()    # legacy streams: no watermarks, no hedging
        hedge_tick = 0.05
        with self._lock:
            self._ready_queue = ready
            self._live_stream_count = len(streams)

        def launch(sid, stream):
            streams[sid] = stream
            last_seen[sid] = time.monotonic()
            with self._lock:
                # Keep the live count honest across resync relaunches:
                # set_credits re-derives the queue bound from it.
                self._live_stream_count = len(streams)
            reader = _StreamReader(sid, stream, ready, stop,
                                   self._note_stream_recv)
            readers.append(reader)
            reader.start()

        def post(event):
            while not stop.is_set():
                try:
                    ready.put(event, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def recover(broken):
            # Retry-then-takeover off the consumer thread: a dead worker's
            # connect timeouts and backoff (tens of seconds) must not stop
            # the consumer from yielding survivors' buffered batches — the
            # head-of-line failure mode this drain exists to remove.
            try:
                replacement = self._retry_stream(broken)
                fresh = ([replacement] if replacement is not None
                         else self._reassign(broken))
            except BaseException as exc:
                post(("error", None, exc))
                return
            if not post(("recovered", None, fresh)):
                for stream in fresh:  # drain torn down mid-recovery
                    stream.close()

        book = _DeliveryBook(self, epoch)

        def resync(active):
            """Re-fetch the assignment under the current fencing epoch and
            reconcile the live streams against it (consumer thread). A
            control-plane failure here (dispatcher mid-restart with no
            workers re-registered yet, dispatcher unreachable) must NOT
            surface into the training loop: the live streams are still
            valid until proven otherwise, so leave them flowing and let
            the next heartbeat re-trigger the resync."""
            try:
                reply = self._fetch_assignment(epoch)
            except (ServiceError, OSError) as exc:
                self._log.warning(
                    "resync under fencing epoch change failed (%s) — "
                    "keeping current streams; the next heartbeat retries",
                    exc)
                with self._lock:
                    self._recovery_inc("resync_failures")
                    self._fence_pending = False
                return
            with self._lock:
                completed = set(self._completed)
                self._recovery_inc("resyncs")
            desired = {}  # pending piece -> (worker_id, address)
            for wid, pieces in reply["assignments"].items():
                for piece in pieces:
                    if piece not in completed:
                        desired[piece] = (wid,
                                          tuple(reply["workers"][wid]))
            for sid in list(active):
                stream = streams[sid]
                # Judge the stream by its PENDING pieces only: piece-level
                # completion (tagged protocol) means some of stream.pieces
                # may already be done — absent from `desired` by design,
                # which must not read as "mapping moved".
                pending = [p for p in stream.pieces if p not in completed]
                if all(desired.get(p, (None,))[0] == stream.worker_id
                       for p in pending):
                    # Mapping unchanged: the stream keeps flowing — its
                    # pieces are accounted for.
                    for piece in pending:
                        desired.pop(piece, None)
                else:
                    # Mapping moved (its worker was evicted/re-planned):
                    # retire the stream; its pending pieces relaunch
                    # below, AT their watermarks (exactly-once).
                    streams.pop(sid)
                    active.discard(sid)
                    retired.add(sid)
                    stream.close()
                    if sid in hedge_sids:
                        # A hedge retired mid-race lost it; clear its pair
                        # so the piece may hedge again after relaunch.
                        hedge_sids.discard(sid)
                        for hp, pair in list(hedges.items()):
                            if pair["hedge"] == sid:
                                hedges.pop(hp)
                        self._note_hedge("lost")
                    else:
                        # A retired PRIMARY orphans its hedge pairs — the
                        # relaunched stream is a fresh race.
                        for hp, pair in list(hedges.items()):
                            if pair["primary"] == sid:
                                hedges.pop(hp)
                    with self._lock:
                        self._recovery_inc("streams_retired")
                    self._log.warning(
                        "resync: retiring stream (pieces %s moved)",
                        pending, worker_id=stream.worker_id,
                        fencing_epoch=reply.get("fencing_epoch"))
            regroup = {}
            for piece, (wid, address) in sorted(desired.items()):
                regroup.setdefault((wid, address), []).append(piece)
            with self._lock:
                marks = dict(self._recv_watermarks)
            for (wid, address), pieces in regroup.items():
                new_sid = next(sid_counter)
                active.add(new_sid)
                # Relaunch in CANONICAL order, not numeric: serving a
                # relaunched stream's pieces in seed-tree relative order
                # keeps ordered mode's reorder buffer small (a
                # canonically-late piece served first just sits buffered
                # until its turn).
                launch(new_sid, _WorkerStream(
                    wid, address,
                    piece_order(self._shuffle_seed, epoch, pieces),
                    epoch, self._connect_timeout,
                    credits=self._effective_credits(), tagged=True,
                    starts={p: marks.get(p, 0) for p in pieces},
                    shuffle_seed=self._shuffle_seed,
                    transform_placement=self._iter_transform_placement,
                    job_id=self.job_id,
                    recv_timeout=self._stream_recv_timeout_s,
                    packing=self._iter_packing_dict(),
                    **self._iter_rewrite_kwargs()))

        def drop_hedge(hsid, outcome, closed=False):
            """Cancel a live hedge stream and clear its pair so the piece
            may hedge again later. ``closed=True`` means the reader already
            posted its terminal event (broken hedge) — nothing left to
            ignore; otherwise the close provokes one, which ``retired``
            swallows."""
            hedge_sids.discard(hsid)
            stream = streams.pop(hsid, None)
            active.discard(hsid)
            if not closed:
                retired.add(hsid)
            if stream is not None:
                stream.close()
            for piece, pair in list(hedges.items()):
                if pair["hedge"] == hsid:
                    hedges.pop(piece)
            if outcome is not None:
                self._note_hedge(outcome)

        def settle_hedge(piece, pair, winner_sid):
            """First ``piece_done`` decides the race. A winning hedge just
            keeps flowing to its own ``end`` (the slow primary's late
            batches are sub-watermark and dedup away, its late marker hits
            the completion guard); a losing hedge is cancelled."""
            hedges.pop(piece, None)
            hsid = pair["hedge"]
            if winner_sid == hsid:
                self._note_hedge("won")
                hedge_won.add(piece)
                hstream = streams.get(hsid)
                if hstream is not None:
                    self._note_stream_success(hstream.worker_id)
            else:
                drop_hedge(hsid, "lost")

        def pick_peer(primary_wid):
            """A ``(worker_id, address)`` on a DIFFERENT worker whose
            breaker admits traffic — a half-open breaker's single probe
            slot may be spent on the hedge (its win/loss feeds back via
            the stream-success/failure notes). Prefers the
            most-recently-active live stream's worker (demonstrably
            fast); falls back to the assignment's worker map, because in
            the straggler ENDGAME the fast workers' streams have already
            ended — exactly when a hedge pays most."""
            best, best_seen = None, -1.0
            now = time.monotonic()
            for osid in active:
                if osid in hedge_sids:
                    continue
                other = streams.get(osid)
                if other is None or other.worker_id == primary_wid:
                    continue
                if not self._breaker(other.worker_id).allow(now):
                    continue
                seen = last_seen.get(osid, 0.0)
                if seen > best_seen:
                    best = (other.worker_id, other.address)
                    best_seen = seen
            if best is not None:
                return best
            for wid, address in sorted((workers or {}).items()):
                if wid == primary_wid:
                    continue
                if not self._breaker(wid).allow(now):
                    continue
                return (wid, tuple(address))
            return None

        def maybe_hedge():
            """Scan active primaries for silence past the fitted gap
            threshold and hedge the first pending piece of each offender
            (one live hedge per piece)."""
            threshold = self._gap_tracker.threshold_s()
            if threshold is None:
                return   # not enough gap samples yet to call anything slow
            now = time.monotonic()
            with self._lock:
                completed = set(self._completed)
                marks = dict(self._recv_watermarks)
            for sid in list(active):
                if sid in hedge_sids or sid in untagged_sids:
                    continue
                stream = streams.get(sid)
                if stream is None:
                    continue
                silent_s = now - last_seen.get(sid, now)
                if silent_s <= threshold:
                    continue
                pending = [p for p in stream.pieces if p not in completed]
                if not pending or pending[0] in hedges:
                    continue
                peer = pick_peer(stream.worker_id)
                if peer is None:
                    continue   # single-worker fleet (or all peers open)
                peer_wid, peer_addr = peer
                piece = pending[0]
                fp = failpoints.ACTIVE
                if fp is not None:
                    fp.fire("hedge-race")
                hsid = next(sid_counter)
                hedge_sids.add(hsid)
                hedges[piece] = {"primary": sid, "hedge": hsid}
                active.add(hsid)
                self._note_hedge("launched")
                self._log.warning(
                    "stream silent %.2fs (threshold %.2fs) — hedging "
                    "piece %d at watermark %d on peer %s", silent_s,
                    threshold, piece, marks.get(piece, 0), peer_wid,
                    worker_id=stream.worker_id)
                launch(hsid, _WorkerStream(
                    peer_wid, peer_addr, [piece], epoch,
                    self._connect_timeout,
                    credits=self._effective_credits(), tagged=True,
                    starts={piece: marks.get(piece, 0)},
                    shuffle_seed=self._shuffle_seed,
                    transform_placement=self._iter_transform_placement,
                    job_id=self.job_id,
                    recv_timeout=self._stream_recv_timeout_s,
                    packing=self._iter_packing_dict(),
                    **self._iter_rewrite_kwargs()))
                # The hedge resets this primary's silence clock: give the
                # race a full window before hedging its NEXT piece.
                last_seen[sid] = now

        try:
            for sid, stream in list(streams.items()):
                launch(sid, stream)
            active = set(streams)
            recovering = 0
            fence_deferred = False
            last_hedge_check = time.monotonic()
            while active or recovering:
                if hedge_armed:
                    # Timed get: silence anywhere must surface even while
                    # OTHER streams keep the queue busy (and especially
                    # when it is empty because everything stalled).
                    try:
                        kind, sid, item = ready.get(timeout=hedge_tick)
                    except queue.Empty:
                        maybe_hedge()
                        last_hedge_check = time.monotonic()
                        continue
                    now = time.monotonic()
                    if now - last_hedge_check >= hedge_tick:
                        last_hedge_check = now
                        maybe_hedge()
                else:
                    kind, sid, item = ready.get()
                if sid is not None and sid in retired:
                    # A batch/terminal event from a stream a resync already
                    # retired: its pieces were relaunched elsewhere, so the
                    # event is stale. Terminal events also finish the
                    # bookkeeping for the retired sid.
                    if kind in ("end", "broken"):
                        retired.discard(sid)
                    continue
                if kind == "batch":
                    batch, piece, ordinal, bid, t_enqueued = item
                    stream = streams[sid]
                    if hedge_armed:
                        now = time.monotonic()
                        prev = last_seen.get(sid)
                        if prev is not None:
                            self._gap_tracker.observe(now - prev)
                        last_seen[sid] = now
                        if piece is None:
                            untagged_sids.add(sid)
                    # Ack BEFORE yielding: the worker refills its window
                    # while the trainer computes on this batch — also in
                    # ordered mode, where the batch may only be buffered:
                    # deferring the ack to sequencer release deadlocks,
                    # because the engine's decode lookahead (and warm
                    # cache staging) can legally fill a window with a
                    # canonically-later piece's batches while an earlier
                    # piece is still decoding on the same stream.
                    stream.add_credit(1)
                    if piece is not None and ordinal is not None:
                        with self._lock:
                            duplicate = (
                                ordinal < self._recv_watermarks.get(piece,
                                                                    0))
                            if duplicate:
                                # A re-serve repeated a delivered batch —
                                # the watermark skip should have prevented
                                # it at the source; drop it here so the
                                # consumer still sees it exactly once.
                                self._recovery_inc("duplicates_dropped")
                            else:
                                self._recv_watermarks[piece] = ordinal + 1
                        if duplicate:
                            CLIENT_DEDUP_DROPPED.labels(
                                "hedge" if piece in hedges
                                else "takeover").inc()
                            continue
                    elif sequencer is not None:
                        raise ServiceError(
                            "ordered delivery needs the tagged stream "
                            f"protocol, but worker {stream.worker_id} "
                            "sent an untagged batch (its reader pool has "
                            "no per-piece completion attribution — use "
                            "reader_pool_type='thread')")
                    # Sampled on dequeue: what a scraper sees is the depth
                    # the consumer actually experienced.
                    CLIENT_READY_QUEUE_DEPTH.set(ready.qsize())
                    if sequencer is not None:
                        released = sequencer.push(
                            piece, (ordinal, batch, stream, bid,
                                    t_enqueued))
                        CLIENT_WATERMARK_LAG.set(sequencer.lag)
                        yield from book.emit(released)
                    else:
                        book.account_yielded(piece, ordinal,
                                             stream.worker_id, bid)
                        collector = tracing.COLLECTOR
                        if collector.enabled:
                            collector.record_span(
                                "client.queue", t_enqueued,
                                time.perf_counter(), bid=bid)
                        yield batch
                elif kind == "piece_done":
                    piece = int(item)
                    stream = streams.get(sid)
                    if stream is None:
                        continue
                    if piece in hedge_won:
                        # The slow primary's late marker for a piece its
                        # hedge already completed — dedup the completion
                        # like the watermark dedups its batches.
                        hedge_won.discard(piece)
                        continue
                    pair = hedges.get(piece)
                    if pair is not None:
                        settle_hedge(piece, pair, sid)
                    if sequencer is not None:
                        released = sequencer.finish_piece(
                            piece, stream.worker_id)
                        CLIENT_WATERMARK_LAG.set(sequencer.lag)
                        yield from book.emit(released)
                    else:
                        book.complete_piece(piece, stream.worker_id)
                elif kind == "piece_failed":
                    piece, failure = item
                    stream = streams.get(sid)
                    if stream is None:
                        continue
                    if sid in hedge_sids:
                        # A hedge is advisory: its failure never
                        # quarantines (the primary still owns the piece) —
                        # drop it and let the race re-open.
                        self._log.warning(
                            "hedge for piece %d failed on worker %s (%s) "
                            "— primary continues", piece, stream.worker_id,
                            failure)
                        drop_hedge(sid, "lost")
                        continue
                    if self._on_piece_error != "quarantine":
                        raise ServiceError(
                            f"worker {stream.worker_id} failed piece "
                            f"{piece}: {failure} (on_piece_error='fail' — "
                            f"run with 'quarantine' to skip poison pieces "
                            f"instead)")
                    # Quarantine: record + report, then COMPLETE the piece
                    # with zero rows so the epoch (and ordered mode's
                    # sequencer) drains past it — every healthy piece
                    # still delivers exactly-once.
                    self._note_quarantined(piece, stream.worker_id,
                                           failure, epoch)
                    if sequencer is not None:
                        released = sequencer.finish_piece(
                            piece, stream.worker_id)
                        CLIENT_WATERMARK_LAG.set(sequencer.lag)
                        yield from book.emit(released)
                    else:
                        book.complete_piece(piece, stream.worker_id)
                elif kind == "end":
                    stream = streams.pop(sid)
                    with self._lock:
                        # Tagged streams completed their pieces one by one
                        # via piece_done; anything still pending here is a
                        # legacy untagged stream (or a lost marker) and
                        # completes at stream granularity, exactly like
                        # the pre-watermark drain. NOT in ordered mode:
                        # there the markers are parked in the sequencer
                        # (a fast stream's end outruns its pieces' turns)
                        # and complete when released — completing them
                        # here would stamp a production count that
                        # predates their own batches, which a v2 snapshot
                        # reads as "already delivered" (sample loss on
                        # resume).
                        pending = ([] if sequencer is not None
                                   else [p for p in stream.pieces
                                         if p not in self._completed])
                        if pending:
                            self._completed.update(pending)
                            self._events.append(
                                (self._production_count, epoch,
                                 sorted(pending)))
                            self._note_pieces_locked(stream.worker_id,
                                                     len(pending))
                    active.discard(sid)
                    hedge_sids.discard(sid)
                elif kind == "error":
                    if sid is not None and sid in hedge_sids:
                        # A protocol-level hedge failure is still just a
                        # lost hedge — the primary path is intact.
                        self._log.warning(
                            "hedge stream errored (%s) — primary "
                            "continues", item)
                        drop_hedge(sid, "lost", closed=True)
                        continue
                    raise item
                elif kind == "recovered":
                    recovering -= 1
                    for new_stream in item:
                        new_sid = next(sid_counter)
                        active.add(new_sid)
                        launch(new_sid, new_stream)
                    if recovering == 0 and fence_deferred:
                        fence_deferred = False
                        resync(active)
                elif kind == "fence":
                    # Defer while a takeover is in flight: the recovery
                    # thread is about to hand back streams planned under an
                    # epoch the resync supersedes — reconcile once, after.
                    if recovering:
                        fence_deferred = True
                    else:
                        resync(active)
                else:  # "broken" — recover concurrently, keep draining
                    if sid in hedge_sids:
                        # A broken hedge never enters recovery: the
                        # primary still owns the piece; feed the peer's
                        # breaker and let the race re-open.
                        broken_hedge = streams.get(sid)
                        if broken_hedge is not None:
                            self._note_stream_failure(
                                broken_hedge.worker_id)
                        drop_hedge(sid, "lost", closed=True)
                        continue
                    stream = streams.pop(sid)
                    active.discard(sid)
                    recovering += 1
                    threading.Thread(
                        target=recover, args=(stream,), daemon=True,
                        name=f"service-recover-{stream.worker_id}").start()
            if sequencer is not None:
                # Defensive: every piece_done should have cleared the
                # sequencer by now; flush anything a lost marker stranded
                # so the epoch never ends with batches held back.
                yield from book.emit(sequencer.drain())
        finally:
            stop.set()
            CLIENT_WATERMARK_LAG.set(0)
            # Closing the sockets unblocks readers parked in recv; the stop
            # flag unblocks readers (and recovery threads) parked on a full
            # queue. A recovery thread still mid-dial is a daemon bounded
            # by its retry budget; streams it creates after this point are
            # closed by its stop-guarded post.
            for stream in streams.values():
                stream.close()
            with self._lock:
                self._ready_queue = None
                self._fence_pending = False
            for reader in readers:
                reader.join(timeout=5)

    def _note_stream_recv(self, worker_id, stall_s, got_batch):
        """Reader-thread callback: receive-stall seconds (time blocked
        waiting on the worker) and one more batch held client-side."""
        CLIENT_RECV_STALL.labels(worker_id).inc(stall_s)
        with self._lock:
            counters = self._per_worker.setdefault(
                worker_id, {"batches": 0, "stall_s": 0.0, "inflight": 0})
            counters["stall_s"] += stall_s
            if got_batch:
                counters["inflight"] += 1

    def _roll_epoch_locked(self, epoch):
        """Per-epoch delivery state reset at an epoch boundary (callers
        hold ``_lock``): completion and watermarks start clean (a resumed
        first epoch's carry-over is over), the new epoch start is
        recorded, and per-batch snapshot events from epochs a
        ``state_dict`` can no longer target are pruned —
        ``_batch_events`` holds one tuple per tagged batch, so without
        pruning a ``num_epochs=None`` run grows it forever. The
        just-finished epoch is retained because a consumer's
        ``yielded_batches`` cursor may lag production by its (bounded)
        prefetch depth; lagging a FULL epoch behind is not a supported
        snapshot position. ``_events`` (one tuple per piece per epoch,
        inspected by diagnostics and tests as completion history) is two
        orders of magnitude smaller and stays unpruned."""
        self._completed = set()
        self._recv_watermarks = {}
        self._resume_watermarks = {}
        self._epoch = epoch
        self._epoch_starts.append(
            (self._production_count, epoch, set(), {}))
        keep_from = epoch - 1
        self._batch_events = [e for e in self._batch_events
                              if e[1] >= keep_from]

    def _note_consumed_locked(self, worker_id):
        """One batch consumed (and its credit acked) — callers hold _lock."""
        CLIENT_BATCHES.labels(worker_id).inc()
        counters = self._per_worker.setdefault(
            worker_id, {"batches": 0, "stall_s": 0.0, "inflight": 0})
        counters["batches"] += 1
        counters["inflight"] = max(0, counters["inflight"] - 1)

    def _note_pieces_locked(self, worker_id, n):
        """``n`` more pieces fully served by this worker — the per-worker
        piece counts the skew/steal benches report. Callers hold _lock."""
        counters = self._per_worker.setdefault(
            worker_id, {"batches": 0, "stall_s": 0.0, "inflight": 0})
        counters["pieces"] = counters.get("pieces", 0) + n

    # -- dynamic mode ------------------------------------------------------

    def _fetch_dynamic_plan(self, epoch):
        """This epoch's initial per-worker piece deques (pieces stamped
        with their ownership generation); syncs the fencing bookkeeping —
        the plan is the freshest state there is."""
        reply = self._dispatcher_request({
            "type": "dynamic_plan", "client_id": self.client_id,
            "client_index": self.client_index,
            "num_clients": self.num_clients, "epoch": epoch})
        with self._lock:
            self._synced_fencing_epoch = int(reply.get("fencing_epoch", 0))
            self._fence_pending = False
        return reply

    def _iter_dynamic(self, info):
        num_epochs = info["num_epochs"]
        epoch = self._epoch
        heartbeat_stop = threading.Event()
        heartbeat = None
        if self._heartbeat_interval_s is not None:
            heartbeat = threading.Thread(
                target=self._heartbeat_loop, args=(heartbeat_stop,),
                daemon=True, name=f"service-heartbeat-{self.client_id}")
            heartbeat.start()
        first = True
        try:
            while num_epochs is None or epoch < num_epochs:
                plan = self._fetch_dynamic_plan(epoch)
                if not plan["assignments"] and num_epochs is None:
                    self._log.warning(
                        "empty dynamic shard and num_epochs is None — "
                        "ending the stream",
                        client_index=self.client_index,
                        num_clients=self.num_clients)
                    return
                with self._lock:
                    self._recv_watermarks = (
                        dict(self._resume_watermarks) if first else {})
                first = False
                yield from self._drain_dynamic(plan, epoch)
                epoch += 1
                with self._lock:
                    self._roll_epoch_locked(epoch)
        finally:
            heartbeat_stop.set()
            if heartbeat is not None:
                heartbeat.join(timeout=5)

    def _drain_dynamic(self, plan, epoch):
        """The dynamic-mode drain: persistent per-worker streams fed from
        dispatcher-owned deques, rebalanced mid-epoch by work stealing.

        Exactly-once across a steal is enforced client-side by the
        **revoke-then-extend handshake**: a steal delta is applied by
        asking the donor's engine to revoke the piece first; only the
        subset the worker ACKS as revoked (meaning zero batches of it were
        or ever will be sent by that engine) is granted to the receiving
        worker's stream — the rest is reported back as ``failed_steals``
        so the dispatcher reverts ownership. ``(piece, generation)`` tags
        on every batch are the safety net on top: a batch whose generation
        does not match the client's current grant is dropped, not yielded.

        Delivery bookkeeping matches static mode (production-order FIFO
        through one ready-queue; ``piece_done`` dequeues strictly after
        the piece's batches), so ``state_dict`` resume works per piece —
        finer grained than static's per-stream completion.

        Exactly-once now also covers the TAKEOVER path: every grant (the
        initial plan, steals, dead-worker reassignments, deferred grants)
        carries the piece's delivery watermark as its ``start``, so the
        receiving engine resumes the piece where delivery stopped, and a
        sub-watermark ordinal arriving anyway is dropped (counted in
        ``duplicates_dropped``). ``sequencer`` re-orders yields into the
        canonical seed-tree order (ordered mode)."""
        with self._lock:
            skip = set(self._completed)
            marks = dict(self._recv_watermarks)
        piece_state = {}   # piece -> {"wid", "gen", "done", "received"}
        outstanding = {}   # wid -> set of not-done pieces granted to it
        addresses = {wid: tuple(addr)
                     for wid, addr in plan["workers"].items()}
        initial_grants = {}
        for wid, pairs in plan["assignments"].items():
            outstanding.setdefault(wid, set())
            for entry in pairs:
                piece, gen = int(entry[0]), int(entry[1])
                done = piece in skip
                piece_state[piece] = {"wid": wid, "gen": gen,
                                      "done": done, "received": False}
                if not done:
                    outstanding[wid].add(piece)
                    initial_grants.setdefault(wid, []).append(
                        (piece, gen, marks.get(piece, 0)))
        remaining = sum(len(ps) for ps in outstanding.values())
        if remaining == 0:
            return
        sequencer = (_OrderedSequencer(piece_order(
            self._shuffle_seed, epoch,
            [p for p, st in piece_state.items() if not st["done"]]))
            if self._ordered else None)
        depth = (self._ready_queue_depth
                 if self._ready_queue_depth is not None
                 else self._derived_ready_depth(len(initial_grants)))
        ready = queue.Queue(maxsize=depth)
        stop = threading.Event()
        sync_stop = threading.Event()
        sync_poke = threading.Event()
        readers = []
        streams = {}          # sid -> _DynamicStream
        sid_by_wid = {}       # wid -> live sid
        recovering = set()    # wids mid-takeover (grants deferred)
        deferred_grants = {}  # wid -> [(piece, gen)] awaiting recovery
        pending_steals = {}   # req -> {"wid": donor, "moves": [...]}
        failed_steals = []    # [[piece, kept_wid, kept_gen]] for next sync
        rows_by_wid = {}      # consumed-row totals (sync-loop rates)
        sid_counter = itertools.count()
        req_counter = itertools.count()
        with self._lock:
            self._ready_queue = ready
            self._live_stream_count = max(1, len(initial_grants))

        def launch(wid, pairs):
            sid = next(sid_counter)
            stream = _DynamicStream(
                wid, addresses[wid], pairs, epoch, self._connect_timeout,
                credits=self._effective_credits(),
                shuffle_seed=self._shuffle_seed,
                transform_placement=self._iter_transform_placement,
                job_id=self.job_id,
                recv_timeout=self._stream_recv_timeout_s,
                packing=self._iter_packing_dict(),
                **self._iter_rewrite_kwargs())
            streams[sid] = stream
            sid_by_wid[wid] = sid
            with self._lock:
                # Mid-epoch joiners/takeovers grow the fleet: keep the
                # live count honest (set_credits re-derives from it).
                self._live_stream_count = max(1, len(streams))
            reader = _DynamicStreamReader(sid, stream, ready, stop,
                                          self._note_stream_recv)
            readers.append(reader)
            reader.start()
            return sid

        def post(event):
            while not stop.is_set():
                try:
                    ready.put(event, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def note_failed_steal(piece, failed_gen):
            """Report a steal that could not be applied. ``failed_gen`` is
            the generation the dispatcher stamped on the failed steal: the
            revert is only valid against exactly that assignment — the
            dispatcher ignores the report if a newer grant (takeover,
            re-plan) has since moved the piece (the report may be retried
            across a sync failure and arrive arbitrarily late)."""
            st = piece_state[piece]
            with self._lock:
                failed_steals.append(
                    [piece, st["wid"], st["gen"], failed_gen])
                self._recovery_inc("steals_failed")

        def grant(wid, pairs):
            """Hand ``(piece, gen, start)`` grants to a worker's live
            stream (or open one) — ``start`` is the piece's delivery
            watermark at grant time, so the engine never repeats what the
            consumer already has."""
            if wid in recovering:
                deferred_grants.setdefault(wid, []).extend(pairs)
                return
            sid = sid_by_wid.get(wid)
            if sid is not None and sid in streams:
                streams[sid].extend(pairs)
            elif wid in addresses:
                launch(wid, pairs)
            else:  # no address for this worker: give the pieces back
                for piece, gen, _start in pairs:
                    note_failed_steal(piece, gen)

        def apply_deltas(reply):
            with self._lock:
                self._synced_fencing_epoch = max(
                    self._synced_fencing_epoch,
                    int(reply.get("fencing_epoch", 0)))
                self._fence_pending = False
            for wid, addr in (reply.get("workers") or {}).items():
                addresses[wid] = tuple(addr)
            by_donor = {}
            for steal in reply.get("steals", []):
                piece = int(steal["piece"])
                gen = int(steal["generation"])
                from_wid, to_wid = steal["from"], steal["to"]
                st = piece_state.get(piece)
                if st is None or st["done"]:
                    continue  # reported done at the next sync anyway
                if st["wid"] == to_wid and st["gen"] == gen:
                    continue  # already applied
                if st["wid"] != from_wid or from_wid in recovering \
                        or sid_by_wid.get(from_wid) not in streams:
                    # The donor moved/broke since the dispatcher planned —
                    # report where the piece actually is.
                    note_failed_steal(piece, gen)
                    continue
                by_donor.setdefault(from_wid, []).append(
                    (piece, to_wid, gen))
            for donor, moves in by_donor.items():
                req = next(req_counter)
                pending_steals[req] = {"wid": donor, "moves": moves}
                streams[sid_by_wid[donor]].revoke(
                    [piece for piece, _, _ in moves], req)

        def on_revoked(sid, item):
            req, revoked_pieces = item
            entry = pending_steals.pop(req, None)
            if entry is None:
                return
            revoked_pieces = set(int(p) for p in revoked_pieces)
            regroup = {}
            for piece, to_wid, gen in entry["moves"]:
                st = piece_state.get(piece)
                if st is None or st["done"]:
                    continue
                if piece in revoked_pieces:
                    with self._lock:
                        outstanding.get(st["wid"], set()).discard(piece)
                        st["wid"], st["gen"] = to_wid, gen
                        outstanding.setdefault(to_wid, set()).add(piece)
                        self._recovery_inc("steals_applied")
                        start = self._recv_watermarks.get(piece, 0)
                    regroup.setdefault(to_wid, []).append(
                        (piece, gen, start))
                else:
                    # The donor had already sent (or is sending) it: the
                    # steal loses, the piece stays where it is.
                    note_failed_steal(piece, gen)
            for to_wid, pairs in regroup.items():
                grant(to_wid, pairs)

        def fail_pending_steals_via(wid):
            """A donor broke mid-handshake: its un-acked steals fail (the
            pieces ride the takeover path with everything else)."""
            for req in [r for r, entry in pending_steals.items()
                        if entry["wid"] == wid]:
                for piece, _to, gen in pending_steals.pop(req)["moves"]:
                    st = piece_state.get(piece)
                    if st is not None and not st["done"]:
                        note_failed_steal(piece, gen)

        def recover(wid, sid):
            """Retry-then-takeover off the consumer thread (same shape as
            static's recovery). Pieces reconnect AT their watermarks —
            the retry, like every other re-serve, is idempotent."""
            with self._lock:
                pairs = sorted(
                    (piece, piece_state[piece]["gen"],
                     self._recv_watermarks.get(piece, 0))
                    for piece in outstanding.get(wid, set()))
            if not pairs:
                post(("dgone", sid, wid))
                return
            try:
                def attempt():
                    fresh = _DynamicStream(
                        wid, addresses[wid], pairs, epoch,
                        self._connect_timeout,
                        credits=self._effective_credits(),
                        shuffle_seed=self._shuffle_seed,
                        transform_placement=self._iter_transform_placement,
                        job_id=self.job_id,
                        recv_timeout=self._stream_recv_timeout_s,
                        packing=self._iter_packing_dict(),
                        **self._iter_rewrite_kwargs())
                    try:
                        fresh._ensure_conn()  # dial + stream request
                    except BaseException:
                        fresh.close()
                        raise
                    return fresh
                try:
                    fresh = retry_with_backoff(
                        attempt, retries=self._max_retries,
                        base_delay=self._backoff_base,
                        max_delay=self._backoff_max,
                        retry_on=(OSError, ProtocolError),
                        no_retry_on=(ServiceError,),
                        description=f"reconnect to worker {wid}")
                except (OSError, ProtocolError):
                    fresh = None
                if fresh is not None:
                    if not post(("drecovered", sid, (wid, fresh))):
                        fresh.close()
                    return
                with self._lock:
                    token = self._synced_fencing_epoch
                reply = self._dispatcher_request({
                    "type": "report_failure", "client_id": self.client_id,
                    "worker_id": wid,
                    "pieces": [t[0] for t in pairs],
                    "fencing_epoch": token})
                if reply.get("type") == "stale_fencing":
                    with self._lock:
                        self._recovery_inc("stale_fencing_retries")
                    reply = self._dispatcher_request({
                        "type": "report_failure",
                        "client_id": self.client_id, "worker_id": wid,
                        "pieces": [t[0] for t in pairs],
                        "fencing_epoch": int(reply["fencing_epoch"])})
                post(("dtakeover", sid, (wid, reply)))
            except BaseException as exc:
                post(("error", None, exc))

        def sync_loop():
            last_t = time.monotonic()
            last_rows = {}
            rate_ema = {}
            while not sync_stop.is_set():
                sync_poke.wait(self._dynamic_sync_interval_s)
                sync_poke.clear()
                if sync_stop.is_set():
                    return
                now = time.monotonic()
                dt = max(1e-6, now - last_t)
                with self._lock:
                    done = sorted(p for p, st in piece_state.items()
                                  if st["done"])
                    owned = {wid: sorted(ps)
                             for wid, ps in outstanding.items()}
                    stealable = {
                        wid: [p for p in ps
                              if not piece_state[p]["received"]]
                        for wid, ps in outstanding.items()}
                    rows_now = dict(rows_by_wid)
                    failed = list(failed_steals)
                    del failed_steals[:]
                # EMA-smoothed delivery rates: one sync window is shorter
                # than a skewed worker's batch period, so instantaneous
                # deltas flap between 0 and bursts — the planner would
                # misread a mid-epoch worker as dead (and vice versa).
                # A worker that has NEVER delivered stays at exactly 0,
                # which the planner treats as "no rate yet".
                for wid in owned:
                    inst = (rows_now.get(wid, 0)
                            - last_rows.get(wid, 0)) / dt
                    prev = rate_ema.get(wid)
                    rate_ema[wid] = (inst if prev is None
                                     else 0.5 * prev + 0.5 * inst)
                rates = {wid: rate_ema.get(wid, 0.0) for wid in owned}
                last_t, last_rows = now, rows_now
                try:
                    reply = self._dispatcher_request({
                        "type": "dynamic_sync",
                        "client_id": self.client_id, "epoch": epoch,
                        "done": done, "owned": owned,
                        "stealable": stealable, "rates": rates,
                        "failed_steals": failed}, retries=0)
                except (ServiceError, OSError):
                    with self._lock:
                        failed_steals.extend(failed)  # re-report next tick
                        self._recovery_inc("heartbeat_failures")
                    continue
                if reply.get("type") == "unknown_plan":
                    post(("dreplan", None, None))
                elif reply.get("type") == "deltas":
                    post(("deltas", None, reply))

        book = _DeliveryBook(self, epoch)

        sync_thread = threading.Thread(
            target=sync_loop, daemon=True,
            name=f"service-dynsync-{self.client_id}")
        try:
            for wid, pairs in initial_grants.items():
                launch(wid, pairs)
            sync_thread.start()
            while remaining > 0:
                kind, sid, item = ready.get()
                if kind == "dbatch":
                    piece, gen, ordinal, payload, bid, t_enqueued = item
                    stream = streams.get(sid)
                    if stream is None:
                        continue  # stream was torn down: stale event
                    # Ack BEFORE yielding, like static: the worker refills
                    # its window while the trainer computes.
                    stream.add_credit(1)
                    st = piece_state.get(piece)
                    if st is None or st["done"] or st["gen"] != gen:
                        # Stale generation (a superseded grant): the dedup
                        # that makes a stolen piece count exactly once.
                        # Deliberately does NOT advance the watermark —
                        # the current owner re-serves this ordinal under
                        # its own generation.
                        with self._lock:
                            self._recovery_inc("dedup_dropped")
                        CLIENT_DEDUP_DROPPED.labels("steal").inc()
                        continue
                    if ordinal is not None:
                        with self._lock:
                            duplicate = (
                                ordinal
                                < self._recv_watermarks.get(piece, 0))
                            if duplicate:
                                self._recovery_inc("duplicates_dropped")
                            else:
                                self._recv_watermarks[piece] = ordinal + 1
                        if duplicate:
                            CLIENT_DEDUP_DROPPED.labels("takeover").inc()
                            continue
                    elif sequencer is not None:
                        raise ServiceError(
                            "ordered delivery needs ordinal-tagged "
                            f"batches, but worker {stream.worker_id} "
                            "sent one untagged")
                    st["received"] = True
                    n = (len(next(iter(payload.values())))
                         if payload else 0)
                    with self._lock:
                        # Rates credit the DELIVERING worker at receipt —
                        # the steal planner balances worker throughput,
                        # not the consumer's (possibly re-ordered) yields.
                        rows_by_wid[stream.worker_id] = (
                            rows_by_wid.get(stream.worker_id, 0) + n)
                    CLIENT_READY_QUEUE_DEPTH.set(ready.qsize())
                    if sequencer is not None:
                        released = sequencer.push(
                            piece, (ordinal, payload, stream,
                                    bid, t_enqueued))
                        CLIENT_WATERMARK_LAG.set(sequencer.lag)
                        yield from book.emit(released)
                    else:
                        book.account_yielded(piece, ordinal,
                                             stream.worker_id, bid)
                        collector = tracing.COLLECTOR
                        if collector.enabled:
                            collector.record_span(
                                "client.queue", t_enqueued,
                                time.perf_counter(), bid=bid)
                        yield payload
                elif kind == "piece_done":
                    piece, gen, _rows = item
                    st = piece_state.get(piece)
                    if st is None or st["done"] or st["gen"] != gen:
                        continue
                    with self._lock:
                        st["done"] = True
                        outstanding.get(st["wid"], set()).discard(piece)
                        drained = not outstanding.get(st["wid"])
                        others_backlogged = any(
                            len(ps) > 1 for w, ps in outstanding.items()
                            if w != st["wid"])
                    if sequencer is not None:
                        released = sequencer.finish_piece(piece, st["wid"])
                        CLIENT_WATERMARK_LAG.set(sequencer.lag)
                        yield from book.emit(released)
                    else:
                        book.complete_piece(piece, st["wid"])
                    remaining -= 1
                    if remaining and drained and others_backlogged:
                        # This worker's deque just ran dry while a peer
                        # still holds backlog: rebalance NOW instead of on
                        # the next interval tick.
                        sync_poke.set()
                elif kind == "piece_failed":
                    piece, gen, failure = item
                    st = piece_state.get(piece)
                    if st is None or st["done"] or st["gen"] != gen:
                        continue  # a superseded grant's quarantine: stale
                    stream = streams.get(sid)
                    wid = stream.worker_id if stream is not None else None
                    if self._on_piece_error != "quarantine":
                        raise ServiceError(
                            f"worker {wid} failed piece {piece}: {failure} "
                            f"(on_piece_error='fail' — run with "
                            f"'quarantine' to skip poison pieces instead)")
                    self._note_quarantined(piece, wid, failure, epoch)
                    with self._lock:
                        st["done"] = True
                        outstanding.get(st["wid"], set()).discard(piece)
                    if sequencer is not None:
                        released = sequencer.finish_piece(piece, wid)
                        CLIENT_WATERMARK_LAG.set(sequencer.lag)
                        yield from book.emit(released)
                    else:
                        book.complete_piece(piece, wid)
                    remaining -= 1
                elif kind == "revoked":
                    on_revoked(sid, item)
                elif kind == "deltas":
                    apply_deltas(item)
                elif kind == "fence":
                    # Dispatcher state moved (restart, eviction): the sync
                    # loop's absolute-state report IS the reconciliation.
                    sync_poke.set()
                elif kind == "dreplan":
                    # Dispatcher lost the plan (restart without journal):
                    # re-seed it; live streams keep flowing and the next
                    # syncs reconcile ownership by corrective steals.
                    try:
                        self._fetch_dynamic_plan(epoch)
                        with self._lock:
                            self._recovery_inc("resyncs")
                    except (ServiceError, OSError):
                        with self._lock:
                            self._recovery_inc("resync_failures")
                elif kind == "error":
                    raise item
                elif kind == "drecovered":
                    wid, fresh = item
                    recovering.discard(wid)
                    old_sid = sid_by_wid.get(wid)
                    if old_sid is not None:
                        streams.pop(old_sid, None)
                    new_sid = next(sid_counter)
                    streams[new_sid] = fresh
                    sid_by_wid[wid] = new_sid
                    reader = _DynamicStreamReader(
                        new_sid, fresh, ready, stop,
                        self._note_stream_recv)
                    readers.append(reader)
                    reader.start()
                    pairs = deferred_grants.pop(wid, None)
                    if pairs:
                        fresh.extend(pairs)
                elif kind == "dtakeover":
                    wid, reply = item
                    recovering.discard(wid)
                    if sid_by_wid.get(wid) == sid:
                        sid_by_wid.pop(wid, None)
                    streams.pop(sid, None)
                    with self._lock:
                        self._recovery_inc("takeovers")
                        self._synced_fencing_epoch = max(
                            self._synced_fencing_epoch,
                            int(reply.get("fencing_epoch", 0)))
                    for wid2, addr in (reply.get("workers") or {}).items():
                        addresses[wid2] = tuple(addr)
                    for piece, gen, _start in deferred_grants.pop(wid, []):
                        note_failed_steal(piece, gen)
                    for wid2, pairs in reply.get("assignments",
                                                 {}).items():
                        pairs = [(int(t[0]), int(t[1])) for t in pairs]
                        fresh_pairs = []
                        with self._lock:
                            for piece, gen in pairs:
                                st = piece_state.get(piece)
                                if st is None or st["done"]:
                                    continue
                                outstanding.get(st["wid"],
                                                set()).discard(piece)
                                st["wid"], st["gen"] = wid2, gen
                                outstanding.setdefault(wid2,
                                                       set()).add(piece)
                                fresh_pairs.append(
                                    (piece, gen,
                                     self._recv_watermarks.get(piece, 0)))
                        if fresh_pairs:
                            grant(wid2, fresh_pairs)
                elif kind == "dgone":
                    wid = item
                    recovering.discard(wid)
                    if sid_by_wid.get(wid) == sid:
                        sid_by_wid.pop(wid, None)
                    streams.pop(sid, None)
                    # Steals granted while recovery was in flight: the
                    # ownership maps already point at this worker, so
                    # dropping them would orphan the pieces (no corrective
                    # delta ever fires — dispatcher and client agree).
                    # Re-grant now that the wid is out of `recovering`:
                    # grant() opens a fresh stream, or fails the steals
                    # back to the dispatcher if the address is unknown.
                    deferred = deferred_grants.pop(wid, [])
                    if deferred:
                        with self._lock:
                            live = [
                                (piece, gen,
                                 self._recv_watermarks.get(piece, 0))
                                for piece, gen, _start in deferred
                                if (st := piece_state.get(piece))
                                is not None and not st["done"]
                                and st["wid"] == wid]
                        if live:
                            grant(wid, live)
                elif kind == "end":
                    # Unexpected end (we have not sent finish): treat like
                    # a broken stream if the worker still owes pieces.
                    stream = streams.pop(sid, None)
                    if stream is None:
                        continue
                    wid = stream.worker_id
                    if sid_by_wid.get(wid) == sid:
                        sid_by_wid.pop(wid, None)
                    if outstanding.get(wid):
                        fail_pending_steals_via(wid)
                        recovering.add(wid)
                        threading.Thread(
                            target=recover, args=(wid, sid), daemon=True,
                            name=f"service-dynrecover-{wid}").start()
                elif kind == "broken":
                    stream = streams.pop(sid, None)
                    if stream is None:
                        continue
                    wid = stream.worker_id
                    if sid_by_wid.get(wid) == sid:
                        sid_by_wid.pop(wid, None)
                    stream.close()
                    fail_pending_steals_via(wid)
                    recovering.add(wid)
                    threading.Thread(
                        target=recover, args=(wid, sid), daemon=True,
                        name=f"service-dynrecover-{wid}").start()
            if sequencer is not None:
                # Defensive: every piece_done cleared the sequencer by
                # now; flush anything a lost marker stranded.
                yield from book.emit(sequencer.drain())
            # Epoch complete: close the piece queues so engines drain and
            # streams end cleanly, then report the final state once so the
            # dispatcher's books close too (best-effort).
            sync_stop.set()
            sync_poke.set()
            for stream in streams.values():
                stream.finish()
            deadline = time.monotonic() + 5.0
            waiting = set(streams)
            while waiting and time.monotonic() < deadline:
                try:
                    kind, sid, item = ready.get(timeout=0.2)
                except queue.Empty:
                    continue
                if kind in ("end", "broken") and sid in waiting:
                    waiting.discard(sid)
            try:
                self._dispatcher_request({
                    "type": "dynamic_sync", "client_id": self.client_id,
                    "epoch": epoch,
                    "done": sorted(p for p, st in piece_state.items()
                                   if st["done"]),
                    "owned": {}, "stealable": {}, "rates": {},
                    "failed_steals": []}, retries=0)
            except (ServiceError, OSError):
                pass  # the next epoch's plan supersedes this state anyway
        finally:
            stop.set()
            sync_stop.set()
            sync_poke.set()
            CLIENT_WATERMARK_LAG.set(0)
            for stream in streams.values():
                stream.close()
            with self._lock:
                self._ready_queue = None
                self._fence_pending = False
            if sync_thread.is_alive():
                sync_thread.join(timeout=5)
            for reader in readers:
                reader.join(timeout=5)

    # -- liveness / fencing -------------------------------------------------

    def _heartbeat_loop(self, stop_event):
        """Poll ``client_heartbeat`` while a static drain is live. The
        reply carries the dispatcher's fencing epoch and recovery
        counters; an epoch past this drain's sync point (restart,
        eviction) — or the dispatcher no longer knowing this client
        (restart without a journal) — posts one ``fence`` event into the
        drain. A dispatcher outage is a counted, retried tick, never an
        error: the data plane keeps flowing without the control plane."""
        while not stop_event.wait(self._heartbeat_interval_s):
            with self._lock:
                # Delivery watermarks ride every heartbeat: the dispatcher
                # journals them through the WAL, so `status` (and a
                # post-restart dispatcher) knows how far each piece got —
                # the observability half of exactly-once recovery. The
                # client's own copy stays authoritative for re-grants (it
                # is never behind). Mid-flight pieces only: a completed
                # piece's watermark is never used for a re-grant
                # (_pending_and_starts filters on completion), and
                # shipping the whole map would grow the heartbeat — and
                # the dispatcher's piece-granularity WAL appends of it —
                # to O(pieces) by late epoch (O(pieces^2) journal bytes).
                marks = {str(p): n
                         for p, n in self._recv_watermarks.items()
                         if n and p not in self._completed}
                epoch_now = self._epoch
                # Overload signal feed: ready-queue fullness (0..1) —
                # one half of the dispatcher's brownout signals (the
                # consumer not keeping up with the fleet).
                ready = self._ready_queue
                saturation = (round(ready.qsize() / ready.maxsize, 4)
                              if ready is not None and ready.maxsize > 0
                              else 0.0)
            try:
                # retries=0 → one dial, so [t0, t1] brackets one round
                # trip: the NTP-style clock sample (offset = dispatcher
                # clock − RTT midpoint) that aligns this client's spans
                # in the merged fleet trace.
                t0 = time.perf_counter()
                reply = self._dispatcher_request(
                    {"type": "client_heartbeat", "client_id": self.client_id,
                     "epoch": epoch_now, "watermarks": marks,
                     "ready_saturation": saturation},
                    retries=0)
                t1 = time.perf_counter()
            except (ServiceError, OSError):
                with self._lock:
                    self._recovery_inc("heartbeat_failures")
                continue
            remote_us = reply.get("dispatcher_time_us")
            if remote_us is not None:
                self._clock.add(
                    tracing.COLLECTOR.ts_us((t0 + t1) / 2.0),
                    float(remote_us), (t1 - t0) * 1e6)
            self._sync_trace_arming(bool(reply.get("trace")))
            fencing = int(reply.get("fencing_epoch", 0))
            with self._lock:
                self._recovery["dispatcher"] = dict(
                    reply.get("recovery") or {})
                stale = (fencing > self._synced_fencing_epoch
                         or not reply.get("known", True))
            if stale:
                self._post_fence(fencing)
        if self._trace_armed_remote:
            # Drain teardown while the fleet is still armed: ship the
            # ring one final time (spans recorded since the last tick
            # would otherwise vanish with this thread), then balance the
            # beacon's acquire.
            self._trace_armed_remote = False
            self._push_trace_ring()
            tracing.COLLECTOR.release()

    def _sync_trace_arming(self, armed):
        """Follow the dispatcher's heartbeat-borne tracing beacon (the
        client half of the worker's ``_sync_trace_arming``): arm the
        local collector when the fleet arms, push the accumulated ring
        each armed tick, release on disarm."""
        if armed and not self._trace_armed_remote:
            self._trace_armed_remote = True
            tracing.COLLECTOR.acquire()
            FLIGHT.note("client.trace_armed")
            self._log.info("fleet tracing armed by dispatcher beacon")
        elif not armed and self._trace_armed_remote:
            self._trace_armed_remote = False
            tracing.COLLECTOR.release()
            self._log.info("fleet tracing disarmed")
            return
        if self._trace_armed_remote:
            self._push_trace_ring()

    def _push_trace_ring(self):
        """Ship-and-clear the local span ring to the dispatcher with the
        current clock offset. Best-effort: a failed push loses that
        tick's spans; heartbeat cadence bounds the exposure."""
        events, dropped = tracing.COLLECTOR.ship()
        if not events and not dropped:
            return
        try:
            self._dispatcher_request(
                {"type": "trace_push", "peer": self.client_id,
                 "events": events, "dropped": dropped,
                 "offset_us": self._clock.offset_us(),
                 "min_rtt_us": self._clock.min_rtt_us()},
                retries=0)
        except (ServiceError, OSError):
            pass  # best-effort: the next tick ships the new ring

    def _post_fence(self, fencing_epoch):
        """Hand the drain a ``fence`` event (dedup'd: one outstanding at a
        time; dropped when no drain is live — the next epoch's assignment
        fetch syncs anyway, and the next heartbeat re-detects)."""
        with self._lock:
            ready = self._ready_queue
            if ready is None or self._fence_pending:
                return
            self._fence_pending = True
        for _ in range(20):  # bounded: never wedge the heartbeat thread
            try:
                ready.put(("fence", None, fencing_epoch), timeout=0.1)
                return
            except queue.Full:
                with self._lock:
                    if self._ready_queue is not ready:
                        break  # drain torn down while we waited
        with self._lock:
            self._fence_pending = False  # next heartbeat re-detects

    def _pending_and_starts(self, pieces):
        """The not-yet-completed subset of ``pieces`` and their delivery
        watermarks — what every re-serve (same-worker retry, takeover,
        resync relaunch) re-grants, so nothing completed is re-read and
        nothing delivered is repeated."""
        with self._lock:
            pending = [p for p in pieces if p not in self._completed]
            starts = {p: self._recv_watermarks.get(p, 0) for p in pending}
        return pending, starts

    def _retry_stream(self, stream):
        """Reconnect to the same worker and resume its pending pieces at
        their watermarks (exactly-once; an untagged legacy worker replays
        from the piece start and the drain's dedup cannot help it — that
        path stays at-least-once). ``None`` when the worker stays
        unreachable — or when its circuit breaker is open (consecutive
        failures already proved it degraded: fail FAST into the takeover
        path instead of burning the backoff budget against it again)."""
        from petastorm_tpu import failpoints

        stream.close()
        pending, starts = self._pending_and_starts(stream.pieces)
        if not pending:
            # Everything this stream owed was already delivered and
            # completed (its break raced the tail piece_done): nothing to
            # re-serve — hand back an immediately-ended stream so the
            # drain just closes the sid's bookkeeping.
            return _EndedStream(stream)
        # The break that brought us here is one failure against the peer;
        # the trip edge (threshold consecutive breaks) reports the worker
        # for dispatcher-side routing exclusion.
        breaker = self._note_stream_failure(stream.worker_id)
        if not breaker.allow(time.monotonic()):
            self._log.warning(
                "circuit breaker %s for worker %s — skipping reconnect, "
                "taking the takeover path", breaker.state,
                stream.worker_id)
            return None

        def attempt():
            fp = failpoints.ACTIVE
            if fp is not None:
                # Injected reconnect failure: feeds this peer's breaker
                # exactly like a real mid-dial reset.
                fp.fire("breaker-trip")
            fresh = _WorkerStream(
                stream.worker_id, stream.address, pending, stream.epoch,
                self._connect_timeout,
                credits=self._effective_credits(), tagged=True,
                starts=starts, shuffle_seed=self._shuffle_seed,
                transform_placement=self._iter_transform_placement,
                job_id=self.job_id,
                recv_timeout=self._stream_recv_timeout_s,
                packing=self._iter_packing_dict(),
                **self._iter_rewrite_kwargs())
            try:
                event = fresh.next_event()  # forces connect + first reply
            except BaseException:
                # The dial succeeded but the request/first-reply failed
                # (peer died mid-handshake, injected reset): close the
                # half-open socket before the retry dials a new one.
                fresh.close()
                raise
            return fresh, event

        try:
            fresh, event = retry_with_backoff(
                attempt, retries=self._max_retries,
                base_delay=self._backoff_base, max_delay=self._backoff_max,
                # ProtocolError = desynced peer: same broken-connection
                # class the established-stream readers already recover.
                retry_on=(OSError, ProtocolError),
                no_retry_on=(ServiceError,),
                # Per-peer retry budget: reconnect attempts against a
                # degraded worker spend tokens its successes refill —
                # a bounded retry rate, never a storm.
                budget=self._budget(stream.worker_id),
                description=f"reconnect to worker {stream.worker_id}")
        except (OSError, ProtocolError):
            self._note_stream_failure(stream.worker_id)
            return None
        self._note_stream_success(stream.worker_id)
        # The first event was consumed by the probe; hand it back by
        # buffering it on the stream object.
        if event[0] == "end":
            # The restarted stream ended immediately; _drain_streams's
            # end-of-stream branch records the completion bookkeeping.
            return _EndedStream(fresh)
        return _BufferedStream(fresh, event)

    def _reassign(self, stream):
        """Report ``stream``'s worker dead; return fresh streams for its
        pieces on the surviving workers the dispatcher names.

        The report carries this client's synced fencing epoch: a
        ``stale_fencing`` reply means the plan moved while this client
        wasn't looking (dispatcher restart, an eviction it hasn't synced)
        — instead of acting on the superseded takeover, re-fetch the
        assignment under the current epoch and route the broken pieces
        per the fresh plan (never double-delivering a piece another
        mapping now owns, never skipping one).

        Survivors re-serve each granted piece AT its watermark: zero
        duplicates on the takeover path, not just zero loss."""
        pending, starts = self._pending_and_starts(stream.pieces)
        self._log.warning(
            "worker unreachable after %d retries; requesting "
            "re-assignment of %d pieces", self._max_retries + 1,
            len(pending), worker_id=stream.worker_id)
        with self._lock:
            token = self._synced_fencing_epoch
        reply = self._dispatcher_request({
            "type": "report_failure", "client_id": self.client_id,
            "worker_id": stream.worker_id, "pieces": pending,
            "fencing_epoch": token})
        if reply.get("type") == "stale_fencing":
            with self._lock:
                self._recovery_inc("stale_fencing_retries")
            # Raw request on purpose: this path only reroutes the BROKEN
            # pieces. Syncing the drain's fencing epoch here would cancel
            # the heartbeat-triggered resync that other live streams
            # (moved by the same bump, e.g. a hung worker's eviction)
            # still depend on.
            fresh = self._request_assignment(stream.epoch)
            broken = set(pending)
            reply = {
                "assignments": {
                    wid: [p for p in pieces if p in broken]
                    for wid, pieces in fresh["assignments"].items()},
                "workers": fresh["workers"],
            }
            reply["assignments"] = {wid: ps for wid, ps
                                    in reply["assignments"].items() if ps}
        # NB: a successful report deliberately does NOT fast-forward the
        # synced epoch — the reply's epoch may also cover an unrelated
        # eviction this client hasn't reconciled; the next heartbeat then
        # triggers a (no-op, if so) resync rather than silently skipping it.
        with self._lock:
            self._recovery_inc("takeovers")
        return [
            # piece_order re-asserts the canonical serve order that keeps
            # ordered mode's reorder buffer small (the dispatcher already
            # replies in it; this keeps the property local).
            _WorkerStream(wid, reply["workers"][wid],
                          piece_order(self._shuffle_seed, stream.epoch,
                                      pieces),
                          stream.epoch,
                          self._connect_timeout,
                          credits=self._effective_credits(), tagged=True,
                          starts={p: starts.get(p, 0) for p in pieces},
                          shuffle_seed=self._shuffle_seed,
                          transform_placement=self._iter_transform_placement,
                          job_id=self.job_id,
                          recv_timeout=self._stream_recv_timeout_s,
                          packing=self._iter_packing_dict(),
                          **self._iter_rewrite_kwargs())
            for wid, pieces in reply["assignments"].items()
        ]

    # -- fcfs mode ---------------------------------------------------------

    def _list_workers(self):
        reply = self._dispatcher_request({"type": "list_workers"})
        return {wid: tuple(addr) for wid, addr in reply["workers"].items()}

    def _iter_fcfs(self, info):
        workers = {wid: tuple(addr) for wid, addr in info["workers"].items()}
        rr_counter = 0
        while True:
            reply = self._dispatcher_request(
                {"type": "next_split", "client_id": self.client_id})
            if reply["type"] == "end_of_stream":
                return
            piece, epoch = reply["piece"], reply["epoch"]
            refreshed = False
            while True:  # serve attempts for this split
                if not workers:
                    # The local fleet snapshot drained: replacements may
                    # have registered since (elastic fleets) — ask the
                    # dispatcher before giving up. Reported-dead workers
                    # are not re-listed, so this terminates.
                    workers = self._list_workers()
                    refreshed = True
                    if not workers:
                        raise ServiceError(
                            f"no worker could serve split {piece} — no "
                            f"live workers registered")
                # Round-robin start offset spreads pieces over the fleet.
                candidates = sorted(workers)
                start = rr_counter % len(candidates)
                rr_counter += 1
                served = False
                for wid in candidates[start:] + candidates[:start]:
                    served = yield from self._serve_split_with_retries(
                        wid, workers[wid], piece, epoch)
                    if served:
                        break
                    # Worker stayed unreachable through the backoff
                    # budget: flag it dead and try the piece elsewhere
                    # (restarting the piece from its beginning:
                    # at-least-once).
                    workers.pop(wid, None)
                    try:
                        self._dispatcher_request({
                            "type": "report_failure",
                            "client_id": self.client_id,
                            "worker_id": wid, "pieces": []})
                    except ServiceError:
                        pass  # surfaces via the refresh path above
                if served:
                    break
                if refreshed and not workers:
                    raise ServiceError(
                        f"no worker could serve split {piece} — all "
                        f"workers unreachable")

    def _serve_split_with_retries(self, wid, address, piece, epoch):
        """Yield one split's batches from one worker, retrying transient
        connection failures on :func:`~petastorm_tpu.utils.backoff_delays`
        — the same schedule ``retry_with_backoff`` sleeps on, used directly
        because a generator must keep yielding between attempts — gated by
        the worker's shared :class:`RetryBudget` (the same bucket the
        control RPCs spend from: an exhausted budget stops retrying even
        when attempts remain, so a degraded worker sees a bounded retry
        RATE, not a storm). Returns ``True`` when the split was fully
        served, ``False`` when the worker stayed unreachable through the
        retry budget. A retry restarts the piece from its beginning
        (at-least-once — batches already yielded from the broken attempt
        arrive again)."""
        from petastorm_tpu.utils import backoff_delays

        budget = self._budget(wid)
        delays = backoff_delays(self._max_retries, self._backoff_base,
                                self._backoff_max)
        for attempt in range(self._max_retries + 1):
            # Sequential consumption: receive == consume, so each batch is
            # acked on arrival (auto_replenish) and the credit window still
            # bounds the worker's read-ahead past this client.
            stream = _WorkerStream(
                wid, address, [piece], epoch, self._connect_timeout,
                credits=self._effective_credits(), auto_replenish=True,
                shuffle_seed=self._shuffle_seed,
                transform_placement=self._iter_transform_placement,
                job_id=self.job_id,
                recv_timeout=self._stream_recv_timeout_s,
                transport=self._transport)
            try:
                yield from self._drain_one(stream)
                budget.record_success()
                RESILIENCE_RETRY_BUDGET.labels(wid).set(budget.balance)
                return True
            except (ConnectionClosedError, ConnectionError, OSError,
                    ProtocolError) as exc:
                if attempt == self._max_retries:
                    return False
                if not budget.try_spend():
                    RESILIENCE_RETRY_BUDGET.labels(wid).set(budget.balance)
                    self._log.warning(
                        "split %s failed (%s); retry budget for the "
                        "worker is exhausted — giving up early "
                        "(%d attempts remained)", piece, exc,
                        self._max_retries - attempt, worker_id=wid)
                    return False
                RESILIENCE_RETRY_BUDGET.labels(wid).set(budget.balance)
                sleep_s = next(delays)
                self._log.warning(
                    "split %s failed (%s); retry %d/%d in %.2fs", piece,
                    exc, attempt + 1, self._max_retries, sleep_s,
                    worker_id=wid)
                self._retry_sleep(sleep_s)
        return False

    def _drain_one(self, stream):
        collector = tracing.COLLECTOR
        try:
            while True:
                t0 = time.perf_counter()
                batch = stream.next_batch()
                t1 = time.perf_counter()
                self._note_stream_recv(stream.worker_id, t1 - t0,
                                       batch is not None)
                if batch is None:
                    return
                if collector.enabled:
                    collector.record_span("client.recv", t0, t1,
                                          bid=stream.last_bid)
                with self._lock:
                    self._note_consumed_locked(stream.worker_id)
                self.last_bid = stream.last_bid
                yield batch
        finally:
            stream.close()

    # -- checkpoint / diagnostics -----------------------------------------

    def state_dict(self, yielded_batches=None):
        """Resumable position: the epoch in progress, the pieces fully
        yielded, and — on the tagged exactly-once protocol — per-piece
        batch **watermarks** for pieces mid-delivery, so a resume
        continues each piece at the next batch instead of re-reading it
        (exactly-once resume; untagged legacy streams still fall back to
        piece-set granularity, at-least-once). With ``ordered=True`` and
        the same dispatcher ``shuffle_seed``, the resumed stream is
        bit-identical to the uninterrupted run from the snapshot batch
        onward — the seed-tree cursor is implied by (epoch, completed,
        watermarks). Static and dynamic modes (dynamic tracks completion
        per piece — a steal mid-epoch changes who served a piece, never
        whether it counts as completed); fcfs has no resumable position.

        ``yielded_batches``: for a consumer that prefetches past this
        source — the number of batches it has actually surfaced.
        Completion AND watermarks are computed as of that batch (batches
        still sitting in a prefetch queue stay un-snapshotted, so they
        are re-served on resume: never sample loss, and never a duplicate
        either, because the re-serve starts exactly at the watermark).
        ``JaxDataLoader.state_dict()`` passes this for you; a consumer
        iterating the source directly has no prefetch gap and the default
        (everything produced) is exact.
        """
        with self._lock:
            if self._mode == "fcfs":
                raise ValueError(
                    "state_dict is not supported in fcfs mode: splits are "
                    "handed out first-come-first-served, so a client has no "
                    "deterministic resumable position — use static sharding "
                    "for resumable training")
            if yielded_batches is not None \
                    and self._filter_dropped_batches:
                # The trainer-local filter dropped whole batches (every
                # row failed the predicate), so the consumer's yielded
                # count no longer indexes this source's production order —
                # prefetch-lag-exact positioning would silently land on
                # the wrong batch. Refuse loudly; the hoisted placement
                # keeps positioning exact (workers collate survivors, so
                # nothing is dropped client-side).
                raise ValueError(
                    "state_dict(yielded_batches=...) is not supported "
                    "while the trainer-local row filter has dropped "
                    "whole batches this iteration — hoist the filter "
                    "(filter_placement='worker') for prefetch-exact "
                    "checkpoints of filtered pipelines")
            count = (self._production_count if yielded_batches is None
                     else min(int(yielded_batches), self._production_count))
            epoch, base, base_marks = (self._epoch_starts[0][1],
                                       self._epoch_starts[0][2],
                                       self._epoch_starts[0][3])
            for start_count, start_epoch, start_base, start_marks \
                    in self._epoch_starts:
                if start_count <= count:
                    epoch, base, base_marks = (start_epoch, start_base,
                                               start_marks)
            completed = set(base)
            completed.update(
                piece
                for event_count, event_epoch, pieces in self._events
                if event_epoch == epoch and event_count <= count
                for piece in pieces)
            watermarks = dict(base_marks)
            for event_count, event_epoch, piece, ordinal \
                    in self._batch_events:
                if event_epoch == epoch and event_count <= count \
                        and ordinal is not None:
                    if ordinal + 1 > watermarks.get(piece, 0):
                        watermarks[piece] = ordinal + 1
            return {
                "version": 2,
                "mode": ("dynamic" if self._mode == "dynamic"
                         else "static"),
                "client_index": self.client_index,
                "num_clients": self.num_clients,
                "epoch": epoch,
                "completed_pieces": sorted(completed),
                # Mid-piece positions (completed pieces need none); JSON
                # object keys are strings for wire/file round-trips.
                "watermarks": {str(p): n for p, n in sorted(
                    watermarks.items()) if n and p not in completed},
                # The order the snapshot was taken under: a resume under a
                # different dispatcher seed stays exactly-once but warns
                # that bit-identical order is off the table.
                "shuffle_seed": self._shuffle_seed,
                "ordered": self._ordered,
                # Worker-placement packing in force: watermarks/ordinals
                # above number PACKED batches, so a resume must re-arm
                # the identical spec (validated at restore).
                "packing": self._iter_packing_dict(),
                # Hoisted row filter in force: a worker-placed predicate
                # means pieces collate only SURVIVORS, so the watermarks
                # above number filtered batches — the same vocabulary
                # hazard as packing, validated the same way at restore.
                # None = no hoisted filter (client-placed filtering does
                # not change what the worker ships), matching legacy
                # snapshots that lack the key.
                "filter": self._hoisted_filter_signature(),
            }

    def _hoisted_filter_signature(self, constructed=False):
        """The watermark-vocabulary ingredient of the hoisted row filter:
        its canonical wire form when worker-placed, else ``None``.
        ``constructed=True`` reads the constructor state (resume
        validation — the next iteration's topology); default reads the
        iteration in force (snapshot time)."""
        if constructed:
            predicate, hoisted = self._predicate, \
                self._filter_placement == "worker"
        else:
            predicate, hoisted = self._iter_predicate, self._iter_hoisted
        return (predicate.to_wire()
                if hoisted and predicate is not None else None)

    def _validate_resume_state(self, state):
        if state.get("version") not in (1, 2):
            raise ValueError(
                f"Unsupported resume_state version {state.get('version')!r}")
        # static and dynamic snapshots are interchangeable: both are
        # (epoch, completed piece set) over the same piece universe.
        if state.get("mode") not in ("static", "dynamic"):
            raise ValueError(
                "resume_state requires static or dynamic sharding mode")
        for key in ("client_index", "num_clients"):
            if state.get(key) != getattr(self, key):
                raise ValueError(
                    f"resume_state mismatch on {key!r}: checkpoint has "
                    f"{state.get(key)!r}, this client has "
                    f"{getattr(self, key)!r}")
        saved_packing = state.get("packing")
        current_packing = (self._packing.to_dict()
                           if self._packing is not None else None)
        if saved_packing != current_packing:
            raise ValueError(
                f"resume_state packing mismatch: checkpoint watermarks "
                f"number batches under {saved_packing!r}, this source "
                f"runs {current_packing!r} — resuming would re-grant at "
                f"positions in a different batch vocabulary")
        saved_filter = state.get("filter")
        current_filter = self._hoisted_filter_signature(constructed=True)
        if saved_filter != current_filter:
            raise ValueError(
                f"resume_state hoisted-filter mismatch: checkpoint "
                f"watermarks number batches under worker-placed filter "
                f"{saved_filter!r}, this source runs {current_filter!r} "
                f"— a hoisted predicate changes each piece's batch "
                f"vocabulary (pieces collate only survivors), so "
                f"resuming would re-grant at wrong positions")

    @property
    def diagnostics(self):
        """Client-side delivery counters for the multiplexed drain:

        - ``ready_queue_depth`` / ``ready_queue_capacity``: batches waiting
          in the shared ready-queue right now (0/0 outside a drain);
        - ``credits_window``: the per-worker flow-control window in force;
        - ``per_worker``: per-worker ``batches`` consumed, ``stall_s``
          (seconds its reader thread spent blocked waiting on the worker —
          a skewed worker shows up here, not in delivery latency), and
          ``credits_outstanding`` (batches received but not yet
          consumed-and-acked);
        - ``epoch_starts``: ``[produced_batch_count, epoch]`` boundaries in
          production order (per-epoch throughput attribution);
        - ``resilience``: overload-robustness state — per-peer circuit
          breaker and retry-budget snapshots, whether hedging is armed,
          the fitted hedge threshold, and the hedge race tallies
          (``launched``/``won``/``lost``);
        - ``recovery``: control-plane recovery events this client observed
          — ``resyncs`` (fence-triggered assignment refreshes),
          ``streams_retired``, ``takeovers``, ``stale_fencing_retries``,
          ``heartbeat_failures``, ``dedup_dropped`` (stale-generation
          batches of superseded dynamic grants), ``duplicates_dropped``
          (sub-watermark batches a re-serve repeated — the exactly-once
          safety net, 0 when the worker-side watermark skip worked), the
          last ``fencing_epoch`` seen, and ``dispatcher`` (the
          dispatcher's own recovery counters — journal replays, evictions,
          fencing bumps — from the last heartbeat).

        ``JaxDataLoader`` snapshots this into its own ``diagnostics`` under
        ``"source"`` when the source is plugged in.
        """
        with self._lock:
            ready = self._ready_queue
            return {
                "ready_queue_depth": ready.qsize() if ready is not None
                else 0,
                "ready_queue_capacity": ready.maxsize if ready is not None
                else 0,
                "credits_window": self._credits,
                # Placement of the batch-transform stage in force for the
                # current iteration (None = no transform armed).
                "transform_placement": self._iter_transform_placement,
                # Graph-rewrite topology in force this iteration
                # (docs/guides/pipeline.md#graph-rewrites).
                "rewrites": {
                    "filter_placement": self._iter_filter_placement,
                    "stage_fusion": ("fused" if self._iter_fused
                                     else "off"),
                    "cache_placement": (self._iter_cache_stage
                                        or "post-transform"),
                    "reader_family": self._iter_reader_family,
                    "filter_dropped_batches":
                        self._filter_dropped_batches,
                },
                # Epoch boundaries in production order: the n-th entry says
                # "epoch `epoch` began at produced-batch `count`" — a
                # consumer correlating its own per-batch timeline (the
                # `service` scenario's per-epoch rows/s breakdown) reads
                # the boundary without private state.
                "epoch_starts": [[count, epoch] for count, epoch, *_
                                 in self._epoch_starts],
                "per_worker": {
                    wid: {"batches": counters["batches"],
                          "stall_s": round(counters["stall_s"], 3),
                          "credits_outstanding": counters["inflight"],
                          "pieces": counters.get("pieces", 0)}
                    for wid, counters in self._per_worker.items()},
                # Poison pieces recorded under on_piece_error="quarantine"
                # (piece, reporting worker, error, epoch) — the trainer-
                # side account of what the epoch was delivered WITHOUT.
                "quarantined_pieces": [dict(entry)
                                       for entry in self._quarantined],
                # Overload-robustness state (service/resilience.py): the
                # per-peer breaker/budget snapshots and the hedged
                # re-serve race tallies.
                "resilience": {
                    "hedging": self._hedging,
                    "hedge_counts": dict(self._hedge_counts),
                    "hedge_threshold_s": self._gap_tracker.threshold_s(),
                    "breakers": {wid: breaker.snapshot()
                                 for wid, breaker
                                 in self._breakers.items()},
                    "retry_budgets": {wid: budget.snapshot()
                                      for wid, budget
                                      in self._budgets.items()},
                },
                "recovery": {
                    key: (dict(value) if isinstance(value, dict)
                          else value)
                    for key, value in self._recovery.items()},
            }

    def remote_diagnostics(self):
        """Per-worker ``Reader.diagnostics`` snapshots — remote input stalls
        become visible trainer-side (see docs/guides/diagnostics.md)."""
        info = self._dispatcher_request({"type": "list_workers"})
        out = {}
        for wid, addr in info["workers"].items():
            try:
                with FramedConnection.connect(
                        tuple(addr), timeout=self._connect_timeout) as conn:
                    _, payload = conn.request({"type": "diagnostics"})
                out[wid] = payload
            except (ConnectionClosedError, OSError) as exc:
                out[wid] = {"error": f"unreachable: {exc}"}
        return out

    def dispatcher_status(self):
        """The dispatcher's control-plane snapshot (workers, clients,
        split-queue depth)."""
        return self._dispatcher_request({"type": "status"})


class _BufferedStream:
    """A stream whose first event was already pulled by the reconnect
    probe — hands it back first, then proxies, mirroring the tag
    attributes the drain's reader thread snapshots per event."""

    def __init__(self, stream, first_event):
        self._stream = stream
        self._first = first_event
        self.worker_id = stream.worker_id
        self.address = stream.address
        self.pieces = stream.pieces
        self.epoch = stream.epoch
        self.credits = stream.credits
        # Tags of the buffered probe event.
        self.last_bid = stream.last_bid
        self.last_piece = stream.last_piece
        self.last_ordinal = stream.last_ordinal

    def next_event(self):
        if self._first is not None:
            event, self._first = self._first, None
            return event
        event = self._stream.next_event()
        self.last_bid = self._stream.last_bid
        self.last_piece = self._stream.last_piece
        self.last_ordinal = self._stream.last_ordinal
        return event

    def next_batch(self):
        while True:
            kind, payload = self.next_event()
            if kind == "batch":
                return payload
            if kind == "end":
                return None

    def add_credit(self, n=1):
        self._stream.add_credit(n)

    def close(self):
        self._stream.close()


class _EndedStream:
    """A stream that already ended cleanly during the reconnect probe (or
    had nothing pending left to re-serve)."""

    def __init__(self, stream):
        self.worker_id = stream.worker_id
        self.address = stream.address
        self.pieces = stream.pieces
        self.epoch = stream.epoch
        self.credits = stream.credits
        self.last_bid = None
        self.last_piece = None
        self.last_ordinal = None

    def next_event(self):
        return ("end", None)

    def next_batch(self):
        return None

    def add_credit(self, n=1):
        pass

    def close(self):
        pass
